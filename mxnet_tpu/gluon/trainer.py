"""gluon.Trainer (ref: python/mxnet/gluon/trainer.py).

Applies an Optimizer to a set of Parameters. The reference's per-GPU grad
arrays + kvstore allreduce collapse here: each Parameter holds ONE buffer
(possibly sharded over the mesh, in which case the backward pass already
psum-reduced the gradient over ICI). The kvstore path is kept with the same
`update_on_kvstore` decision logic (ref: trainer.py — _init_kvstore,
model.py — _create_kvstore) so KVStore-driven training (including
dist types and server-side optimizers) behaves like the reference.
"""
from __future__ import annotations

import time

import jax

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .. import optimizer as opt
from .. import kvstore as kvs
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class _FusedUpdate:
    """ONE donated XLA launch for Trainer.step's optimizer phase.

    The reference's canonical Gluon loop (record/backward/trainer.step,
    ref: gluon/trainer.py — step) issues one engine op per parameter; its
    async engine hides the launches. On the axon tunnel each launch costs
    ~3.4 ms (PERF.md §1.2), so a 200-parameter model would spend ~0.7 s in
    Trainer.step alone. This fuses every eligible parameter's update into
    one jitted program with weights and optimizer state DONATED — the
    static_alloc analog ShardedTrainStep already uses (parallel/sharded.py)
    brought to the canonical path.

    Eligible: optimizer class in _SUPPORTED (sgd/nag/adam/adamw/rmsprop/
    adagrad), dense gradients, no multi_precision, and no
    distributed/server-side kvstore. Anything else
    falls back to the eager per-parameter updater (same numerics, more
    launches). Dynamic scalars (scheduler lr, wd, rescale_grad, step t)
    enter as traced 0-d arguments so no step ever retraces; per-parameter
    lr_mult/wd_mult are folded in as static multipliers at build time.
    Optimizer state stays in Updater.states in the eager layout, so
    save_states/load_states round-trip unchanged.
    """

    _SUPPORTED = ("SGD", "NAG", "Adam", "AdamW", "RMSProp", "AdaGrad")

    @staticmethod
    def eligible(trainer):
        from .. import config as _config

        o = trainer._optimizer
        if not _config.get("MXT_FUSED_TRAINER"):
            return False
        if type(o).__name__ not in _FusedUpdate._SUPPORTED or \
                type(o).__module__ != opt.Optimizer.__module__:
            return False
        if getattr(o, "multi_precision", False):
            return False
        if getattr(o, "aggregate_num", 0):
            return False
        if trainer._update_on_kvstore:
            return False
        kv = trainer._kvstore
        embedding_kv = kv is not None and kv.type == "dist_embedding"
        if kv is not None and not embedding_kv and \
                (kv.type.startswith("dist") or
                 trainer._compression_params):
            return False
        if jax.process_count() > 1:
            return False
        for p in trainer._params:
            if p.grad_req == "null":
                continue
            if getattr(p, "_grad_stype", "default") != "default" \
                    and not embedding_kv:
                # sparse grads are only safe to exclude from the fused
                # program when the embedding kvstore owns them (the
                # trainer routes them through _embedding_step); any
                # other config must stay on the eager per-param path
                return False
        return True

    def __init__(self, trainer):
        self._trainer = trainer
        o = trainer._optimizer
        self._opt = o
        # sparse-grad params are kvstore-owned (dist_embedding routes
        # them via _embedding_step); the fused program covers the rest
        self._indices = [i for i, p in enumerate(trainer._params)
                         if p.grad_req != "null"
                         and getattr(p, "_grad_stype",
                                     "default") == "default"]
        self._upds = [self._param_update(o, i) for i in self._indices]
        self._hyper_cache = None  # host floats, cached between steps
        self._jit_guarded = None  # built on first guarded() call
        self._stream = None       # engine.StepStream for deferred flags
        self._t_dev = None        # device-carried step count (guard mode)
        self._mask_dev = None
        upds = self._upds

        def step(ws, gs, ss, t, lr, wd, rescale):
            out_w, out_s = [], []
            for f, w, g, s in zip(upds, ws, gs, ss):
                w2, s2 = f(w, g, s, t, lr, wd, rescale)
                out_w.append(w2)
                out_s.append(s2)
            return tuple(out_w), tuple(out_s)

        # weights + states donated: buffers are reused across steps and the
        # params' NDArray wrappers rebind to the outputs
        self._jit = jax.jit(step, donate_argnums=(0, 2))
        from .. import tuning
        tuning.register_step(self)  # bare tuning.warmup() AOT-compiles us

    @staticmethod
    def _param_update(o, index):
        """Per-parameter pure update (w, g, state_leaves, t, lr, wd,
        rescale) -> (w2, leaves2), numerics identical to the eager
        Optimizer.update path."""
        import jax.numpy as jnp

        from ..ops.registry import get_op

        lr_mult = o.param_dict[index].lr_mult if index in o.param_dict \
            else o.lr_mult.get(index, o.lr_mult.get(
                o.idx2name.get(index), 1.0))
        wd_mult = o.param_dict[index].wd_mult if index in o.param_dict \
            else o.wd_mult.get(index, o.wd_mult.get(
                o.idx2name.get(index), 1.0))
        clip = o.clip_gradient
        name = type(o).__name__
        if name in ("SGD", "NAG"):
            momentum = o.momentum
            if momentum:
                fn = get_op("sgd_mom_update" if name == "SGD"
                            else "nag_mom_update").fn

                def upd(w, g, s, t, lr, wd, rescale):
                    w2, m2 = fn(w, g, s[0], lr=lr * lr_mult,
                                momentum=momentum, wd=wd * wd_mult,
                                rescale_grad=rescale, clip_gradient=clip)
                    return w2, (m2,)
            else:
                fn = get_op("sgd_update").fn

                def upd(w, g, s, t, lr, wd, rescale):
                    return fn(w, g, lr=lr * lr_mult, wd=wd * wd_mult,
                              rescale_grad=rescale, clip_gradient=clip), ()
        elif name == "RMSProp":
            gamma1, gamma2, eps = o.gamma1, o.gamma2, o.epsilon
            clip_w = o.clip_weights
            if o.centered:
                fn = get_op("rmspropalex_update").fn

                def upd(w, g, s, t, lr, wd, rescale):
                    w2, n2, g2, d2 = fn(
                        w, g, s[0], s[1], s[2], lr=lr * lr_mult,
                        gamma1=gamma1, gamma2=gamma2, epsilon=eps,
                        wd=wd * wd_mult, rescale_grad=rescale,
                        clip_gradient=clip, clip_weights=clip_w)
                    return w2, (n2, g2, d2)
            else:
                fn = get_op("rmsprop_update").fn

                def upd(w, g, s, t, lr, wd, rescale):
                    w2, n2 = fn(w, g, s[0], lr=lr * lr_mult,
                                gamma1=gamma1, epsilon=eps,
                                wd=wd * wd_mult, rescale_grad=rescale,
                                clip_gradient=clip, clip_weights=clip_w)
                    return w2, (n2,)
        elif name == "AdaGrad":
            eps = o.float_stable_eps

            def upd(w, g, s, t, lr, wd, rescale):
                # mirror the eager python update exactly (optimizer.py —
                # AdaGrad.update dense branch)
                g = g * rescale
                if clip is not None:
                    g = jnp.clip(g, -clip, clip)
                g = g + (wd * wd_mult) * w
                s2 = s[0] + g * g
                w2 = w - (lr * lr_mult) * g / (jnp.sqrt(s2) + eps)
                return w2.astype(w.dtype), (s2,)
        else:  # Adam / AdamW — bias correction folded into lr, as eager
            beta1, beta2, eps = o.beta1, o.beta2, o.epsilon
            if name == "Adam":
                fn = get_op("adam_update").fn

                def apply(w, g, s, lr_t, wd, rescale):
                    return fn(w, g, s[0], s[1], lr=lr_t, wd=wd,
                              beta1=beta1, beta2=beta2, epsilon=eps,
                              rescale_grad=rescale, clip_gradient=clip)
            else:
                fn = get_op("adamw_update").fn

                def apply(w, g, s, lr_t, wd, rescale):
                    return fn(w, g, s[0], s[1], lr=lr_t, wd=wd, eta=1.0,
                              beta1=beta1, beta2=beta2, epsilon=eps,
                              rescale_grad=rescale, clip_gradient=clip)

            def upd(w, g, s, t, lr, wd, rescale):
                coef1 = 1.0 - jnp.power(beta1, t)
                coef2 = 1.0 - jnp.power(beta2, t)
                lr_t = lr * lr_mult * jnp.sqrt(coef2) / coef1
                w2, m2, v2 = apply(w, g, s, lr_t, wd * wd_mult, rescale)
                return w2, (m2, v2)
        return upd

    @staticmethod
    def _leaves(state):
        if state is None:
            return ()
        if isinstance(state, tuple):
            return state
        return (state,)

    def _prepare(self, updater):
        """Shared per-step invariants: grads/states present, counts even.
        Returns False if the caller must fall back to the eager path."""
        o = self._opt
        params = self._trainer._params
        for i in self._indices:
            p = params[i]
            if p._data is None or getattr(p._data, "_grad", None) is None:
                return False
            if i not in updater.states:
                updater.states[i] = o.create_state_multi_precision(
                    i, p.data())
                updater.states_synced[i] = True
        # the fused program uses ONE step count for every parameter; if a
        # prior eager/kvstore path left counts uneven, stay eager
        counts = {o._index_update_count.get(i, o.begin_num_update)
                  for i in self._indices}
        return len(counts) == 1

    def _host_hypers(self, o):
        """(lr, wd) host floats with the constant-scheduler conversions
        cached between steps (off the dispatch hot path)."""
        cache = self._hyper_cache
        if cache is None or cache[0] != o.lr or cache[1] != o.wd:
            cache = (o.lr, o.wd, float(o.lr), float(o.wd))  # sync-ok: host scalars, cached
            self._hyper_cache = cache
        return cache[2], cache[3]

    def __call__(self, rescale):
        """Run one fused update. Returns False (caller should fall back to
        the eager path) if host-side invariants don't hold this step."""
        tr = self._trainer
        o = self._opt
        updater = tr._updaters[0]
        params = tr._params
        if self._t_dev is not None:
            # a guarded (deferred-flag) run preceded this unguarded step:
            # land its bookkeeping before advancing counts on host
            self.flush_guarded()
        if not self._prepare(updater):
            return False

        # host-side bookkeeping first, mirroring eager order (_update_count
        # then _get_lr): scheduler sees the post-bump num_update
        for i in self._indices:
            o._update_count(i)
        t = o._index_update_count[self._indices[0]] if self._indices else 1
        if o.lr_scheduler is not None:
            lr = float(o.lr_scheduler(o.num_update))  # sync-ok: host scheduler scalar
            wd = float(o.wd)  # sync-ok: host scalar
        else:
            lr, wd = self._host_hypers(o)

        _t0 = time.perf_counter()
        ws = tuple(params[i].data().data for i in self._indices)
        gs = tuple(params[i].grad().data for i in self._indices)
        ss = tuple(tuple(l.data for l in self._leaves(updater.states[i]))
                   for i in self._indices)
        new_w, new_s = self._jit(ws, gs, ss, t, lr, wd, rescale)
        from .. import profiler
        profiler.record_launch()
        for i, w2, s2 in zip(self._indices, new_w, new_s):
            params[i].data()._set_data(w2)
            for leaf, v in zip(self._leaves(updater.states[i]), s2):
                leaf._set_data(v)
        from .. import telemetry
        telemetry.record_phase("dispatch", time.perf_counter() - _t0,
                               stream="trainer_step")
        return True

    # -- deferred non-finite guard (async dispatch) ------------------------
    def _build_guarded(self):
        """The same fused update with the resilience guard compiled IN:
        a lax.cond makes the whole update the identity when any gradient
        is non-finite, the step count rides the program as a device
        scalar, and the flag lands in a carried bitmask consumed by the
        engine's in-flight window — no per-step host read."""
        import jax.numpy as jnp

        upds = self._upds

        def step(ws, gs, ss, t, mask, lr, wd, rescale):
            finite = jnp.bool_(True)
            for g in gs:
                finite = jnp.logical_and(finite, jnp.isfinite(g).all())
            t_upd = t + 1

            def _apply(_):
                out_w, out_s = [], []
                for f, w, g, s in zip(upds, ws, gs, ss):
                    w2, s2 = f(w, g, s, t_upd, lr, wd, rescale)
                    out_w.append(w2)
                    out_s.append(s2)
                return tuple(out_w), tuple(out_s)

            def _skip(_):
                return tuple(ws), tuple(ss)

            new_w, new_s = jax.lax.cond(finite, _apply, _skip, None)
            t_new = t + jnp.where(finite, 1, 0)
            mask_new = (mask << 1) | jnp.where(finite, 0, 1)
            return new_w, new_s, t_new, mask_new

        self._jit_guarded = jax.jit(step, donate_argnums=(0, 2))
        from .. import engine
        self._stream = engine.StepStream(name="trainer_step",
                                         on_flags=self._on_flag)

    def _on_flag(self, finite):
        """Deferred bookkeeping for one retired step, in dispatch order
        (the loss-scale wrapper drives its own scaler — not here)."""
        if finite:
            for i in self._indices:
                self._opt._update_count(i)
        else:
            from .. import resilience
            resilience.record_skipped_step()

    def flush_guarded(self):
        """Land every deferred flag and drop the device step count (the
        next guarded step re-derives it from host counts)."""
        if self._stream is not None and self._stream.pending:
            self._stream.flush()
        self._t_dev = None
        self._mask_dev = None

    @property
    def pending(self):
        return self._stream.pending if self._stream is not None else 0

    def aot_warmup(self):
        """AOT-lower-and-compile the fused optimizer update (and the
        guarded variant when ``MXT_SKIP_NONFINITE`` is on) from the live
        parameter shapes — donation makes execute-to-warm destructive,
        so this never touches a weight. With ``MXT_COMPILE_CACHE_DIR``
        set the compiles land in (or replay from) the persistent cache;
        the first real ``trainer.step`` then performs no hot-path JIT.
        Returns the number of programs compiled, or False when the
        parameters aren't initialized yet."""
        import jax

        from .. import config as _cfg

        tr = self._trainer
        o = self._opt
        updater = tr._updaters[0]
        params = tr._params
        for i in self._indices:
            if params[i]._data is None:
                return False
            if i not in updater.states:
                updater.states[i] = o.create_state_multi_precision(
                    i, params[i].data())
                updater.states_synced[i] = True

        def sds(a):
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        ws = tuple(sds(params[i].data().data) for i in self._indices)
        gs = ws  # gradient avals match the weights
        ss = tuple(tuple(sds(l.data)
                         for l in self._leaves(updater.states[i]))
                   for i in self._indices)
        # scalar args mirror the hot path's aval kinds exactly (python
        # int/float here = weak-typed there) so the persistent-cache key
        # matches the real dispatch
        self._jit.lower(ws, gs, ss, 1, 0.0, 0.0, 1.0).compile()
        count = 1
        if _cfg.get("MXT_SKIP_NONFINITE"):
            import jax.numpy as jnp

            if self._jit_guarded is None:
                self._build_guarded()
            self._jit_guarded.lower(ws, gs, ss, jnp.int32(0),
                                    jnp.uint32(0), 0.0, 0.0, 1.0).compile()
            count += 1
        return count

    def guarded(self, rescale):
        """One fused update with the in-program non-finite guard,
        dispatched asynchronously. Returns False when this step can't run
        guarded-fused (caller falls back to the synchronous check)."""
        o = self._opt
        if o.lr_scheduler is not None:
            # scheduler lr depends on the data-dependent step count — the
            # synchronous guard path keeps exact lr semantics
            return False
        tr = self._trainer
        updater = tr._updaters[0]
        if not self._prepare(updater):
            self.flush_guarded()
            return False
        params = tr._params
        if self._jit_guarded is None:
            self._build_guarded()
        if self._t_dev is None:
            import jax.numpy as jnp

            base = o._index_update_count.get(
                self._indices[0], o.begin_num_update) if self._indices \
                else 0
            self._t_dev = jnp.int32(base)
            self._mask_dev = jnp.uint32(0)
        lr, wd = self._host_hypers(o)
        _t0 = time.perf_counter()
        ws = tuple(params[i].data().data for i in self._indices)
        gs = tuple(params[i].grad().data for i in self._indices)
        ss = tuple(tuple(l.data for l in self._leaves(updater.states[i]))
                   for i in self._indices)
        new_w, new_s, t_new, mask_new = self._jit_guarded(
            ws, gs, ss, self._t_dev, self._mask_dev, lr, wd, rescale)
        from .. import profiler
        profiler.record_launch()
        for i, w2, s2 in zip(self._indices, new_w, new_s):
            params[i].data()._set_data(w2)
            for leaf, v in zip(self._leaves(updater.states[i]), s2):
                leaf._set_data(v)
        self._t_dev, self._mask_dev = t_new, mask_new
        self._stream.push(mask_new, flags=mask_new)
        from .. import telemetry
        telemetry.record_phase("dispatch", time.perf_counter() - _t0,
                               stream="trainer_step")
        return True


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                "got %s." % (type(params),))
        self._params = []
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    "got list of %s." % (type(param),))
            self._param2idx[param.name] = i
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))  # sync-ok: construction-time host scalar
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_params = {
            "kvstore": kvstore, "update_on_kvstore": update_on_kvstore}
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None
        self._params_to_init = []
        self._fused = None  # None = undecided, False = ineligible
        self._reset_kvstore()

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            if optimizer_params and set(optimizer_params) != {"rescale_grad"}:
                raise ValueError(
                    "optimizer_params must be None if optimizer is an "
                    "instance of Optimizer instead of str")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)]

    def _reset_kvstore(self):
        if self._kvstore and self._kvstore.type.startswith("dist"):
            raise RuntimeError(
                "Cannot reset distributed KVStore.")
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None
        self._params_to_init = [p for p in self._params]
        self._fused = None

    def _init_kvstore(self):
        config = self._kvstore_params
        kvstore_arg = config["kvstore"]
        update_on_kvstore = config["update_on_kvstore"]
        has_sparse = any(getattr(p, "_grad_stype", "default") != "default"
                         for p in self._params)
        kvstore = None
        if kvstore_arg:
            if isinstance(kvstore_arg, kvs.KVStore):
                kvstore = kvstore_arg
            elif isinstance(kvstore_arg, str):
                kvstore = kvs.create(kvstore_arg)
            else:
                raise ValueError("kvstore must be a KVStore instance or name")
        elif has_sparse:
            # sparse grads are applied where the weight lives
            kvstore = kvs.create("local")
        if kvstore is not None and kvstore.type == "dist_embedding":
            # hybrid ownership: row_sparse tables update on the sharded
            # embedding fleet (server-side sparse optimizer), dense
            # parameters stay on the local — fused — update path
            if update_on_kvstore is False:
                raise ValueError(
                    "update_on_kvstore=False is not supported with "
                    "kvstore='dist_embedding': sparse tables update on "
                    "the embedding servers by design")
            kvstore.set_optimizer(self._optimizer)
            update_on_kvstore = False
        elif kvstore is not None:
            if has_sparse:
                # ref: trainer.py — sparse gradients force
                # update_on_kvstore=True (row_sparse rows are updated on
                # the store that holds the full weight)
                if update_on_kvstore is False:
                    raise ValueError(
                        "update_on_kvstore=False is not supported with "
                        "sparse gradients (matches reference)")
                update_on_kvstore = True
            if update_on_kvstore is None:
                # reference default: update on kvstore when distributed
                update_on_kvstore = kvstore.type.startswith("dist")
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
                # server-side optimizer owns the state; keep updater list
                # for save_states compatibility
                self._updaters = [kvstore._updater]
        else:
            update_on_kvstore = False
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._kv_initialized = True

    @property
    def _embedding_kv(self):
        return self._kvstore is not None \
            and self._kvstore.type == "dist_embedding"

    def _init_params(self):
        """Lazily register params whose deferred init has completed."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._kvstore is None:
            self._params_to_init = []
            return
        remaining = []
        emb = self._embedding_kv
        for param in self._params_to_init:
            if param._deferred_init is not None or param._data is None:
                remaining.append(param)
            else:
                if emb and getattr(param, "_grad_stype",
                                   "default") != "row_sparse":
                    # dist_embedding registers ONLY the sparse tables;
                    # dense params never ship to the fleet
                    continue
                idx = self._param2idx[param.name]
                self._kvstore.init(idx, param.data())
        self._params_to_init = remaining

    @property
    def learning_rate(self):
        return self._optimizer.lr if self._optimizer.lr_scheduler is None \
            else self._optimizer.lr_scheduler(self._optimizer.num_update)

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def fuse_step(self, net, loss_fn, batch_axis=0, return_outputs=False):
        """Whole-step fusion: forward + backward + optimizer update as ONE
        donated XLA launch (gluon/train_step.py — CachedTrainStep), with
        transparent fallback to the eager record/backward/step loop when
        this trainer's config is ineligible. Returns a callable
        ``step(x, y, batch_size=None) -> loss`` (or ``(loss, outputs)``
        with ``return_outputs=True``)."""
        from .train_step import CachedTrainStep

        return CachedTrainStep(net, loss_fn, self, batch_axis=batch_axis,
                               return_outputs=return_outputs)

    # ------------------------------------------------------------------
    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce + optimizer update, scaled by 1/batch_size
        (ref: trainer.py — step). With ``MXT_SKIP_NONFINITE=1`` a batch
        whose gradients contain NaN/Inf is skipped wholesale — weights,
        optimizer state, and update counts untouched (resilience.py). On
        the fused path the guard compiles INTO the launch and its flag is
        observed deferred through the engine's in-flight window, so no
        per-step host read throttles dispatch; the eager path keeps the
        synchronous check (the skip decision gates the update itself)."""
        rescale_grad = self._scale / batch_size
        self._check_and_rescale_grad(rescale_grad)
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        if self._fused is None:
            self._fused = _FusedUpdate(self) if _FusedUpdate.eligible(self) \
                else False
        from .. import resilience
        emb = self._embedding_kv
        if resilience.skip_nonfinite_enabled():
            # the embedding push is not gated by a deferred flag (rows
            # apply server-side the moment they arrive), so with an
            # embedding kvstore the guard must decide SYNCHRONOUSLY
            # before any row ships
            if not emb and self._fused and self._fused.guarded(
                    rescale_grad):
                return  # guard + update in one launch, flag deferred
            if self._fused:
                self._fused.flush_guarded()
            if self._grads_overflowed():
                resilience.record_skipped_step()
                return
        if emb:
            # sparse tables: gradient rows to the fleet (server-side
            # sparse optimizer), then a row pull of exactly the touched
            # rows back into the dense mirror — through the hot cache,
            # which the push's write-back just refreshed
            self._embedding_step()
        if self._fused and self._fused(rescale_grad):
            return  # one donated launch covered reduce (identity) + update
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def _embedding_step(self):
        """Route every row_sparse parameter through the sharded
        embedding fleet: push gradient rows, pull the updated rows back
        into the parameter's dense buffer (the device-resident working
        set — untouched rows keep their values, the lazy-update
        contract)."""
        kv = self._kvstore
        for i, param in enumerate(self._params):
            if param.grad_req == "null" or \
                    getattr(param, "_grad_stype", "default") != "row_sparse":
                continue
            grad = param.grad()  # RowSparseNDArray at the boundary
            kv.push(i, grad)
            kv.row_sparse_pull(i, out=param.data(), row_ids=grad.indices)

    def _grads_overflowed(self):
        """True if any live gradient is non-finite — one fused device
        check + one host read for the whole set (the LossScaler
        machinery; resilience.all_finite)."""
        from .. import resilience

        grads = [p.grad() for p in self._params
                 if p.grad_req != "null" and p._data is not None
                 and getattr(p._data, "_grad", None) is not None]
        return bool(grads) and not resilience.all_finite(grads)

    def _check_and_rescale_grad(self, scale):
        if self._kv_initialized and \
                (self._update_on_kvstore or self._embedding_kv) and \
                self._optimizer.rescale_grad != scale:
            raise UserWarning(
                "Possible change in the `batch_size` from previous `step` "
                "detected. Optimizer gradient normalizing factor will not "
                "change w.r.t new batch_size when update_on_kvstore=True")
        self._optimizer.rescale_grad = scale

    def allreduce_grads(self):
        """Only reduce gradients, no update (for grad manipulation between
        allreduce and update; ref: trainer.py — allreduce_grads)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        if self._update_on_kvstore:
            raise AssertionError(
                "allreduce_grads() when parameters are updated on kvstore "
                "is not supported. Try setting `update_on_kvstore` to False "
                "when creating trainer.")
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        if self._embedding_kv:
            # sparse params already flowed through _embedding_step;
            # dense grads stay local (single-process data path — the
            # fleet holds tables, not a gradient-reduction plane)
            return
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if self._update_on_kvstore:
                # push grad; server applies the update into the weight,
                # pull brings it back
                self._kvstore.push(i, param.list_grad()[0])
                self._kvstore.pull(i, param.data(), ignore_sparse=False)
            else:
                self._kvstore.push(i, param.list_grad()[0])
                self._kvstore.pull(i, param.list_grad()[0])

    def update(self, batch_size, ignore_stale_grad=False):
        """Only the optimizer update (call allreduce_grads first;
        ref: trainer.py — update)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        assert not (self._kvstore and self._update_on_kvstore), \
            "update() when parameters are updated on kvstore is not " \
            "supported. Try setting `update_on_kvstore` to False when " \
            "creating trainer."
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        if self._update_on_kvstore:
            return  # weights already updated server-side in _allreduce_grads
        updater = self._updaters[0]
        emb = self._embedding_kv
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if emb and getattr(param, "_grad_stype",
                               "default") == "row_sparse":
                continue  # applied server-side by _embedding_step
            if param._data is None:
                if not ignore_stale_grad:
                    raise MXNetError(
                        "parameter %s has not been initialized" % param.name)
                continue
            updater(i, param.grad(), param.data())

    # -- state persistence (ref: trainer.py — save_states/load_states) -----
    def save_states(self, fname):
        """Serialize optimizer state + update counts. Valid at ANY point
        — including before the first ``step()`` (per-parameter state is
        created lazily, so an early save just records the optimizer and
        empty state dicts); failure modes raise a clear MXNetError
        rather than an IndexError/AssertionError."""
        if self._optimizer is None:
            raise MXNetError(
                "Trainer has no optimizer — cannot save states")
        from .. import engine
        engine.wait_all()  # land deferred update counts before serializing
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        if self._update_on_kvstore:
            if self._kvstore is None or self._kvstore._updater is None:
                raise MXNetError(
                    "update_on_kvstore trainer has no server-side "
                    "updater yet — cannot save states")
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
        else:
            if not self._updaters:
                raise MXNetError(
                    "Trainer has no updater — cannot save states")
            with open(fname, "wb") as fout:
                fout.write(self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        from .. import engine
        engine.wait_all()  # drain in-flight steps before swapping state
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        if not self._update_on_kvstore and not self._updaters:
            raise MXNetError(
                "Trainer has no updater — cannot load states")
        # the fused step closes over the optimizer OBJECT (hyper-params,
        # update counts); loading swaps it — rebuild on next step
        self._fused = None
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            self._optimizer = self._kvstore._updater.optimizer
        else:
            with open(fname, "rb") as f:
                states = f.read()
            for updater in self._updaters:
                updater.set_states(states)
                updater.optimizer = self._updaters[0].optimizer
            self._optimizer = self._updaters[0].optimizer
        param_dict = {i: param for i, param in enumerate(self._params)}
        self._optimizer.param_dict = param_dict
