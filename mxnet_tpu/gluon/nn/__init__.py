"""Gluon neural-net layers (ref: python/mxnet/gluon/nn/__init__.py)."""
from ..block import Block, HybridBlock
from .basic_layers import *
from .conv_layers import *
from .activations import *
from .layout import *

from .basic_layers import __all__ as _basic_all
from .conv_layers import __all__ as _conv_all
from .activations import __all__ as _act_all
from .layout import __all__ as _layout_all

__all__ = ["Block", "HybridBlock"] + list(_basic_all) + list(_conv_all) + \
    list(_act_all) + list(_layout_all)
