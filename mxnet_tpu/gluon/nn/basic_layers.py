"""Core Gluon layers (ref: python/mxnet/gluon/nn/basic_layers.py)."""
from __future__ import annotations

import numpy as np

from ... import autograd
from ...base import MXNetError
from ..block import Block, HybridBlock
from .layout import resolve_norm_axis

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
           "SyncBatchNorm",
           "Embedding", "Flatten", "Lambda", "HybridLambda", "Activation",
           "LayerNorm", "InstanceNorm", "GroupNorm"]


class Sequential(Block):
    """Stack of Blocks executed in order (ref: basic_layers.py — Sequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                for layer in layers[key]:
                    net.add(layer)
            return net
        return layers[key]

    def __iter__(self):
        return iter(self._children.values())


class HybridSequential(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                for layer in layers[key]:
                    net.add(layer)
            return net
        return layers[key]

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully connected layer (ref: basic_layers.py — Dense; op:
    src/operator/nn/fully_connected.cc)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._flatten = flatten
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def infer_shape(self, x, *args):
        in_units = int(np.prod(x.shape[1:])) if self._flatten else x.shape[-1]
        self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight=None, bias=None):
        out = F.FullyConnected(
            x, weight, bias, num_hidden=self._units,
            no_bias=bias is None, flatten=self._flatten)
        if self.act is not None:
            out = self.act(out)
        return out


class Activation(HybridBlock):
    def __init__(self, activation, prefix=None, params=None):
        self._act_type = activation  # before super(): _alias() uses it
        super().__init__(prefix=prefix, params=params)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = tuple(axes)

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate, axes=self._axes,
                         train_mode=autograd.is_training())


class BatchNorm(HybridBlock):
    """Batch normalization (ref: basic_layers.py — BatchNorm; op:
    src/operator/nn/batch_norm.cc). Running stats are aux params mutated on
    training forwards, exactly like the reference."""

    def __init__(self, axis=None, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        # axis=None resolves against nn.layout_scope (1, the reference
        # default, unless a channels-last scope is active)
        self._axis = resolve_norm_axis(axis)
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                grad_req="write" if scale else "null")
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
                grad_req="write" if center else "null")
            self.running_mean = self.params.get(
                "running_mean", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False)

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape = (c,)

    def hybrid_forward(self, F, x, gamma=None, beta=None, running_mean=None,
                       running_var=None):
        train = autograd.is_training()
        ret = F.BatchNorm(
            x, gamma, beta, running_mean, running_var,
            eps=self._epsilon, momentum=self._momentum,
            fix_gamma=not self._scale,
            use_global_stats=self._use_global_stats,
            axis=self._axis, train_mode=train)
        if not isinstance(ret, tuple):
            return ret  # symbolic trace: extra outputs are hidden
        out, new_mean, new_var = ret
        if train and not self._use_global_stats:
            with autograd.pause():
                self.running_mean.data()._set_data(new_mean.data)
                self.running_var.data()._set_data(new_var.data)
        return out


class SyncBatchNorm(BatchNorm):
    """Cross-device synchronized BatchNorm
    (ref: python/mxnet/gluon/contrib/nn — SyncBatchNorm over
    src/operator/contrib/sync_batch_norm.cc, which runs an explicit
    all-reduce of per-device sums inside the kernel).

    TPU-native design note: no explicit collective is needed. Inside a
    jitted SPMD step (ShardedTrainStep / pjit) the batch is a GLOBAL
    array sharded over the mesh's data axis, so the ``jnp.mean``/var in
    the BatchNorm kernel are already global reductions — GSPMD inserts
    the cross-device psum automatically, and partitioning stays XLA's
    job. This subclass therefore only exists for API parity: it IS
    synchronized wherever the reference's would be (inside the sharded
    step), and in pure single-device eager mode it degenerates to plain
    BatchNorm exactly like the reference's does in a 1-GPU run.
    ``num_devices``/``ndev`` are accepted and ignored (mesh size rules).
    tests/test_parallel.py pins the global-stats property on an 8-device
    mesh."""

    def __init__(self, in_channels=0, num_devices=None, ndev=None,
                 momentum=0.9, epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", **kwargs):
        del num_devices, ndev
        super().__init__(axis=kwargs.pop("axis", None), momentum=momentum,
                         epsilon=epsilon, center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=(
                             running_variance_initializer),
                         in_channels=in_channels, **kwargs)


class Embedding(HybridBlock):
    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._sparse_grad = sparse_grad
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer,
                grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight=None):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim,
                           sparse_grad=self._sparse_grad)


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.flatten(x)


class Lambda(Block):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as F

            if not hasattr(F, function):
                raise MXNetError("unknown nd function %r" % (function,))
            self._func = getattr(F, function)
            self._name_ = function
        else:
            self._func = function
            self._name_ = getattr(function, "__name__", "lambda")

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            self._func_name = function
            self._func = None
        else:
            self._func = function
            self._func_name = getattr(function, "__name__", "lambda")

    def hybrid_forward(self, F, *args):
        if self._func is not None:
            return self._func(F, *args)
        return getattr(F, self._func_name)(*args)


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                grad_req="write" if scale else "null")
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
                grad_req="write" if center else "null")

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma=None, beta=None):
        return F.LayerNorm(x, gamma, beta, axis=self._axis,
                           eps=self._epsilon)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                grad_req="write" if scale else "null")
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
                grad_req="write" if center else "null")

    def infer_shape(self, x, *args):
        c = x.shape[1]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma=None, beta=None):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class GroupNorm(HybridBlock):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_groups = num_groups
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                grad_req="write" if scale else "null")
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
                grad_req="write" if center else "null")

    def infer_shape(self, x, *args):
        c = x.shape[1]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma=None, beta=None):
        return F.GroupNorm(x, gamma, beta, num_groups=self._num_groups,
                           eps=self._epsilon)
