"""Channels-last layout scope — TPU-native addition (no reference analog).

The reference API is NCHW-first: every conv/pool layer defaults to
``layout="NCHW"`` and BatchNorm to ``axis=1`` (ref:
python/mxnet/gluon/nn/conv_layers.py signatures). On TPU the MXU tiles
best when the channel dimension is minor (channels-last): with NCHW HLO
the compiler has to insert transpose fusions around every conv, which
shows up directly as lost MFU. Rather than threading a ``layout``
argument through every model-zoo constructor, ``layout_scope("NHWC")``
rewrites the *defaults* for layers constructed under it::

    with nn.layout_scope("NHWC"):
        net = model_zoo.get_model("resnet50_v1")   # whole net channels-last
    net.initialize()
    out = net(nhwc_batch)                          # input is (N, H, W, C)

An explicit ``layout=`` / ``axis=`` passed by the caller always wins over
the scope. The scope is captured at *construction* time (layers remember
their layout), so it does not need to be re-entered for forward passes.
Weight layout stays logical OIHW either way, so checkpoints are
layout-portable.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

__all__ = ["layout_scope", "current_layout", "channel_axis",
           "resolve_layout", "resolve_norm_axis"]

_state = threading.local()

# rank-indexed spellings of the two layout families
_CHANNELS_FIRST = {1: "NCW", 2: "NCHW", 3: "NCDHW"}
_CHANNELS_LAST = {1: "NWC", 2: "NHWC", 3: "NDHWC"}


def current_layout():
    """The active scope's layout family ("NCHW" / "NHWC") or None."""
    return getattr(_state, "layout", None)


@contextmanager
def layout_scope(layout):
    """Set the default layout family for layers constructed in the scope."""
    if layout not in ("NCHW", "NHWC"):
        raise ValueError(
            "layout_scope expects the 2-D family name 'NCHW' or 'NHWC'; "
            "got %r" % (layout,))
    prev = current_layout()
    _state.layout = layout
    try:
        yield
    finally:
        _state.layout = prev


def channel_axis():
    """Channel axis implied by the active scope (for concat/split sites):
    1 for channels-first (the default), -1 under a channels-last scope."""
    return -1 if current_layout() == "NHWC" else 1


def resolve_layout(layout, nd):
    """Resolve a layer's layout argument: an explicit value wins; None
    falls back to the scope (or channels-first, matching the reference)."""
    if layout is not None:
        return layout
    family = _CHANNELS_LAST if current_layout() == "NHWC" \
        else _CHANNELS_FIRST
    return family[nd]


def resolve_norm_axis(axis):
    """Resolve BatchNorm's axis argument against the scope."""
    if axis is not None:
        return axis
    return -1 if current_layout() == "NHWC" else 1
