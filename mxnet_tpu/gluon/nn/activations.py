"""Activation blocks (ref: python/mxnet/gluon/nn/activations.py)."""
from __future__ import annotations

from ..block import HybridBlock

__all__ = ["LeakyReLU", "PReLU", "ELU", "SELU", "Swish", "GELU"]


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer=None, in_channels=1, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        from ... import initializer

        with self.name_scope():
            self.alpha = self.params.get(
                "alpha", shape=(in_channels,),
                init=alpha_initializer or initializer.Constant(0.25))

    def hybrid_forward(self, F, x, alpha=None):
        return F.LeakyReLU(x, alpha, act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class Swish(HybridBlock):
    def __init__(self, beta=1.0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)


class GELU(HybridBlock):
    def __init__(self, approximate=False, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._approx = approximate

    def hybrid_forward(self, F, x):
        return F.gelu(x, approximate=self._approx)
