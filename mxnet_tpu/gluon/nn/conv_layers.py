"""Convolution / pooling layers (ref: python/mxnet/gluon/nn/conv_layers.py).

Default layout is NCHW for reference parity; pass layout='NHWC' for the
TPU-preferred layout (the model zoo does this) — XLA then keeps channels in
the minor dimension, which tiles better onto the MXU.
"""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock
from .layout import resolve_layout

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv2DTranspose",
           "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D",
           "GlobalMaxPool1D", "GlobalMaxPool2D", "GlobalMaxPool3D",
           "GlobalAvgPool1D", "GlobalAvgPool2D", "GlobalAvgPool3D"]


def _tup(x, n):
    if isinstance(x, int):
        return (x,) * n
    return tuple(x)


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", op_name="Convolution",
                 adj=None, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        nd_ = len(kernel_size)
        self._channels = channels
        self._in_channels = in_channels
        self._kernel = kernel_size
        self._stride = strides
        self._pad = padding
        self._dilate = dilation
        self._groups = groups
        self._layout = resolve_layout(layout, nd_)
        self._op_name = op_name
        self._adj = adj
        self._nd = nd_
        with self.name_scope():
            if op_name == "Deconvolution":
                wshape = (in_channels, channels // groups) + kernel_size
            else:
                wshape = (channels, in_channels // max(groups, 1)) + kernel_size
            self.weight = self.params.get(
                "weight", shape=wshape, init=weight_initializer,
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(channels,), init=bias_initializer,
                    allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                from .basic_layers import Activation

                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def _channel_axis(self, x):
        return 1 if self._layout.startswith("NC") else x.ndim - 1

    def infer_shape(self, x, *args):
        c = x.shape[self._channel_axis(x)]
        self._in_channels = c
        if self._op_name == "Deconvolution":
            self.weight.shape = (c, self._channels // self._groups) + self._kernel
        else:
            self.weight.shape = (self._channels, c // self._groups) + self._kernel

    def hybrid_forward(self, F, x, weight=None, bias=None):
        kwargs = dict(
            kernel=self._kernel, stride=self._stride, dilate=self._dilate,
            pad=self._pad, num_filter=self._channels, num_group=self._groups,
            no_bias=bias is None, layout=self._layout)
        if self._op_name == "Deconvolution":
            kwargs["adj"] = self._adj or (0,) * self._nd
        out = getattr(F, self._op_name)(x, weight, bias, **kwargs)
        if self.act is not None:
            out = self.act(out)
        return out


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout=None, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tup(kernel_size, 1), _tup(strides, 1),
                         _tup(padding, 1), _tup(dilation, 1), groups, layout,
                         in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout=None, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tup(kernel_size, 2), _tup(strides, 2),
                         _tup(padding, 2), _tup(dilation, 2), groups, layout,
                         in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout=None, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tup(kernel_size, 3), _tup(strides, 3),
                         _tup(padding, 3), _tup(dilation, 3), groups, layout,
                         in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout=None, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tup(kernel_size, 2), _tup(strides, 2),
                         _tup(padding, 2), _tup(dilation, 2), groups, layout,
                         in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="Deconvolution",
                         adj=_tup(output_padding, 2), **kwargs)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, layout, count_include_pad=None, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        if strides is None:
            strides = pool_size
        self._kwargs = dict(
            kernel=pool_size, stride=strides, pad=padding,
            global_pool=global_pool, pool_type=pool_type,
            pooling_convention="full" if ceil_mode else "valid",
            layout=resolve_layout(layout, len(pool_size)))
        if count_include_pad is not None:
            self._kwargs["count_include_pad"] = count_include_pad

    def _alias(self):
        return "pool"

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout=None,
                 ceil_mode=False, **kwargs):
        super().__init__(_tup(pool_size, 1),
                         _tup(strides, 1) if strides is not None else None,
                         _tup(padding, 1), ceil_mode, False, "max", layout,
                         **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout=None, ceil_mode=False, **kwargs):
        super().__init__(_tup(pool_size, 2),
                         _tup(strides, 2) if strides is not None else None,
                         _tup(padding, 2), ceil_mode, False, "max", layout,
                         **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout=None, ceil_mode=False, **kwargs):
        super().__init__(_tup(pool_size, 3),
                         _tup(strides, 3) if strides is not None else None,
                         _tup(padding, 3), ceil_mode, False, "max", layout,
                         **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout=None,
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_tup(pool_size, 1),
                         _tup(strides, 1) if strides is not None else None,
                         _tup(padding, 1), ceil_mode, False, "avg", layout,
                         count_include_pad, **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout=None, ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_tup(pool_size, 2),
                         _tup(strides, 2) if strides is not None else None,
                         _tup(padding, 2), ceil_mode, False, "avg", layout,
                         count_include_pad, **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout=None, ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_tup(pool_size, 3),
                         _tup(strides, 3) if strides is not None else None,
                         _tup(padding, 3), ceil_mode, False, "avg", layout,
                         count_include_pad, **kwargs)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1,), None, (0,), False, True, "max", layout, **kwargs)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1, 1), None, (0, 0), False, True, "max", layout,
                         **kwargs)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), False, True, "max",
                         layout, **kwargs)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1,), None, (0,), False, True, "avg", layout, **kwargs)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1, 1), None, (0, 0), False, True, "avg", layout,
                         **kwargs)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), False, True, "avg",
                         layout, **kwargs)
