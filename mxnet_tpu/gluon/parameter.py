"""Parameter / ParameterDict (ref: python/mxnet/gluon/parameter.py).

A Parameter owns one primary NDArray (data-parallel replication is handled
by the Trainer/KVStore layer over shardings, not by per-ctx copies as in the
reference — on TPU the mesh owns placement). Deferred init mirrors the
reference: unknown dims are 0 until the first forward infers them.

Trace support: while a CachedOp (hybridize) trace is running, ``data()``
returns the traced stand-in installed by the trace scope, so the same layer
code serves eager and compiled paths.
"""
from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np

from ..base import MXNetError, get_dtype
from ..context import current_context
from ..ndarray import ndarray as _nd
from ..ndarray.ndarray import NDArray
from .. import initializer as init_mod

__all__ = ["Parameter", "Constant", "ParameterDict", "DeferredInitializationError",
           "param_trace_scope", "tracing_override"]


class DeferredInitializationError(MXNetError):
    """Raised when a deferred-init parameter's data is requested before the
    first forward (ref: parameter.py — DeferredInitializationError)."""


class _TraceState(threading.local):
    def __init__(self):
        super().__init__()
        self.maps = []


_trace_state = _TraceState()


class param_trace_scope:
    """Installs {Parameter -> NDArray} overrides during a CachedOp trace."""

    def __init__(self, mapping):
        self._mapping = mapping

    def __enter__(self):
        _trace_state.maps.append(self._mapping)
        return self

    def __exit__(self, *args):
        _trace_state.maps.pop()


def tracing_override(param):
    for m in reversed(_trace_state.maps):
        if param in m:
            return m[param]
    return None


class Parameter:
    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None,
                 allow_deferred_init=False, differentiable=True,
                 stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        if isinstance(shape, int):
            shape = (shape,)
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = get_dtype(dtype)
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        self._stype = stype
        self._grad_stype = grad_stype
        self._data = None  # NDArray
        self._deferred_init = None  # (initializer, ctx)

    # ------------------------------------------------------------------
    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        unknown = any(s == 0 or s == -1 for s in self._shape)
        if not unknown and tuple(new_shape) != self._shape:
            raise MXNetError(
                "cannot reset shape of %s from %s to %s"
                % (self.name, self._shape, tuple(new_shape)))
        merged = tuple(
            n if (s in (0, -1)) else s
            for s, n in zip(self._shape, new_shape)
        )
        self._shape = merged

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        if req not in ("write", "add", "null"):
            raise MXNetError("invalid grad_req %r" % (req,))
        if not self._differentiable:
            req = "null"
        self._grad_req = req
        if self._data is not None:
            if req == "null":
                self._data._grad = None
                self._data._ag_node = None
            else:
                self._data.attach_grad(req)

    @property
    def stype(self):
        return self._stype

    def _shape_incomplete(self):
        return self._shape is None or any(s in (0, -1) for s in self._shape)

    # ------------------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        if self._data is not None and not force_reinit:
            return
        if isinstance(ctx, (list, tuple)):
            ctx = ctx[0] if ctx else None
        ctx = ctx or current_context()
        default_init = default_init or init_mod.Uniform(0.07)
        initializer = self.init or init or default_init
        if self._shape_incomplete():
            if self.allow_deferred_init:
                self._deferred_init = (initializer, ctx)
                return
            raise MXNetError(
                "cannot initialize %s: shape %s is incomplete and deferred "
                "init is not allowed" % (self.name, self._shape))
        self._init_impl(initializer, ctx)

    def _init_impl(self, initializer, ctx):
        arr = _nd.zeros(self._shape, ctx=ctx, dtype=self.dtype)
        if isinstance(initializer, str):
            initializer = init_mod.create(initializer)
        # a param-specific init rides in InitDesc attrs and bypasses
        # name-suffix dispatch (so bias_initializer='ones' actually wins)
        attrs = {"__init__": self.init} if self.init is not None else {}
        initializer(init_mod.InitDesc(self.name, attrs), arr)
        self._data = arr
        self._deferred_init = None
        if self._grad_req != "null":
            arr.attach_grad(self._grad_req)

    def _finish_deferred_init(self):
        if self._deferred_init is None:
            return
        if self._shape_incomplete():
            raise DeferredInitializationError(
                "parameter %s shape still incomplete: %s"
                % (self.name, self._shape))
        initializer, ctx = self._deferred_init
        self._init_impl(initializer, ctx)

    # ------------------------------------------------------------------
    def data(self, ctx=None):
        traced = tracing_override(self)
        if traced is not None:
            return traced
        if self._data is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    "parameter %s deferred init not complete; run a forward "
                    "pass or set shape" % (self.name,))
            raise MXNetError(
                "parameter %s has not been initialized; call .initialize()"
                % (self.name,))
        del ctx  # single storage; Trainer/mesh own placement
        return self._data

    def list_data(self):
        return [self.data()]

    def grad(self, ctx=None):
        del ctx
        d = self.data()
        if d._grad is None:
            raise MXNetError(
                "parameter %s has grad_req='null'; no gradient buffer"
                % (self.name,))
        if self._grad_stype == "row_sparse":
            # TPU-native split (sparse.py design note): inside XLA the
            # embedding backward is a dense scatter-add; the row_sparse
            # view materializes here, at the framework boundary, so
            # Trainer/KVStore push and the optimizer update touch only
            # rows with nonzero gradient (ref: Embedding sparse_grad +
            # _sparse_*_update lazy semantics).
            # DOCUMENTED DEVIATION: rows are recovered from the dense
            # buffer's nonzero rows, not from the batch's index list —
            # a batch-touched row whose gradient cancels to exactly 0
            # is treated as untouched (skipping its wd/momentum decay),
            # where the reference would include it.
            from ..sparse import row_sparse_array
            return row_sparse_array(d._grad)
        return d._grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        if self._data is None:
            if self._deferred_init is not None:
                return [self._deferred_init[1]]
            raise MXNetError("parameter %s not initialized" % (self.name,))
        return [self._data.context]

    def set_data(self, data):
        if isinstance(data, NDArray):
            data = data.data
        import jax.numpy as jnp

        # shape setter raises on mismatch — keeps param.shape, the buffer,
        # and the grad buffer in sync (checkpoint loads with wrong shapes
        # must fail here, not deep inside XLA later)
        self.shape = tuple(data.shape)
        if self._data is None:
            self._deferred_init_default()
        # copy, never alias: the reference's set_data writes INTO the
        # param's own storage, and an aliased buffer would be invalidated
        # for this param when the source param's trainer donates it
        # (jax.jit donate_argnums in _FusedUpdate / ShardedTrainStep)
        self._data._set_data(jnp.array(data, dtype=self.dtype, copy=True))

    def _deferred_init_default(self):
        if self._data is None:
            if self._deferred_init is not None:
                self._finish_deferred_init()
            else:
                self._init_impl(init_mod.Zero(), current_context())

    def zero_grad(self):
        d = self._data
        if d is not None and d._grad is not None:
            import jax.numpy as jnp

            d._grad._set_data(jnp.zeros(d.shape, d.dtype))

    def reset_ctx(self, ctx):
        if self._data is not None:
            self._data = self._data.as_in_context(ctx)
            if self._grad_req != "null":
                self._data.attach_grad(self._grad_req)

    def cast(self, dtype):
        self.dtype = get_dtype(dtype)
        if self._data is not None:
            had_grad = self._data._grad is not None
            self._data = self._data.astype(self.dtype)
            if had_grad:
                self._data.attach_grad(self._grad_req)

    def var(self):
        from ..symbol.symbol import var

        return var(self.name, shape=self._shape, dtype=self.dtype)

    def __repr__(self):
        return "Parameter %s (shape=%s, dtype=%s)" % (
            self.name, self._shape, np.dtype(self.dtype).name)


class _ValueInit(init_mod.Initializer):
    """Fills with a fixed array — backs Constant so force_reinit restores
    the constant's value instead of zeroing it."""

    def __init__(self, value_np):
        super().__init__()
        self._value = value_np

    def _init_weight(self, name, arr):
        self._fill(arr, self._value)


class Constant(Parameter):
    """Non-learnable constant parameter (ref: parameter.py — Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = _nd.array(value)
        self.value = value
        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype,
                         init=_ValueInit(value.asnumpy()),
                         differentiable=False)
        self._data = value


class ParameterDict:
    """Ordered name→Parameter mapping with a shared prefix
    (ref: parameter.py — ParameterDict)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def get(self, name, **kwargs):
        """Get-or-create parameter ``prefix+name``."""
        full = self._prefix + name
        if self._shared is not None and full in self._shared._params:
            # record the shared hit locally too (ref: parameter.py —
            # ParameterDict.get inserts found shared params): a tied
            # parameter must appear in the borrowing block's
            # collect_params(), else CachedOp traces it as a baked-in
            # constant instead of a live input (fatal once the trainer
            # donates the underlying buffer)
            param = self._shared._params[full]
            shape = kwargs.get("shape")
            if shape is not None and param.shape is not None:
                want, have = tuple(shape), tuple(param.shape)
                if len(want) != len(have) or any(
                        w and h and w != h for w, h in zip(want, have)):
                    raise MXNetError(
                        "tied parameter %s has shape %s, incompatible "
                        "with requested %s (ref: get() validates against "
                        "a shared-found parameter)" % (full, have, want))
            dtype = kwargs.get("dtype")
            if dtype is not None and param.dtype is not None and \
                    np.dtype(dtype) != np.dtype(param.dtype):
                raise MXNetError(
                    "tied parameter %s has dtype %s, incompatible with "
                    "requested %s" % (full, param.dtype, dtype))
            self._params[full] = param
            return param
        if full in self._params:
            param = self._params[full]
            for k, v in kwargs.items():
                if k == "shape" and v is not None:
                    param.shape = v
            return param
        param = Parameter(full, **kwargs)
        self._params[full] = param
        return param

    def get_constant(self, name, value=None):
        full = self._prefix + name
        if full in self._params:
            return self._params[full]
        c = Constant(full, value)
        self._params[full] = c
        return c

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError("duplicate parameter name %s" % (k,))
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        del verbose
        for p in self._params.values():
            p.initialize(init=init, ctx=ctx, force_reinit=force_reinit)

    def zero_grad(self):
        for p in self._params.values():
            p.zero_grad()

    def setattr(self, name, value):
        for p in self._params.values():
            setattr(p, name, value)

    def reset_ctx(self, ctx):
        for p in self._params.values():
            p.reset_ctx(ctx)

    def save(self, fname, strip_prefix=""):
        payload = {}
        for name, p in self._params.items():
            key = name[len(strip_prefix):] if name.startswith(strip_prefix) \
                else name
            payload[key] = p.data()
        _nd.save(fname, payload)

    def load(self, fname, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        loaded = _nd.load(fname)
        loaded = {restore_prefix + k: v for k, v in loaded.items()}
        for name, p in self._params.items():
            if name in loaded:
                p.set_data(loaded[name])
            elif not allow_missing:
                raise MXNetError("parameter %s missing from %s" % (name, fname))
        if not ignore_extra:
            extra = set(loaded) - set(self._params)
            if extra:
                raise MXNetError(
                    "file %s contains extra parameters: %s" % (fname, extra))

    def __repr__(self):
        return "ParameterDict(%s)" % (", ".join(self._params),)
