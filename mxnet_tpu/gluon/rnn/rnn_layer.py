"""Fused recurrent layers (ref: python/mxnet/gluon/rnn/rnn_layer.py).

The reference backs RNN/LSTM/GRU with the single fused ``RNN`` op (cuDNN
path, packed flat weights). Here the same fused op lowers to one
``lax.scan`` per layer inside the jitted program (ops/rnn.py): the input
projection for the whole sequence is one big MXU matmul and only the
recurrent part scans. Parameters stay registered *unfused* (per
layer/direction ``l0_i2h_weight`` …, matching the reference's param names
and checkpoint format) and are packed at trace time — XLA folds the
concatenation away.
"""
from __future__ import annotations

from ...base import MXNetError
from ...ndarray.ndarray import NDArray
from ..block import HybridBlock
from ..parameter import DeferredInitializationError

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    """Base for fused-op recurrent layers (ref: rnn_layer.py — _RNNLayer)."""

    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, dtype="float32", prefix=None,
                 params=None):
        self._mode = mode  # before super(): _alias() uses it
        super().__init__(prefix=prefix, params=params)
        if layout not in ("TNC", "NTC"):
            raise MXNetError(
                "Invalid layout %r; must be one of ['TNC', 'NTC']" % layout)
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._dtype = dtype
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]

        ng, ni, nh = self._gates, input_size, hidden_size
        with self.name_scope():
            for i in range(num_layers):
                for j in ["l", "r"][: self._dir]:
                    self._register_param(
                        "%s%d_i2h_weight" % (j, i), (ng * nh, ni),
                        i2h_weight_initializer, dtype)
                    self._register_param(
                        "%s%d_h2h_weight" % (j, i), (ng * nh, nh),
                        h2h_weight_initializer, dtype)
                    self._register_param(
                        "%s%d_i2h_bias" % (j, i), (ng * nh,),
                        i2h_bias_initializer, dtype)
                    self._register_param(
                        "%s%d_h2h_bias" % (j, i), (ng * nh,),
                        h2h_bias_initializer, dtype)
                ni = nh * self._dir

    def _register_param(self, name, shape, init, dtype):
        p = self.params.get(name, shape=shape, init=init, dtype=dtype,
                            allow_deferred_init=True)
        setattr(self, name, p)

    def _alias(self):
        return self._mode

    def __repr__(self):
        s = "{name}({mapping}, {_layout}"
        if self._num_layers != 1:
            s += ", num_layers={_num_layers}"
        if self._dropout != 0:
            s += ", dropout={_dropout}"
        if self._dir == 2:
            s += ", bidirectional"
        s += ")"
        shape = self.l0_i2h_weight.shape
        mapping = "%s -> %s" % (
            shape[1] if shape[1] else None, shape[0] // self._gates)
        return s.format(name=type(self).__name__, mapping=mapping,
                        **self.__dict__)

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def infer_shape(self, x, *args):
        ni = x.shape[2] if self._layout == "TNC" else x.shape[-1]
        for i in range(self._num_layers):
            for j in ["l", "r"][: self._dir]:
                p = getattr(self, "%s%d_i2h_weight" % (j, i))
                p.shape = (self._gates * self._hidden_size, ni)
            ni = self._hidden_size * self._dir

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial recurrent states (ref: rnn_layer.py — begin_state)."""
        from ... import ndarray as F

        if func is None:
            func = F.zeros
        states = []
        for info in self.state_info(batch_size):
            kw = dict(kwargs)
            kw.update(info)
            shape = kw.pop("shape")
            kw.pop("__layout__", None)
            states.append(func(shape=shape, **kw))
        return states

    def forward(self, inputs, states=None):
        """Run the fused recurrence. With ``states=None`` begins from zeros
        and returns only the output; otherwise returns
        ``(output, new_states)`` (ref: rnn_layer.py — forward)."""
        from ... import ndarray as F

        batch_axis = self._layout.find("N")
        batch_size = inputs.shape[batch_axis]
        skip_states = states is None
        if skip_states:
            states = self.begin_state(batch_size, dtype=inputs.dtype)
        if isinstance(states, NDArray):
            states = [states]
        for info, state in zip(self.state_info(batch_size), states):
            if state.shape != info["shape"]:
                raise MXNetError(
                    "Invalid recurrent state shape. Expecting %s, got %s."
                    % (str(info["shape"]), str(state.shape)))

        try:
            params = {k: p.data() for k, p in self._reg_params.items()}
        except DeferredInitializationError:
            self._deferred_infer(inputs)
            params = {k: p.data() for k, p in self._reg_params.items()}

        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, dim1=0, dim2=1)

        flat = []
        for group in ("weight", "bias"):
            for i in range(self._num_layers):
                for j in ["l", "r"][: self._dir]:
                    for conn in ("i2h", "h2h"):
                        flat.append(F.reshape(
                            params["%s%d_%s_%s" % (j, i, conn, group)],
                            shape=(-1,)))
        packed = F.concat(*flat, dim=0)

        import mxnet_tpu.autograd as ag

        rnn_args = [inputs, packed, states[0]]
        if self._mode == "lstm":
            rnn_args.append(states[1])
        out = F.RNN(
            *rnn_args, mode=self._mode, state_size=self._hidden_size,
            num_layers=self._num_layers, bidirectional=self._dir == 2,
            p=self._dropout, state_outputs=True,
            train_mode=ag.is_training())
        outputs, new_states = out[0], list(out[1:])

        if self._layout == "NTC":
            outputs = F.swapaxes(outputs, dim1=0, dim2=1)
        if skip_states:
            return outputs
        return outputs, new_states


class RNN(_RNNLayer):
    """Multi-layer Elman RNN with tanh/relu (ref: rnn_layer.py — RNN)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, dtype="float32", **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "rnn_" + activation,
                         dtype=dtype, **kwargs)

    def state_info(self, batch_size=0):
        return [{
            "shape": (self._num_layers * self._dir, batch_size,
                      self._hidden_size),
            "__layout__": "LNC",
        }]


class LSTM(_RNNLayer):
    """Multi-layer LSTM (ref: rnn_layer.py — LSTM; gate order [i,f,g,o])."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 dtype="float32", **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "lstm", dtype=dtype, **kwargs)

    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        return [{"shape": shape, "__layout__": "LNC"},
                {"shape": shape, "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """Multi-layer GRU (ref: rnn_layer.py — GRU; gate order [r,z,n])."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 dtype="float32", **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "gru", dtype=dtype, **kwargs)

    def state_info(self, batch_size=0):
        return [{
            "shape": (self._num_layers * self._dir, batch_size,
                      self._hidden_size),
            "__layout__": "LNC",
        }]
