"""Recurrent cells + unroll (ref: python/mxnet/gluon/rnn/rnn_cell.py).

Cells are the step-granular API; ``unroll`` lays the steps out in Python so
the whole unrolled sequence traces into one XLA program under hybridize —
the reference's unfused fallback path, which on TPU is also fast because XLA
fuses across steps. Variable-length handling uses SequenceMask/SequenceLast,
like the reference.
"""
from __future__ import annotations

from ... import autograd
from ...base import MXNetError
from ...ndarray.ndarray import NDArray
from ..block import Block, HybridBlock
from ..parameter import DeferredInitializationError

__all__ = [
    "RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell", "GRUCell",
    "SequentialRNNCell", "HybridSequentialRNNCell", "DropoutCell",
    "ModifierCell", "ZoneoutCell", "ResidualCell", "BidirectionalCell",
]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _format_sequence(length, inputs, layout, merge, in_layout=None):
    """Normalize inputs to (list-of-steps | time-major tensor) form
    (ref: rnn_cell.py — _format_sequence)."""
    from ... import ndarray as F

    axis = layout.find("T")
    batch_axis = layout.find("N")
    if isinstance(inputs, NDArray):
        batch_size = inputs.shape[batch_axis]
        if merge is False:
            if length is not None and inputs.shape[axis] != length:
                raise MXNetError(
                    "unroll(length=%s) does not match input sequence "
                    "length %d" % (length, inputs.shape[axis]))
            inputs = list(F.split(
                inputs, axis=axis, num_outputs=inputs.shape[axis],
                squeeze_axis=1))
    else:
        assert length is None or len(inputs) == length
        batch_size = inputs[0].shape[batch_axis]
        if merge is True:
            inputs = F.stack(*inputs, axis=axis)
    del in_layout
    return inputs, axis, batch_size


def _mask_sequence_variable_length(F, data, length, valid_length, time_axis,
                                   merge):
    assert valid_length is not None
    if not isinstance(data, NDArray):
        data = F.stack(*data, axis=time_axis)
    outputs = F.SequenceMask(data, valid_length,
                             use_sequence_length=True, axis=time_axis)
    if not merge:
        outputs = list(F.split(outputs, num_outputs=data.shape[time_axis],
                               axis=time_axis, squeeze_axis=True))
    return outputs


class RecurrentCell(Block):
    """Abstract base for recurrent cells (ref: rnn_cell.py — RecurrentCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called " \
            "directly. Call the modifier cell instead."
        from ... import ndarray as F

        if func is None:
            func = F.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            kw = dict(kwargs)
            if info is not None:
                kw.update(info)
            shape = kw.pop("shape")
            kw.pop("__layout__", None)
            states.append(func(shape=shape, **kw))
        return states

    def __call__(self, inputs, states):
        self._counter += 1
        return super().__call__(inputs, states)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll the cell for ``length`` steps (ref: rnn_cell.py — unroll)."""
        from ... import ndarray as F

        self.reset()
        inputs, axis, batch_size = _format_sequence(
            length, inputs, layout, False)
        begin_state = self._get_begin_state(inputs, begin_state, batch_size)

        states = begin_state
        outputs = []
        all_states = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
            if valid_length is not None:
                all_states.append(states)
        if valid_length is not None:
            states = [
                F.SequenceLast(
                    F.stack(*[st[j] for st in all_states], axis=0),
                    valid_length, use_sequence_length=True, axis=0)
                for j in range(len(states))
            ]
            outputs = _mask_sequence_variable_length(
                F, outputs, length, valid_length, axis, True)
            if merge_outputs is False:
                outputs = list(F.split(outputs, num_outputs=length, axis=axis,
                                       squeeze_axis=True))
        elif merge_outputs is True:
            outputs = F.stack(*outputs, axis=axis)
        # merge_outputs None keeps the per-step list (no valid_length) /
        # the merged tensor (valid_length path), matching the reference
        return outputs, states

    def _get_begin_state(self, inputs, begin_state, batch_size):
        if begin_state is None:
            if isinstance(inputs, NDArray):
                dtype = inputs.dtype
            else:
                dtype = inputs[0].dtype
            begin_state = self.begin_state(batch_size, dtype=dtype)
        return begin_state


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    """RecurrentCell whose step is hybridizable."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def forward(self, x, *args):
        try:
            params = {k: p.data() for k, p in self._reg_params.items()}
        except DeferredInitializationError:
            self._deferred_infer(x, *args)
            params = {k: p.data() for k, p in self._reg_params.items()}
        from ... import ndarray as F

        return self.hybrid_forward(F, x, *args, **params)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class RNNCell(HybridRecurrentCell):
    """Elman RNN cell (ref: rnn_cell.py — RNNCell)."""

    def __init__(self, hidden_size, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight=None,
                       h2h_weight=None, i2h_bias=None, h2h_bias=None):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = F.Activation(i2h + h2h, act_type=self._activation)
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    """LSTM cell, gate order [i,f,g,o] (ref: rnn_cell.py — LSTMCell)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight=None,
                       h2h_weight=None, i2h_bias=None, h2h_bias=None):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slice_gates = F.split(gates, num_outputs=4, axis=1)
        in_gate = F.sigmoid(slice_gates[0])
        forget_gate = F.sigmoid(slice_gates[1])
        in_transform = F.tanh(slice_gates[2])
        out_gate = F.sigmoid(slice_gates[3])
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * F.tanh(next_c)
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """GRU cell, gate order [r,z,n] (ref: rnn_cell.py — GRUCell)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(3 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(3 * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(3 * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(3 * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (3 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight=None,
                       h2h_weight=None, i2h_bias=None, h2h_bias=None):
        prev_state_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_state_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h = F.split(i2h, num_outputs=3, axis=1)
        h2h_r, h2h_z, h2h = F.split(h2h, num_outputs=3, axis=1)
        reset_gate = F.sigmoid(i2h_r + h2h_r)
        update_gate = F.sigmoid(i2h_z + h2h_z)
        next_h_tmp = F.tanh(i2h + reset_gate * h2h)
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_state_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Sequentially stacked cells (ref: rnn_cell.py — SequentialRNNCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(),
                                  batch_size=batch_size, **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children.values():
            assert not isinstance(cell, BidirectionalCell)
            n = len(cell.state_info())
            inputs, state = cell(inputs, states[p: p + n])
            p += n
            next_states.append(state)
        return inputs, sum(next_states, [])

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        _, _, batch_size = _format_sequence(length, inputs, layout, None)
        num_cells = len(self._children)
        begin_state = self._get_begin_state(inputs, begin_state, batch_size)
        p = 0
        next_states = []
        for i, cell in enumerate(self._children.values()):
            n = len(cell.state_info())
            states = begin_state[p: p + n]
            p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < num_cells - 1 else merge_outputs,
                valid_length=valid_length)
            next_states.extend(states)
        return inputs, next_states

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)

    def forward(self, *args, **kwargs):
        raise NotImplementedError


class HybridSequentialRNNCell(HybridRecurrentCell):
    """Hybridizable sequential stack (ref: rnn_cell.py)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(),
                                  batch_size=batch_size, **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children.values():
            assert not isinstance(cell, BidirectionalCell)
            n = len(cell.state_info())
            inputs, state = cell(inputs, states[p: p + n])
            p += n
            next_states.append(state)
        return inputs, sum(next_states, [])

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        return SequentialRNNCell.unroll(
            self, length, inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs, valid_length=valid_length)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)


class DropoutCell(HybridRecurrentCell):
    """Applies dropout on input (ref: rnn_cell.py — DropoutCell)."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        assert isinstance(rate, (int, float))
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes,
                               train_mode=autograd.is_training())
        return inputs, states


class ModifierCell(HybridRecurrentCell):
    """Base for cells that modify another cell (ref: rnn_cell.py)."""

    def __init__(self, base_cell):
        assert not base_cell._modified, \
            "Cell %s is already modified." % base_cell.name
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias(),
                         params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(batch_size=batch_size, func=func,
                                           **kwargs)
        self.base_cell._modified = True
        return begin


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (ref: rnn_cell.py — ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, BidirectionalCell)
        self._alias_name = "zoneout"
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        cell = self.base_cell
        next_output, next_states = cell(inputs, states)
        if not autograd.is_training():
            return next_output, next_states

        def mask(p, like):
            # reached only under autograd.is_training() (guard above)
            return F.Dropout(F.ones_like(like), p=p, train_mode=True)

        prev_output = self._prev_output
        if prev_output is None:
            prev_output = F.zeros_like(next_output)
        p_outputs = self.zoneout_outputs
        p_states = self.zoneout_states
        output = (F.where(mask(p_outputs, next_output), next_output,
                          prev_output)
                  if p_outputs != 0.0 else next_output)
        new_states = ([F.where(mask(p_states, new_s), new_s, old_s)
                       for new_s, old_s in zip(next_states, states)]
                      if p_states != 0.0 else next_states)
        self._prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    """Adds input to output (ref: rnn_cell.py — ResidualCell)."""

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states

    def _alias(self):
        return "residual"

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as F

        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs, valid_length=valid_length)
        self.base_cell._modified = True

        merge_outputs = (isinstance(outputs, NDArray)
                         if merge_outputs is None else merge_outputs)
        inputs, axis, _ = _format_sequence(
            length, inputs, layout, merge_outputs)
        if valid_length is not None:
            inputs = _mask_sequence_variable_length(
                F, inputs, length, valid_length, axis, merge_outputs)
        if merge_outputs:
            outputs = outputs + inputs
        else:
            outputs = [out + inp for out, inp in zip(outputs, inputs)]
        return outputs, states


class BidirectionalCell(HybridRecurrentCell):
    """Runs forward + backward cells over the sequence
    (ref: rnn_cell.py — BidirectionalCell)."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise MXNetError(
            "Bidirectional cannot be stepped. Please use unroll")

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(),
                                  batch_size=batch_size, **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as F

        self.reset()
        inputs, axis, batch_size = _format_sequence(
            length, inputs, layout, False)
        if valid_length is None:
            reversed_inputs = list(reversed(inputs))
        else:
            # reverse only the valid prefix so the backward cell sees real
            # tokens first, not padding (ref: rnn_cell.py — BidirectionalCell
            # uses SequenceReverse with sequence_length)
            rev = F.SequenceReverse(F.stack(*inputs, axis=0), valid_length,
                                    use_sequence_length=True)
            reversed_inputs = list(F.split(
                rev, num_outputs=length, axis=0, squeeze_axis=True))
        begin_state = self._get_begin_state(inputs, begin_state, batch_size)

        states = begin_state
        l_cell, r_cell = self._children.values()
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs,
            begin_state=states[: len(l_cell.state_info(batch_size))],
            layout=layout, merge_outputs=merge_outputs,
            valid_length=valid_length)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=reversed_inputs,
            begin_state=states[len(l_cell.state_info(batch_size)):],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        if valid_length is None:
            reversed_r_outputs = list(reversed(r_outputs))
        else:
            stacked = F.stack(*r_outputs, axis=0)
            rev = F.SequenceReverse(stacked, valid_length,
                                    use_sequence_length=True)
            reversed_r_outputs = list(F.split(
                rev, num_outputs=length, axis=0, squeeze_axis=True))
        if merge_outputs is None:
            merge_outputs = isinstance(l_outputs, NDArray)
        if merge_outputs:
            if not isinstance(l_outputs, NDArray):
                l_outputs = F.stack(*l_outputs, axis=axis)
            reversed_r_outputs = F.stack(*reversed_r_outputs, axis=axis)
            outputs = F.concat(l_outputs, reversed_r_outputs, dim=2)
        else:
            if isinstance(l_outputs, NDArray):
                l_outputs = list(F.split(
                    l_outputs, num_outputs=length, axis=axis,
                    squeeze_axis=True))
            outputs = [F.concat(l_o, r_o, dim=1)
                       for l_o, r_o in zip(l_outputs, reversed_r_outputs)]
        if valid_length is not None:
            outputs = _mask_sequence_variable_length(
                F, outputs, length, valid_length, axis, merge_outputs)
        states = l_states + r_states
        return outputs, states
