"""Elastic membership for the distributed KVStore — heartbeats, liveness,
stale-push fencing, and worker rejoin (ref: ps-lite's ``Van`` membership
under src/kvstore/kvstore_dist_server.h: ADD_NODE/HEARTBEAT control
messages and the per-node timestamp table the scheduler reaps).

PR 2 made the dist paths survive *transient* faults; this module handles
*permanent* ones — a worker that died, froze, or rebooted:

1. **Heartbeats + liveness.** Every worker registers with the
   coordinator-side server (the authenticated async-server transport)
   and heartbeats on a background thread every ``MXT_HEARTBEAT_INTERVAL``
   seconds. The server's :class:`MembershipTable` stamps each beat; a
   reaper thread declares a worker dead after ``MXT_LIVENESS_TIMEOUT``
   seconds of silence, fences its generation, and bumps the membership
   *epoch* (the version number of the member view).

2. **Stale-push fencing.** Registration assigns a monotonically
   increasing *generation* number (never reused, even across store
   resets). Data frames carry ``(worker_id, generation)``; the server
   rejects any frame whose generation is fenced — dead, replaced by a
   re-registration, or never registered — with a typed
   :class:`StaleWorkerError`, so a zombie's delayed in-flight push can
   never corrupt server-side weights (the classic fencing-token design).

3. **Elastic degradation + rejoin.** :meth:`MembershipTable.barrier` and
   :meth:`MembershipTable.reduce` release against the LIVE member set,
   not the static world size: when a peer is declared dead mid-round the
   survivors complete (the kvstore renormalizes the reduced sum by
   ``num_workers / len(survivors)`` so the gradient stays an unbiased
   full-batch estimate) and the loss lands in the ``lost_workers``
   profiler counter. A restarted worker rejoins by re-registering: it
   receives a fresh generation, the current epoch, and a CRC-verified
   full parameter snapshot of the server store (the wire analog of
   resilience.CheckpointManager's CRC'd manifest) before it may push.

Failure modes are deterministic through the seeded ``MXT_FAULT`` rules:
``hb_drop`` loses heartbeats on the wire, ``worker_freeze:worker=I``
freezes worker I's heartbeat thread (the process lives on as a zombie),
and ``rejoin_race:ms=N`` widens the server-side window between fencing
the old generation and answering the re-registration.
"""
from __future__ import annotations

import threading
import time
import zlib

import numpy as np

from .base import MXNetError
from .resilience import KVStoreError

__all__ = [
    "StaleWorkerError", "BarrierTimeout", "MemberInfo", "MembershipTable",
    "WorkerMembership", "record_lost_workers", "lost_worker_count",
]


class StaleWorkerError(KVStoreError):
    """A frame arrived from a fenced-out (worker_id, generation): the
    worker was declared dead, was replaced by a re-registration, or
    never registered. The server refuses the frame so a zombie's delayed
    push cannot corrupt server-side weights; the worker must re-register
    (rejoin) before it may speak again."""


class BarrierTimeout(KVStoreError):
    """A membership barrier/reduce exceeded its deadline — a live peer
    never arrived. Raised instead of hanging the waiting workers."""


_LOST_COUNTER = "lost_workers"
_lost_counter = None


def record_lost_workers(n=1):
    """Bump the lost-worker profiler counter (shows in profiler.dumps())."""
    global _lost_counter
    from . import profiler

    if _lost_counter is None or _LOST_COUNTER not in profiler._counters:
        _lost_counter = profiler.Counter(None, _LOST_COUNTER)
    _lost_counter.increment(n)


def lost_worker_count():
    from . import profiler

    return profiler.counter_value(_LOST_COUNTER)


class MemberInfo:
    """One registered worker: its fencing generation, last heartbeat,
    and optional registration metadata (embedding servers announce
    their serving endpoint here so clients can rebuild the consistent-
    hash ring from the membership view alone)."""

    __slots__ = ("worker_id", "generation", "last_beat", "alive", "meta")

    def __init__(self, worker_id, generation, now, meta=None):
        self.worker_id = worker_id
        self.generation = generation
        self.last_beat = now
        self.alive = True
        self.meta = meta


class MembershipTable:
    """Server-side membership view (ref: ps-lite Postoffice's node table).

    Thread-safe; one Condition serializes mutation and wakes barrier and
    reduce waiters when the view changes (arrival, death, rejoin). The
    generation counter is global and monotone — it survives
    :meth:`reset` so a generation can never be reused and an old world's
    frames always fence out.
    """

    def __init__(self):
        self._cond = threading.Condition()
        self._members = {}      # worker_id -> MemberInfo
        self._epoch = 0         # bumped on every view change
        self._next_gen = 1      # global monotone fencing-token counter
        self._lost_total = 0    # workers declared dead (not deregistered)
        self._barriers = {}     # tag -> {"arrived": set, "waiters": int}
        self._barrier_released = set()  # tags whose round has released
        self._barrier_last = {}  # tag base -> last released numeric seq
        self._reduces = {}      # (key, seq) -> in-flight round entry
        self._reduce_last = {}  # key -> (seq, sum, wids) last released
        self._death_listeners = []  # fn(worker_ids) on reap (see below)

    # -- registration ------------------------------------------------------
    def register(self, worker_id, now=None, meta=None):
        """Admit (or re-admit) a worker. Returns ``(generation, epoch,
        rejoin)`` — ``rejoin`` is True when this worker_id was known
        before (crashed/fenced/restarted), which entitles it to a state
        snapshot. The previous generation, if any, is fenced by the
        replacement. ``meta`` (a small picklable dict — e.g. an
        embedding server's serving endpoint) is carried in the member
        view."""
        now = time.monotonic() if now is None else now
        with self._cond:
            rejoin = worker_id in self._members
            gen = self._next_gen
            self._next_gen += 1
            self._members[worker_id] = MemberInfo(worker_id, gen, now,
                                                  meta=meta)
            self._epoch += 1
            epoch = self._epoch
            live = len(self._live_ids_locked())
            self._cond.notify_all()
        self._note_view_change(epoch, live,
                               "rejoin" if rejoin else "register",
                               worker_id=worker_id, generation=gen)
        return gen, epoch, rejoin

    def deregister(self, worker_id, generation):
        """Graceful leave: removed from the view without counting as
        lost. A stale generation is ignored (a zombie cannot evict its
        replacement)."""
        with self._cond:
            m = self._members.get(worker_id)
            if m is not None and m.generation == generation:
                del self._members[worker_id]
                self._epoch += 1
                self._cond.notify_all()

    def reset(self):
        """New store world (kvstore 'reset'): forget members but KEEP the
        generation counter so pre-reset credentials stay fenced."""
        with self._cond:
            self._members.clear()
            self._barriers.clear()
            self._barrier_released.clear()
            self._barrier_last.clear()
            self._reduces.clear()
            self._reduce_last.clear()
            self._epoch += 1
            self._cond.notify_all()

    # -- liveness ----------------------------------------------------------
    def _check_locked(self, worker_id, generation):
        m = self._members.get(worker_id)
        if m is None:
            raise StaleWorkerError(
                "worker %r (generation %r) is not a registered member — "
                "a restarted worker must re-register (rejoin) before it "
                "may push" % (worker_id, generation))
        if m.generation != generation:
            raise StaleWorkerError(
                "worker %r generation %r is fenced out (current "
                "generation %r): frames from the old incarnation are "
                "rejected" % (worker_id, generation, m.generation))
        if not m.alive:
            raise StaleWorkerError(
                "worker %r (generation %r) was declared dead after "
                "missing its liveness window — re-register to rejoin"
                % (worker_id, generation))

    def check(self, worker_id, generation):
        """Raise :class:`StaleWorkerError` unless (worker_id, generation)
        is the current, live incarnation."""
        with self._cond:
            self._check_locked(worker_id, generation)

    def heartbeat(self, worker_id, generation, now=None):
        """Stamp a beat. Returns ``(epoch, lost_total)`` so workers learn
        membership changes for free on every beat."""
        now = time.monotonic() if now is None else now
        with self._cond:
            self._check_locked(worker_id, generation)
            self._members[worker_id].last_beat = now
            return self._epoch, self._lost_total

    def reap(self, timeout, now=None):
        """Declare workers dead whose last beat is older than ``timeout``
        seconds. Returns the newly dead worker_ids; bumps the epoch and
        the ``lost_workers`` profiler counter, and wakes barrier/reduce
        waiters so survivors release."""
        now = time.monotonic() if now is None else now
        with self._cond:
            dead = [m for m in self._members.values()
                    if m.alive and now - m.last_beat > timeout]
            for m in dead:
                m.alive = False
            if dead:
                self._lost_total += len(dead)
                self._epoch += 1
                epoch = self._epoch
                live = len(self._live_ids_locked())
                self._cond.notify_all()
        if dead:
            record_lost_workers(len(dead))
            self._note_view_change(epoch, live, "reaped",
                                   workers=[m.worker_id for m in dead])
            # death listeners run OUTSIDE the lock on the reaper's
            # thread: the elastic reshard controller
            # (parallel/reshard.py) records the loss here and reshapes
            # the mesh at the training loop's next drain point. A
            # listener failure must never kill the reaper.
            ids = [m.worker_id for m in dead]
            for fn in list(self._death_listeners):
                try:
                    fn(list(ids))
                except Exception:  # noqa: BLE001 — listener isolation
                    pass
        return [m.worker_id for m in dead]

    def add_death_listener(self, fn):
        """Register ``fn(worker_ids)`` to run whenever :meth:`reap`
        declares workers dead (after fencing + telemetry, outside the
        condition lock). This is the hook that fuses the elasticity
        layer with the GSPMD path: survivors reshard the mesh in place
        instead of restarting (parallel.ElasticReshardController)."""
        self._death_listeners.append(fn)

    @staticmethod
    def _note_view_change(epoch, live, event, **fields):
        """Publish a membership view change to the telemetry layer:
        epoch/live-member gauges, a per-event counter, and a JSONL
        event — outside the condition lock (the sink enqueue must never
        serialize against barrier/reduce waiters)."""
        from . import telemetry

        telemetry.gauge("mxt_membership_epoch",
                        "Membership view version (bumped on every "
                        "register/death/leave).").set(epoch)
        telemetry.gauge("mxt_membership_live_workers",
                        "Live registered workers.").set(live)
        telemetry.counter("mxt_membership_events_total",
                          "Membership view changes by kind.",
                          ("event",)).labels(event).inc()
        telemetry.emit_event("membership", event=event, epoch=epoch,
                             live=live, **fields)

    # -- views -------------------------------------------------------------
    def _live_ids_locked(self):
        return {w for w, m in self._members.items() if m.alive}

    def live_ids(self):
        with self._cond:
            return self._live_ids_locked()

    def has_members(self):
        with self._cond:
            return bool(self._members)

    def view(self):
        """Serializable snapshot of the membership state."""
        with self._cond:
            return {
                "epoch": self._epoch,
                "members": {w: m.generation
                            for w, m in self._members.items() if m.alive},
                "dead": {w: m.generation
                         for w, m in self._members.items() if not m.alive},
                "meta": {w: m.meta for w, m in self._members.items()
                         if m.alive and m.meta is not None},
                "lost_total": self._lost_total,
            }

    # -- elastic rendezvous ------------------------------------------------
    def rendezvous_seqs(self):
        """Last RELEASED barrier/reduce round per tag base / key. Handed
        to a rejoining worker inside the registration snapshot so its
        client-side counters resume at the survivors' rounds: a
        respawned worker whose counters restarted at 0 would tag rounds
        the survivors already finished, and every later rendezvous on
        both sides would time out."""
        with self._cond:
            return {"barrier": dict(self._barrier_last),
                    "reduce": {k: s for k, (s, _, _)
                               in self._reduce_last.items()}}

    def _release_barrier_locked(self, tag):
        if tag in self._barrier_released:
            return
        self._barrier_released.add(tag)
        base, sep, num = tag.rpartition(":")
        if sep and num.isdigit():
            self._barrier_last[base] = max(
                self._barrier_last.get(base, 0), int(num))

    def barrier(self, worker_id, generation, tag, timeout, poll=0.05):
        """Block until every LIVE member arrived at ``tag``. A member
        declared dead while others wait is dropped from the release
        condition (sync degrades instead of hanging); a live peer that
        never arrives within ``timeout`` raises :class:`BarrierTimeout`.
        Returns the epoch at release.

        At-least-once safe: duplicate waiters for one (tag, worker) —
        a client retry whose first frame is still parked — are
        refcounted, so the round's bookkeeping is freed exactly when
        the last waiter leaves; a retry arriving AFTER the round
        released is acked immediately (tags are never reused) instead
        of recreating the entry and leaking it."""
        deadline = time.monotonic() + float(timeout)
        with self._cond:
            self._check_locked(worker_id, generation)
            if tag in self._barrier_released:
                return self._epoch
            ent = self._barriers.setdefault(
                tag, {"arrived": set(), "waiters": 0})
            ent["arrived"].add(worker_id)
            ent["waiters"] += 1
            self._cond.notify_all()
            try:
                while tag not in self._barrier_released \
                        and not ent["arrived"] >= self._live_ids_locked():
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise BarrierTimeout(
                            "membership barrier %r timed out after %.1fs "
                            "waiting on live workers %s"
                            % (tag, float(timeout),
                               sorted(self._live_ids_locked()
                                      - ent["arrived"])))
                    self._cond.wait(min(poll, remaining))
                self._release_barrier_locked(tag)
                return self._epoch
            finally:
                ent["waiters"] -= 1
                if ent["waiters"] <= 0:
                    self._barriers.pop(tag, None)

    def reduce(self, worker_id, generation, key, seq, array, timeout,
               poll=0.05):
        """Elastic sum-reduction round ``(key, seq)``: contributions from
        live members accumulate server-side; the round releases when
        every live member has contributed (deaths shrink the wait set —
        the reaper wakes the waiters). Returns
        ``(sum, sorted(contributor_ids))`` — the CALLER renormalizes by
        its static world size if survivors < world.

        At-least-once safe: a contribution re-sent while the round is
        open is idempotent (one add per worker); one re-sent after the
        round released replays the released result instead of opening a
        fresh solo round that would wait out the full timeout; one older
        than the last released round is a stale frame and is refused."""
        rkey = (key, seq)
        deadline = time.monotonic() + float(timeout)
        array = np.asarray(array)
        with self._cond:
            self._check_locked(worker_id, generation)
            last = self._reduce_last.get(key)
            if rkey not in self._reduces and last is not None \
                    and seq <= last[0]:
                if seq == last[0]:
                    return np.array(last[1]), list(last[2])
                raise BarrierTimeout(
                    "membership reduce %r seq %d is older than the last "
                    "released round %d — the round is gone and cannot "
                    "be joined" % (key, seq, last[0]))
            ent = self._reduces.setdefault(
                rkey, {"sum": None, "wids": set(), "waiters": 0,
                       "released": None})
            if ent["released"] is None and worker_id not in ent["wids"]:
                ent["wids"].add(worker_id)
                ent["sum"] = array.copy() if ent["sum"] is None \
                    else ent["sum"] + array
                self._cond.notify_all()
            ent["waiters"] += 1
            try:
                while ent["released"] is None \
                        and not ent["wids"] >= self._live_ids_locked():
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise BarrierTimeout(
                            "membership reduce %r seq %d timed out after "
                            "%.1fs waiting on live workers %s"
                            % (key, seq, float(timeout),
                               sorted(self._live_ids_locked()
                                      - ent["wids"])))
                    self._cond.wait(min(poll, remaining))
                if ent["released"] is None:
                    ent["released"] = (np.array(ent["sum"]),
                                       sorted(ent["wids"]))
                    prev = self._reduce_last.get(key)
                    if prev is None or seq > prev[0]:
                        self._reduce_last[key] = (
                            seq, ent["released"][0], ent["released"][1])
                total, wids = ent["released"]
                return np.array(total), list(wids)
            finally:
                ent["waiters"] -= 1
                if ent["waiters"] <= 0:
                    self._reduces.pop(rkey, None)


def snapshot_checksums(weights):
    """CRC32 per array — the wire analog of CheckpointManager's per-file
    manifest CRCs, so a rejoin snapshot is verified before it is
    trusted."""
    return {k: zlib.crc32(np.ascontiguousarray(v).tobytes()) & 0xFFFFFFFF
            for k, v in weights.items()}


def verify_snapshot(snap):
    """Raise MXNetError if a rejoin snapshot fails its CRC manifest."""
    if snap is None:
        return None
    want = snap.get("crc32", {})
    got = snapshot_checksums(snap.get("weights", {}))
    if want != got:
        bad = sorted(k for k in set(want) | set(got)
                     if want.get(k) != got.get(k))
        raise MXNetError(
            "rejoin snapshot failed CRC verification for keys %s "
            "(corrupt handoff)" % bad)
    return snap


# A rendezvous request is legitimately held server-side for up to its
# full timeout before the typed release/timeout reply comes back. The
# transport gets that window PLUS this margin, so the server's reply
# always wins the race against the client-side deadline — otherwise the
# client gives up first, retries, and seeds duplicate server-side
# waiters for rounds that were about to answer.
_RENDEZVOUS_MARGIN = 5.0

# Graceful deregister is BEST-EFFORT and short-bounded: it runs during
# teardown, when the coordinator may already be gone (the PR 10
# teardown-order gotcha generalized — a fleet closed coordinator-first
# used to cost a full transport deadline PER dependent handle, because
# the deregister's reconnect spun out the handle's whole connect
# timeout). The bound caps both the retry deadline and the reconnect
# window; a missed deregister just means the reaper counts the member
# lost, which teardown doesn't care about.
_DEREGISTER_DEADLINE = 2.0


class WorkerMembership:
    """One worker's membership session: registration, the background
    heartbeat thread, and the elastic barrier/reduce client calls.

    Owns its own control connection to the server (separate from the
    data client) so a long-blocked push can never starve the heartbeat.
    ``MXT_FAULT`` hooks: ``hb_drop`` loses individual beats on the wire;
    ``worker_freeze:worker=I[,after=K]`` permanently freezes worker I's
    beats after K sends (the zombie scenario — the process and its data
    connection stay alive while the server declares it dead).
    """

    def __init__(self, host, port, worker_id, timeout=30.0):
        from .async_server import AsyncClient

        self.worker_id = int(worker_id)
        self.generation = None
        self._meta = None
        self.epoch = 0
        self.lost_total = 0
        self.snapshot = None
        self.frozen = False
        self.fenced = False
        self._ctl = AsyncClient(host, port, timeout=timeout)
        # barrier/reduce block server-side until the round releases — on
        # their own connection so a long rendezvous can never starve the
        # heartbeat (a worker must not be reaped for WAITING)
        self._rdv = None
        self._addr = (host, port, timeout)
        self._stop = threading.Event()
        self._thread = None
        self._beats = 0
        self._beat_source = "membership_beat_w%d" % self.worker_id

    def _rendezvous_client(self):
        if self._rdv is None:
            from .async_server import AsyncClient

            host, port, timeout = self._addr
            self._rdv = AsyncClient(host, port, timeout=timeout)
        return self._rdv

    # -- registration / rejoin --------------------------------------------
    def register(self, want_snapshot=False, meta=None):
        """Register (or rejoin). Fences any previous incarnation of this
        worker_id; on rejoin the server hands back a CRC-verified full
        parameter snapshot so the worker can resync before pushing.
        ``meta`` is published in the member view (embedding servers
        announce their serving endpoint through it)."""
        self._meta = meta
        payload = (self.worker_id, bool(want_snapshot)) if meta is None \
            else (self.worker_id, bool(want_snapshot), meta)
        status = self._ctl.request("register", None, payload)
        gen, epoch, snap = status
        self.generation = gen
        self.epoch = epoch
        self.snapshot = verify_snapshot(snap)
        self.fenced = False
        return self

    def re_register(self):
        """Rejoin after a fencing or server restart: fresh generation,
        current epoch, full snapshot; restarts heartbeats if the sender
        stopped."""
        self.register(want_snapshot=True,
                      meta=getattr(self, "_meta", None))
        if self._thread is not None and not self._thread.is_alive() \
                and not self._stop.is_set():
            self._thread = None
            self.start_heartbeats()
        return self.snapshot

    # -- heartbeats --------------------------------------------------------
    def heartbeat_now(self):
        """One synchronous beat; updates the cached epoch/lost view."""
        epoch, lost = self._ctl.request(
            "heartbeat", None, (self.worker_id, self.generation))
        self.epoch = epoch
        self.lost_total = lost
        return epoch, lost

    def start_heartbeats(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        # the hang watchdog observes the beat loop: a worker_freeze
        # zombie (beats silently stop, process lives) shows as pending=1
        # with a frozen counter — the exact silent hang this source
        # exists to type. A fenced worker stops beating DELIBERATELY
        # (typed, observable via StaleWorkerError), so it reads idle.
        from . import diagnostics

        self._beat_source = "membership_beat_w%d" % self.worker_id
        diagnostics.register_source(
            self._beat_source,
            pending_fn=lambda: 0 if (self._stop.is_set() or self.fenced)
            else 1)
        self._thread = threading.Thread(
            target=self._beat_loop, daemon=True,
            name="kv-heartbeat-w%d" % self.worker_id)
        self._thread.start()
        return self

    def _interval(self):
        from . import config

        return float(config.get("MXT_HEARTBEAT_INTERVAL"))

    def _beat_loop(self):
        from . import diagnostics, resilience

        while not self._stop.wait(self._interval()):
            diagnostics.progress(self._beat_source)
            inj = resilience.fault_point()
            frz = inj.rule("worker_freeze")
            if frz is not None \
                    and int(frz.get("worker", -1)) == self.worker_id \
                    and self._beats >= int(frz.get("after", 0)) \
                    and inj.should("worker_freeze"):
                # the zombie scenario: beats stop but the process (and
                # its data connection) lives on — the reaper must fence
                self.frozen = True
                return
            self._beats += 1
            if inj.should("hb_drop"):
                continue  # beat lost on the wire
            gen = self.generation
            try:
                self.heartbeat_now()
            except StaleWorkerError:
                if self.generation != gen:
                    # a concurrent re-registration replaced our
                    # credentials while this beat was in flight — the
                    # NEW generation is live, keep beating under it
                    continue
                # fenced (declared dead or replaced): stop beating — a
                # zombie must NOT auto-rejoin; rejoin is explicit
                self.fenced = True
                return
            except (MXNetError, ConnectionError, OSError):
                pass  # server unreachable this beat; keep trying

    # -- elastic rendezvous ------------------------------------------------
    def _deadline(self):
        from . import config

        t = config.get("MXT_BARRIER_TIMEOUT")
        return float(t if t is not None else config.get("MXT_KV_DEADLINE"))

    def barrier(self, tag, timeout=None):
        """Barrier over LIVE members (dead peers are excluded by the
        server). Raises KVStoreError on deadline instead of hanging.
        The transport deadline is the rendezvous timeout plus
        ``_RENDEZVOUS_MARGIN`` so the server's typed release/timeout
        reply beats the client-side retry."""
        timeout = self._deadline() if timeout is None else float(timeout)
        return self._rendezvous_client().request(
            "barrier", None, (self.worker_id, self.generation, tag,
                              timeout),
            deadline=timeout + _RENDEZVOUS_MARGIN)

    def reduce(self, key, seq, array, timeout=None):
        """Elastic sum-reduction; returns (sum, contributor_ids)."""
        timeout = self._deadline() if timeout is None else float(timeout)
        return self._rendezvous_client().request(
            "reduce", key, (self.worker_id, self.generation, seq,
                            np.asarray(array), timeout),
            deadline=timeout + _RENDEZVOUS_MARGIN)

    def members(self):
        """Current server-side membership view."""
        return self._ctl.request("members")

    def wait_for_world(self, n, timeout=None):
        """Block until ``n`` live members are registered (bounded poll).
        Registration is a rendezvous — like ps-lite's ADD_NODE barrier:
        the elastic live-member semantics (degrade over survivors) only
        apply AFTER the world has formed, otherwise an early worker's
        first reduce would release solo before its peers even register.
        Raises :class:`BarrierTimeout` when the world never forms."""
        timeout = self._deadline() if timeout is None else float(timeout)
        deadline = time.monotonic() + timeout
        while True:
            view = self.members()
            if len(view["members"]) >= n:
                self.epoch = view["epoch"]
                self.lost_total = view["lost_total"]
                return view
            if time.monotonic() >= deadline:
                raise BarrierTimeout(
                    "membership world never formed: %d/%d workers "
                    "registered within %.1fs (%s)"
                    % (len(view["members"]), n, timeout,
                       sorted(view["members"])))
            time.sleep(0.02)

    # -- teardown ----------------------------------------------------------
    def stop(self, deregister=True):
        """Stop the heartbeat thread; optionally leave gracefully (a
        deregistered worker does not count as lost)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if getattr(self, "_beat_source", None) is not None:
            from . import diagnostics

            diagnostics.unregister_source(self._beat_source)
        if deregister and self.generation is not None and not self.fenced:
            # best-effort, short-bounded: shrinking the control client's
            # connect timeout bounds the reconnect a dead coordinator
            # would otherwise spin for (the deadline alone only bounds
            # the retry loop, not the reconnect inside it)
            old_timeout = self._ctl._timeout
            self._ctl._timeout = min(old_timeout, _DEREGISTER_DEADLINE)
            try:
                self._ctl.request(
                    "deregister", None, (self.worker_id, self.generation),
                    deadline=_DEREGISTER_DEADLINE)
            except (MXNetError, ConnectionError, OSError):
                pass
            finally:
                self._ctl._timeout = old_timeout
        if self._rdv is not None:
            self._rdv.close()
        self._ctl.close()
