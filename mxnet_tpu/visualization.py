"""Network visualization (ref: python/mxnet/visualization.py —
print_summary + plot_network).

``print_summary`` is pure text (always available); ``plot_network``
returns a graphviz Digraph when the ``graphviz`` package is installed and
raises ImportError otherwise, exactly like the reference.
"""
from __future__ import annotations

import json

from .base import MXNetError

__all__ = ["print_summary", "plot_network"]


def _graph_nodes(symbol):
    conf = json.loads(symbol.tojson())
    return conf["nodes"], set(conf["arg_nodes"]), conf["heads"]


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Layer-table summary with output shapes and parameter counts
    (ref: visualization.print_summary)."""
    positions = positions or [0.44, 0.64, 0.74, 1.0]
    nodes, arg_nodes, _ = _graph_nodes(symbol)

    shape_dict = {}
    if shape is not None:
        arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**shape)
        names = symbol.list_arguments()
        shape_dict.update(zip(names, arg_shapes))
        # per-node output shapes via an internal-output walk
        internals = symbol.get_internals()
        _, int_shapes, _ = internals.infer_shape_partial(**shape)
        for name, s in zip(internals.list_outputs(), int_shapes):
            shape_dict[name] = s

    positions = [int(line_length * p) for p in positions]
    header = ["Layer (type)", "Output Shape", "Param #",
              "Previous Layer"]

    lines = ["_" * line_length]
    row = ""
    for fld, pos in zip(header, positions):
        row = (row + fld).ljust(pos)
    lines.append(row)
    lines.append("=" * line_length)

    total_params = 0
    for i, node in enumerate(nodes):
        if node["op"] == "null" and i in arg_nodes:
            continue
        name = node["name"]
        out_shape = shape_dict.get(name + "_output",
                                   shape_dict.get(name, ""))
        n_params = 0
        prevs = []
        data_names = set(shape or {})
        for inp in node.get("inputs", []):
            src = nodes[inp[0]]
            if src["op"] == "null":
                if src["name"] in data_names:
                    continue  # data inputs are not parameters
                s = shape_dict.get(src["name"])
                if s:
                    cnt = 1
                    for d in s:
                        cnt *= d
                    n_params += cnt
            else:
                prevs.append(src["name"])
        total_params += n_params
        row = ""
        for fld, pos in zip(["%s (%s)" % (name, node["op"]),
                             str(out_shape), str(n_params),
                             ", ".join(prevs)], positions):
            row = (row + str(fld)).ljust(pos)
        lines.append(row)
        lines.append("_" * line_length)
    lines.append("Total params: %d" % total_params)
    lines.append("_" * line_length)
    summary = "\n".join(lines)
    print(summary)
    return summary


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Graphviz rendering of the symbol graph
    (ref: visualization.plot_network)."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise ImportError(
            "plot_network requires the graphviz python package "
            "(matches reference behavior)") from e
    if not hasattr(symbol, "tojson"):
        raise MXNetError("plot_network expects a Symbol")
    nodes, arg_nodes, _ = _graph_nodes(symbol)
    node_attrs = dict({"shape": "box", "fixedsize": "false"},
                      **(node_attrs or {}))
    param_suffixes = ("_weight", "_bias", "_gamma", "_beta",
                     "_moving_mean", "_moving_var")

    def _hidden(i, node):
        return (node["op"] == "null" and i in arg_nodes and hide_weights
                and node["name"].endswith(param_suffixes))

    dot = Digraph(name=title, format=save_format)
    drawn = set()
    for i, node in enumerate(nodes):
        if _hidden(i, node):
            continue
        drawn.add(str(i))
        if node["op"] == "null":
            dot.node(str(i), node["name"], **dict(node_attrs,
                                                  shape="oval"))
        else:
            dot.node(str(i), "%s\n%s" % (node["name"], node["op"]),
                     **node_attrs)
    for i, node in enumerate(nodes):
        for inp in node.get("inputs", []):
            if str(inp[0]) in drawn and str(i) in drawn:
                dot.edge(str(inp[0]), str(i))
    return dot
