"""Consistent-hash placement for sharded embedding tables.

``row_id -> virtual node -> live server``: every live embedding server
owns ``vnodes`` points on a 64-bit ring; a row lands on the first vnode
clockwise of its hash. The classic properties this buys the fleet
(ref: ps-lite's key-range partitioner is the static ancestor; consistent
hashing is its elastic replacement):

- **stability** — adding/removing one server remaps only ~1/N of the
  rows (the rest keep their owner, so their server-side optimizer state
  stays put);
- **balance** — vnodes smooth per-server load to within a few percent;
- **determinism** — the mapping is a pure function of (sorted server
  ids, row id), so every worker computes identical placement with no
  coordination beyond the live-member view it hashes from.

Hashes are ``blake2b`` (stable across processes and Python runs, unlike
``hash()`` under PYTHONHASHSEED).
"""
from __future__ import annotations

import bisect
import hashlib
import struct

import numpy as np

from ..base import MXNetError

__all__ = ["HashRing", "stable_hash"]


def stable_hash(data):
    """64-bit process-stable hash of bytes/str/int."""
    if isinstance(data, int):
        data = struct.pack("!q", data)
    elif isinstance(data, str):
        data = data.encode("utf-8")
    return struct.unpack(
        "!Q", hashlib.blake2b(data, digest_size=8).digest())[0]


class HashRing:
    """A rebuild-in-place consistent-hash ring over live server ids."""

    def __init__(self, vnodes=64):
        if vnodes < 1:
            raise MXNetError("HashRing needs at least 1 vnode per server")
        self._vnodes = int(vnodes)
        self._points = []   # sorted vnode hashes
        self._owners = []   # parallel: server id owning each vnode
        self._servers = ()
        self.epoch = 0      # membership epoch the ring was built from

    def rebuild(self, server_ids, epoch=None):
        """Recompute the ring for the given live server set. Sorted input
        makes the ring a pure function of the member set, so every
        worker that sees the same membership view routes identically."""
        servers = tuple(sorted(server_ids, key=str))
        pts = []
        for sid in servers:
            for v in range(self._vnodes):
                pts.append((stable_hash("%s#%d" % (sid, v)), sid))
        pts.sort()
        self._points = [h for h, _ in pts]
        self._owners = [s for _, s in pts]
        self._servers = servers
        if epoch is not None:
            self.epoch = int(epoch)
        return self

    @property
    def servers(self):
        return self._servers

    def __len__(self):
        return len(self._servers)

    def owner(self, row_id):
        """Server id owning one row."""
        if not self._points:
            raise MXNetError("hash ring is empty — no live embedding "
                             "servers (rebuild from the membership view)")
        i = bisect.bisect_right(self._points, stable_hash(int(row_id)))
        return self._owners[i % len(self._owners)]

    def route(self, row_ids):
        """Batch placement: ``{server_id: positions}`` where positions
        index into ``row_ids`` (host-side metadata — row routing is
        control plane, never a device read). One entry per DESTINATION
        server, so a caller issues at most one RPC per server
        regardless of batch size."""
        ids = np.asarray(row_ids, dtype=np.int64).ravel()  # sync-ok: row routing is host metadata (control plane)
        out = {}
        for pos, rid in enumerate(ids):
            out.setdefault(self.owner(int(rid)), []).append(pos)
        return {sid: np.asarray(p, dtype=np.int64)  # sync-ok: host position metadata
                for sid, p in out.items()}
