"""Distributed sparse embedding parameter server (ROADMAP item 4).

The rec-sys scenario: terabyte-class ``row_sparse`` embedding tables
that cannot be replicated into device HBM. Tables shard across a server
fleet by consistent hashing over the membership view (hashing.py),
workers pull only the rows a batch touches through a hot-row device
cache (cache.py) and push only gradient rows — applied server-side with
the real sparse optimizers (store.py, sparse.py kernels) — batched to
at most one RPC per server per op (client.py). Fencing extends PR 3's
monotone-generation design to row-granular sparse pushes, plus a ring-
epoch fence adopted when rows migrate in a reshard.

Gluon front door: ``gluon.nn.Embedding(sparse_grad=True)`` +
``gluon.Trainer(kvstore='dist_embedding')`` — the dense towers keep the
fused one-launch step; embedding lookups/updates flow through this
package (kvstore.py, gluon/trainer.py).
"""
from .hashing import HashRing, stable_hash
from .cache import HotRowCache
from .store import EmbeddingStore
from .client import (EmbeddingFleet, ShardedEmbedding,
                     LocalEmbeddingServer, local_fleet, start_local_server)

__all__ = [
    "HashRing", "stable_hash", "HotRowCache", "EmbeddingStore",
    "EmbeddingFleet", "ShardedEmbedding", "LocalEmbeddingServer",
    "local_fleet", "start_local_server",
]
