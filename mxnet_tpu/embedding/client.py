"""Client side of the distributed sparse embedding parameter server.

:class:`EmbeddingFleet` owns the connections to the embedding servers,
the consistent-hash ring (rebuilt from the coordinator's membership
view, so servers can join/leave), and this worker's fencing credentials
at every server. :class:`ShardedEmbedding` is one table's view over a
fleet: sparse row pull with the hot-row device cache in front
(read-through on miss), sparse row push applying the SERVER-side sparse
optimizer (the reply's updated rows write back into the cache), both
batched per destination server — one lookup or update is at most one
RPC per live server regardless of batch size (the ps-lite
``PullRowSparse`` contract).

Elasticity: a server that stops answering is marked dead locally, the
ring is rebuilt over the survivors from the refreshed membership view,
and the affected rows re-route — missing rows on the inheriting server
are re-seeded from the worker's ``recover`` source (the dense mirror
that gluon.Trainer keeps) via ``emb_load``, which also hands the new
owner the current ring epoch so gradients delayed from before the
reshard are refused typed (store.py). A rejoining server re-registers
with the coordinator (fresh endpoint in its membership meta) and is
folded back into the ring on the next refresh.
"""
from __future__ import annotations

import pickle
import threading
import time

import numpy as np

from ..base import MXNetError
from ..membership import StaleWorkerError, WorkerMembership
from ..resilience import KVStoreError
from .hashing import HashRing
from .store import EmbeddingStore

__all__ = ["EmbeddingFleet", "ShardedEmbedding", "LocalEmbeddingServer",
           "local_fleet", "start_local_server", "bucket_rows"]


def bucket_rows(n):
    """Next power of two ≥ n (min 1) — the row-count shape bucket.

    Every per-step device program in the sparse path (the client's
    duplicate-id segment-sum, the pull scatter/gathers, the server's
    compact sparse apply) takes a DATA-DEPENDENT unique-row count;
    unbucketed, a zipf draw mints a fresh XLA program almost every step
    (PERF.md measured ~320 compiles over 8 bench steps — both A/B legs
    were compile-bound). Padding the row axis to pow2 buckets bounds
    the program count at log2(batch) per op, the ``tuning.paged_key``
    discipline applied to the embedding fleet."""
    n = max(1, int(n))
    p = 1
    while p < n:
        p <<= 1
    return p

# how a transport-dead server surfaces from AsyncClient.request
_DEAD_ERRORS = (KVStoreError, ConnectionError, OSError)

_STALE_EPOCH = "stale ring epoch"
_NO_TABLE = "does not exist on this server"


def _server_member_id(index):
    """Embedding servers register in the coordinator's membership table
    under a negative id namespace (training workers own the
    non-negative ints)."""
    return -(int(index) + 1)


class EmbeddingFleet:
    """Connections + ring + credentials for one worker's view of the
    embedding server fleet."""

    def __init__(self, endpoints=None, coordinator=None, vnodes=64,
                 timeout=None, heartbeats=True):
        from .. import config

        # static seed endpoints: {server_id: (host, port)}; the
        # membership view (server registrations carrying endpoint meta)
        # overrides these whenever it knows better
        self._static = dict(endpoints or {})
        if coordinator is None:
            if not self._static:
                raise MXNetError(
                    "EmbeddingFleet needs endpoints or a coordinator")
            coordinator = self._static[sorted(self._static)[0]]
        self.coordinator = tuple(coordinator)
        self._timeout = float(timeout if timeout is not None  # sync-ok: host config scalar
                              else config.get("MXT_KV_DEADLINE"))
        self._heartbeats = bool(heartbeats)
        self._endpoints = dict(self._static)
        self._clients = {}     # server_id -> AsyncClient (data plane)
        self._members = {}     # server_id -> WorkerMembership (this worker)
        self._dead = {}        # server_id -> endpoint observed dead
        self._coord_client = None
        self._lock = threading.RLock()
        self.ring = HashRing(vnodes=vnodes)
        self.epoch = 0
        self.worker_id = None
        self._opt_blob = None  # last shipped optimizer (new-server reship)
        self._tables = []      # ShardedEmbedding registry (re-init heal)

    @classmethod
    def from_spec(cls, spec, **kw):
        """Build from an ``MXT_EMBEDDING_SERVERS``-style string:
        ``host:port,host:port`` — server ids are list positions."""
        endpoints = {}
        for i, item in enumerate(s for s in spec.split(",") if s.strip()):
            host, _, port = item.strip().rpartition(":")
            endpoints[i] = (host, int(port))
        return cls(endpoints=endpoints, **kw)

    # -- membership / ring -------------------------------------------------
    def _coordinator_client(self):
        from ..async_server import AsyncClient

        if self._coord_client is None:
            self._coord_client = AsyncClient(
                self.coordinator[0], self.coordinator[1],
                timeout=self._timeout)
        return self._coord_client

    def refresh(self):
        """Rebuild the ring from the coordinator's live-member view.
        Registered embedding servers (negative-id members with endpoint
        meta) take precedence; without any, the static endpoint list is
        the fleet (minus servers this worker observed dead)."""
        try:
            view = self._coordinator_client().request("members")
        except _DEAD_ERRORS:
            view = None
        live = {}
        epoch = self.epoch
        if view is not None:
            epoch = int(view.get("epoch", self.epoch))
            meta = view.get("meta", {})
            for wid in view.get("members", {}):
                m = meta.get(wid)
                if isinstance(m, dict) and m.get("embedding_server"):
                    live[int(m.get("index", wid))] = (m["host"],
                                                      int(m["port"]))
        if not live:
            live = {sid: ep for sid, ep in self._static.items()
                    if self._dead.get(sid) != ep}
        with self._lock:
            # a server that re-registered at a NEW endpoint is alive
            # again; one the coordinator lists at the endpoint this
            # worker saw die stays dead until it moves
            for sid, ep in list(live.items()):
                if self._dead.get(sid) == ep:
                    del live[sid]
                elif sid in self._dead:
                    del self._dead[sid]
            joined = set(live) - set(self._endpoints) | {
                sid for sid, ep in live.items()
                if self._endpoints.get(sid) != ep}
            for sid in set(self._endpoints) - set(live) | joined:
                self._drop_client(sid)
            self._endpoints = live
            self.epoch = epoch
            self.ring.rebuild(sorted(live), epoch=epoch)
        for sid in sorted(joined):
            self._on_server_joined(sid)
        return self.ring

    def _on_server_joined(self, sid):
        """A (re)joined server starts from whatever its snapshot held:
        re-ship the optimizer, let each table re-create itself, and
        re-seed the rows this worker trained that now map to it — its
        snapshot predates the kill, so rows updated on the survivors
        while it was away would otherwise resurrect stale from the
        shard file."""
        if self._opt_blob is not None:
            try:
                self.request(sid, "emb_set_optimizer", None, self._opt_blob)
            except _DEAD_ERRORS:
                return
        for table in list(self._tables):
            table.ensure_table(sid)
            table.reseed_touched(sid)

    def live_servers(self):
        with self._lock:
            return sorted(self._endpoints)

    def mark_dead(self, sid):
        """This worker observed the server dead (transport failure):
        drop it locally and rebuild over the survivors, then fold in
        whatever the coordinator knows."""
        with self._lock:
            ep = self._endpoints.pop(sid, None)
            if ep is not None:
                self._dead[sid] = ep
            self._drop_client(sid)
            self.ring.rebuild(sorted(self._endpoints), epoch=self.epoch)
        from .. import diagnostics

        diagnostics.record_event("embedding_server_dead", server=sid,
                                 survivors=len(self._endpoints))
        self.refresh()

    def _drop_client(self, sid):
        cl = self._clients.pop(sid, None)
        if cl is not None:
            cl.close()
        wm = self._members.pop(sid, None)
        if wm is not None:
            try:
                wm.stop(deregister=False)
            except Exception:  # noqa: BLE001 — teardown best effort
                pass

    # -- credentials -------------------------------------------------------
    def register_worker(self, worker_id):
        """Register this worker with every live embedding server: each
        hands back a fencing generation that stamps all data frames
        (PR 3 semantics, now covering sparse row pushes)."""
        self.worker_id = int(worker_id)
        for sid in self.live_servers():
            self._ensure_registered(sid)
        return self

    def _ensure_registered(self, sid):
        if self.worker_id is None or sid in self._members:
            return
        host, port = self._endpoints[sid]
        wm = WorkerMembership(host, port, self.worker_id,
                              timeout=self._timeout)
        wm.register()
        if self._heartbeats:
            wm.start_heartbeats()
        self._members[sid] = wm
        cl = self._clients.get(sid)
        if cl is not None:
            cl.set_credentials(wm.worker_id, wm.generation)

    # -- data plane --------------------------------------------------------
    def client(self, sid):
        from ..async_server import AsyncClient

        with self._lock:
            cl = self._clients.get(sid)
            if cl is None:
                if sid not in self._endpoints:
                    raise KVStoreError(
                        "embedding server %r is not in the live fleet"
                        % (sid,))
                host, port = self._endpoints[sid]
                cl = self._clients[sid] = AsyncClient(
                    host, port, timeout=self._timeout)
        self._ensure_registered(sid)
        wm = self._members.get(sid)
        if wm is not None and wm.generation is not None:
            cl.set_credentials(wm.worker_id, wm.generation)
        return cl

    def request(self, sid, op, key=None, payload=None):
        return self.client(sid).request(op, key, payload)

    def scatter(self, requests):
        """Issue ``{server_id: (op, key, payload)}`` concurrently (one
        thread per destination beyond the first — each server has its
        own connection, so fan-out overlaps server-side work). Returns
        ``{server_id: result_or_exception}``; transport and typed
        errors come back as values so the caller can heal per server."""
        out = {}

        def run(sid, req):
            try:
                out[sid] = self.request(sid, *req)
            except (MXNetError,) + _DEAD_ERRORS as e:
                out[sid] = e

        items = list(requests.items())
        threads = [threading.Thread(target=run, args=item, daemon=True)
                   for item in items[1:]]
        for t in threads:
            t.start()
        if items:
            run(*items[0])
        for t in threads:
            t.join()
        return out

    # -- fleet-wide control ------------------------------------------------
    def set_optimizer(self, optimizer):
        """Ship the optimizer to every live server (the server applies
        sparse updates with it). ``param_dict`` is stripped — parameter
        objects (and their device buffers) must not ride to the fleet;
        per-key multipliers travel via ``lr_mult``/``wd_mult``."""
        pd, optimizer.param_dict = optimizer.param_dict, {}
        try:
            self._opt_blob = pickle.dumps(optimizer)
        finally:
            optimizer.param_dict = pd
        for sid in self.live_servers():
            self.request(sid, "emb_set_optimizer", None, self._opt_blob)

    def snapshot(self):
        """Ask every live server to persist its shard; returns
        {server_id: path}."""
        return {sid: self.request(sid, "emb_snapshot")
                for sid in self.live_servers()}

    def _register_table(self, table):
        if table not in self._tables:
            self._tables.append(table)

    def close(self):
        with self._lock:
            for sid in list(self._clients):
                self._drop_client(sid)
            if self._coord_client is not None:
                self._coord_client.close()
                self._coord_client = None


class ShardedEmbedding:
    """One embedding table sharded across the fleet, with the hot-row
    device cache in front of pulls and the write-back path behind
    pushes."""

    def __init__(self, fleet, key, shape, dtype="float32", cache_rows=None,
                 recover=None):
        from .. import config
        from .cache import HotRowCache

        self.fleet = fleet
        self.key = key
        self.shape = tuple(int(s) for s in shape)
        self.dtype = np.dtype(dtype)
        self._row_shape = self.shape[1:]
        self._dim = int(np.prod(self._row_shape)) if self._row_shape else 1
        if cache_rows is None:
            cache_rows = int(config.get("MXT_EMBEDDING_CACHE_ROWS"))
        cache_rows = min(int(cache_rows), self.shape[0])
        self.cache = HotRowCache("emb:%s" % key, cache_rows, self._dim,
                                 dtype=dtype) if cache_rows > 0 else None
        # recover(ids) -> rows: the worker-side source of truth used to
        # re-seed rows a surviving server inherited without state (the
        # gluon path wires the dense mirror buffer here)
        self.recover = recover
        self._lazy = None      # (seed, scale) when lazily initialized
        self._attempts = 4     # heal rounds per op (remap/refresh/retry)
        # ids this worker has pushed: the dirty set re-seeded onto a
        # REJOINING server, whose snapshot predates its death — without
        # this, rows updated on the survivors while it was away would
        # map back to it and resurrect stale
        self._touched = set()
        fleet._register_table(self)

    # -- initialization ----------------------------------------------------
    def init(self, dense):
        """Scatter initial rows to their owning servers (one emb_init
        RPC per server). ``dense`` is the full initial value — use
        :meth:`init_lazy` for tables too big to materialize anywhere."""
        vals = np.asarray(  # sync-ok: network serialization of init rows
            dense.asnumpy() if hasattr(dense, "asnumpy") else dense,  # sync-ok: network serialization of init rows (one-time)
            dtype=self.dtype)
        if vals.shape != self.shape:
            raise MXNetError("init shape %s != table shape %s"
                             % (vals.shape, self.shape))
        ids = np.arange(self.shape[0], dtype=np.int64)
        routed = self.fleet.ring.route(ids)
        reqs = {sid: ("emb_init", self.key,
                      (self.shape, str(self.dtype), ids[pos], vals[pos],
                       self.fleet.epoch))
                for sid, pos in routed.items()}
        self._raise_failures(self.fleet.scatter(reqs), "emb_init")
        return self

    def init_lazy(self, seed=0, scale=0.01):
        """Declare the table everywhere without materializing a single
        row: servers generate rows deterministically from (seed, row_id)
        on first touch — the ≥10×-HBM configuration."""
        self._lazy = (int(seed), float(scale))  # sync-ok: host config scalars
        for sid in self.fleet.live_servers():
            self.ensure_table(sid)
        return self

    def ensure_table(self, sid):
        """Idempotently (re)create this table's spec on one server — the
        heal path when a fresh server joins the ring."""
        try:
            if self._lazy is not None:
                self.fleet.request(
                    sid, "emb_init_lazy", self.key,
                    (self.shape, str(self.dtype), self._lazy[0],
                     self._lazy[1], self.fleet.epoch))
            else:
                self.fleet.request(
                    sid, "emb_init", self.key,
                    (self.shape, str(self.dtype),
                     np.zeros((0,), np.int64),
                     np.zeros((0, self._dim), self.dtype),
                     self.fleet.epoch))
        except _DEAD_ERRORS:
            pass

    def reseed_touched(self, sid):
        """Force-load this worker's trained rows that the (re)joined
        server now owns: emb_load installs current values AND the
        current ring epoch (fencing pre-rejoin gradients). Rows this
        worker never pushed are unchanged since init/snapshot, so the
        server's own restore is authoritative for them."""
        if self.recover is None or not self._touched:
            return
        ids = np.asarray(sorted(self._touched),  # sync-ok: host id metadata
                         dtype=np.int64)
        mine = self.fleet.ring.route(ids).get(sid)
        if mine is None or not len(mine):
            return
        rows = np.asarray(  # sync-ok: rejoin re-seed serialization (cold path)
            self.recover(ids[mine]), dtype=self.dtype).reshape(
                len(mine), -1)
        try:
            self.fleet.request(sid, "emb_load", self.key,
                               (ids[mine], rows, self.fleet.epoch))
        except _DEAD_ERRORS:
            pass

    @staticmethod
    def _raise_failures(results, what):
        for sid, r in results.items():
            if isinstance(r, BaseException):
                raise MXNetError("%s failed on embedding server %r: %s"
                                 % (what, sid, r)) from r

    # -- pull (read-through cache) ----------------------------------------
    def pull(self, row_ids):
        """Rows for ``row_ids`` (duplicates fine) as ONE device array of
        shape ``ids.shape + row_shape``. Cache hits gather on device;
        misses are fetched batched per owning server and inserted."""
        import jax.numpy as jnp
        from .. import telemetry

        t0 = time.perf_counter()
        ids = np.asarray(  # sync-ok: row ids are host metadata (control plane)
            row_ids.asnumpy() if hasattr(row_ids, "asnumpy") else row_ids,  # sync-ok: row ids are host metadata (control plane)
            dtype=np.int64)
        flat = ids.ravel()
        uids, inverse = np.unique(flat, return_inverse=True)
        # every device shape below is padded to a pow2 row bucket so a
        # varying unique/hit/miss count replays a compiled program
        # instead of minting a new one (out-of-range pad positions are
        # dropped by the scatters)
        ub = bucket_rows(len(uids))
        out = jnp.zeros((ub, self._dim), dtype=str(self.dtype))
        if self.cache is not None:
            hit_pos, hit_slots, miss_pos = self.cache.lookup(uids)
            if len(hit_pos):
                hb = bucket_rows(len(hit_pos))
                pos = np.full((hb,), ub, np.int64)  # pad -> dropped
                pos[:len(hit_pos)] = hit_pos
                slots = np.zeros((hb,), np.int64)
                slots[:len(hit_slots)] = hit_slots
                out = out.at[jnp.asarray(pos)].set(
                    self.cache.gather(slots), mode="drop")
        else:
            miss_pos = np.arange(len(uids), dtype=np.int64)
        if len(miss_pos):
            fetched = self._fetch(uids[miss_pos])
            mb = bucket_rows(len(miss_pos))
            pos = np.full((mb,), ub, np.int64)  # pad -> dropped
            pos[:len(miss_pos)] = miss_pos
            rows = np.zeros((mb, self._dim), fetched.dtype)
            rows[:len(miss_pos)] = fetched
            out = out.at[jnp.asarray(pos)].set(
                jnp.asarray(rows, dtype=out.dtype), mode="drop")
            if self.cache is not None:
                self.cache.insert(uids[miss_pos], fetched)
        telemetry.record_embedding_pull(time.perf_counter() - t0)
        return out[jnp.asarray(inverse)].reshape(
            tuple(ids.shape) + self._row_shape)

    def _fetch(self, miss_ids):
        """Server fetch of one unique-id batch, with remap/heal rounds:
        returns rows aligned to ``miss_ids``."""
        from .. import telemetry

        rows = np.zeros((len(miss_ids), self._dim), dtype=self.dtype)
        filled = np.zeros(len(miss_ids), dtype=bool)
        pending = np.arange(len(miss_ids), dtype=np.int64)
        for _ in range(self._attempts):
            if not len(pending):
                break
            routed = self.fleet.ring.route(miss_ids[pending])
            results = self.fleet.scatter(
                {sid: ("emb_pull", self.key,
                       (miss_ids[pending][pos], self.fleet.epoch))
                 for sid, pos in routed.items()})
            mp = miss_ids[pending]  # sorted: unique ids keep their order
            retry = []
            for sid, r in results.items():
                if isinstance(r, BaseException):
                    retry.extend(self._heal(sid, r, mp[routed[sid]]))
                    continue
                found, vals, missing = r
                if len(found):
                    vals = np.asarray(vals,  # sync-ok: RPC reply rows are already host bytes
                                      dtype=self.dtype).reshape(len(found),
                                                                -1)
                    telemetry.record_embedding_rpc("emb_pull", vals.nbytes)
                    # vectorized reply decode: found ⊆ mp and mp is
                    # sorted, so one searchsorted aligns every reply
                    # row (the per-row python dict walk was a measured
                    # per-step cost that DOUBLED with the server count)
                    found = np.asarray(found, dtype=np.int64)  # sync-ok: reply ids are host metadata
                    at = pending[np.searchsorted(mp, found)]
                    rows[at] = vals
                    filled[at] = True
                else:
                    telemetry.record_embedding_rpc("emb_pull", 0)
                if len(missing):
                    retry.extend(self._reseed(sid, np.asarray(missing)))  # sync-ok: RPC reply ids are host metadata
            pending = np.flatnonzero(~filled).astype(np.int64)  # sync-ok: host position metadata
            if len(pending) and not retry:
                # nothing healed this round — don't spin
                break
        if len(pending):
            raise MXNetError(
                "embedding pull could not resolve %d row(s) of table %r "
                "(ids %s...) — rows lost with no recover source"
                % (len(pending), self.key,
                   miss_ids[pending][:4].tolist()))
        return rows

    # -- push (server-side optimizer + write-back) ------------------------
    def push(self, row_ids, grad_rows):
        """Apply gradient rows server-side. Duplicate ids are combined
        (sum) on device first; one RPC per owning server; the reply's
        updated row values write back into the hot cache."""
        import jax
        import jax.numpy as jnp
        from .. import telemetry

        ids = np.asarray(  # sync-ok: row ids are host metadata (control plane)
            row_ids.asnumpy() if hasattr(row_ids, "asnumpy") else row_ids,  # sync-ok: row ids are host metadata (control plane)
            dtype=np.int64).ravel()
        vals = grad_rows.data if hasattr(grad_rows, "data") else grad_rows
        vals = jnp.asarray(vals).reshape(len(ids), self._dim)
        uids, inverse = np.unique(ids, return_inverse=True)
        # duplicate-id combine on device, into a pow2-bucketed segment
        # count: the unique count is data-dependent, and an unbucketed
        # num_segments recompiled this op (and everything downstream)
        # nearly every step. Also aligns grads to uids ORDER always —
        # the dup-free path used to ship original-order rows against
        # sorted unique ids.
        ub = bucket_rows(len(uids))
        vals = jax.ops.segment_sum(vals, jnp.asarray(inverse),
                                   num_segments=ub)
        grads = np.asarray(  # sync-ok: network serialization of grad rows
            vals, dtype=np.float32)[:len(uids)]
        self._touched.update(int(i) for i in uids)
        pending = uids
        pgrads = grads
        for _ in range(self._attempts):
            if not len(pending):
                return self
            routed = self.fleet.ring.route(pending)
            results = self.fleet.scatter(
                {sid: ("emb_push", self.key,
                       (pending[pos], pgrads[pos], self.fleet.epoch))
                 for sid, pos in routed.items()})
            retry = []
            for sid, r in results.items():
                if isinstance(r, BaseException):
                    retry.extend(self._heal(sid, r, pending[routed[sid]]))
                    continue
                kids, new_rows, missing = r
                telemetry.record_embedding_rpc(
                    "emb_push",
                    int(pgrads[routed[sid]].nbytes))
                if len(kids) and self.cache is not None:
                    if new_rows is not None:
                        self.cache.insert(kids, np.asarray(  # sync-ok: RPC reply rows are already host bytes (cache write-back)
                            new_rows, dtype=self.dtype).reshape(
                                len(kids), -1))
                    else:
                        self.cache.invalidate(kids)
                if len(missing):
                    retry.extend(self._reseed(sid, np.asarray(missing)))  # sync-ok: RPC reply ids are host metadata
            if not retry:
                return self
            keep = {int(i) for i in retry}
            sel = np.asarray([p for p, i in enumerate(pending)  # sync-ok: host position metadata
                              if int(i) in keep], dtype=np.int64)
            pending, pgrads = pending[sel], pgrads[sel]
        raise MXNetError(
            "embedding push could not apply %d row(s) of table %r after "
            "%d heal rounds" % (len(pending), self.key, self._attempts))

    # -- healing -----------------------------------------------------------
    def _heal(self, sid, err, ids):
        """Per-server failure triage. Returns the row ids to retry (the
        next round re-routes them over the refreshed ring)."""
        if isinstance(err, StaleWorkerError):
            if _STALE_EPOCH in str(err):
                # this worker's ring is behind the server's adopted
                # reshard epoch: refresh and re-send
                self.fleet.refresh()
                return list(ids)
            raise err  # fenced generation: a zombie must NOT self-heal
        if isinstance(err, MXNetError) and _NO_TABLE in str(err):
            self.ensure_table(sid)
            return list(ids)
        if isinstance(err, _DEAD_ERRORS):
            self.fleet.mark_dead(sid)
            return list(ids)
        raise err

    def _reseed(self, sid, missing):
        """Rows the owning server does not hold (it inherited the hash
        range in a reshard, or restarted from a stale snapshot): re-seed
        them from the worker-side recover source via emb_load — which
        also hands the server the current ring epoch to adopt — then
        retry."""
        if self._lazy is not None or self.recover is None:
            # lazy tables materialize server-side; nothing to do here —
            # and without a recover source the rows are truly lost
            if self._lazy is not None:
                return []
            raise MXNetError(
                "embedding server %r does not hold rows %s of table %r "
                "and no recover source is attached (rows lost in a "
                "reshard?)" % (sid, missing[:4].tolist(), self.key))
        rows = np.asarray(  # sync-ok: recovery re-seed serialization
            self.recover(missing), dtype=self.dtype).reshape(
                len(missing), -1)
        from .. import diagnostics

        diagnostics.record_event("embedding_reseed", server=sid,
                                 table=str(self.key), rows=len(missing))
        try:
            self.fleet.request(sid, "emb_load", self.key,
                               (missing, rows, self.fleet.epoch))
        except _DEAD_ERRORS:
            self.fleet.mark_dead(sid)
        return list(missing)

    def rows_resident(self):
        return len(self.cache) if self.cache is not None else 0

    def close(self):
        if self.cache is not None:
            self.cache.close()
        if self in self.fleet._tables:
            self.fleet._tables.remove(self)


class LocalEmbeddingServer:
    """One in-process embedding server (tests, benches, single-host
    rigs): the async transport + an EmbeddingStore + its registration
    at the fleet coordinator."""

    def __init__(self, index, host, port, server, store, member=None):
        self.index = index
        self.host = host
        self.port = port
        self.server = server
        self.store = store
        self.member = member

    def register(self, coordinator, timeout=5.0):
        """Announce this server in the coordinator's membership table —
        the endpoint rides in the registration meta, which is what
        fleet.refresh() builds the ring from. ``timeout`` bounds every
        control RPC (including the deregister at close — a dead
        coordinator must not park teardown for the full transport
        deadline)."""
        self.member = WorkerMembership(coordinator[0], coordinator[1],
                                       _server_member_id(self.index),
                                       timeout=timeout)
        self.member.register(meta={
            "embedding_server": True, "index": self.index,
            "host": self.host, "port": self.port})
        self.member.start_heartbeats()
        return self

    def kill(self):
        """Ungraceful death: the socket goes away mid-conversation and
        heartbeats silently stop (no deregistration) — exactly what a
        SIGKILL looks like to the fleet."""
        if self.member is not None:
            self.member.stop(deregister=False)
        self.server.close()

    def close(self):
        """Graceful leave (deregisters from the coordinator)."""
        if self.member is not None:
            self.member.stop(deregister=True)
        self.server.close()


def start_local_server(index, coordinator=None, snapshot_dir=None,
                       timeout=5.0):
    """Spin one embedding server on an ephemeral loopback port."""
    from .. import async_server

    srv = async_server.AsyncParamServer("127.0.0.1", 0)
    port = srv._sock.getsockname()[1]
    store = EmbeddingStore(snapshot_dir=snapshot_dir, server_id=index)
    srv.attach_embedding(store)
    handle = LocalEmbeddingServer(index, "127.0.0.1", port, srv, store)
    if coordinator is not None:
        handle.register(coordinator, timeout=timeout)
    return handle


def local_fleet(n, snapshot_dir=None, worker_id=0, vnodes=64,
                timeout=None):
    """An in-process fleet of ``n`` embedding servers with server 0's
    membership table as the fleet coordinator. Returns
    ``(fleet, handles)`` — close the handles when done, NON-coordinator
    servers first (their graceful deregister needs server 0 alive)."""
    if n < 1:
        raise MXNetError("local_fleet needs at least one server")
    reg_timeout = 5.0 if timeout is None else float(timeout)  # sync-ok: host config scalar
    handles = [start_local_server(0, snapshot_dir=snapshot_dir)]
    coord = (handles[0].host, handles[0].port)
    handles[0].register(coord, timeout=reg_timeout)
    for i in range(1, n):
        handles.append(start_local_server(i, coordinator=coord,
                                          snapshot_dir=snapshot_dir,
                                          timeout=reg_timeout))
    fleet = EmbeddingFleet(coordinator=coord, vnodes=vnodes,
                           timeout=timeout)
    fleet.refresh()
    if worker_id is not None:
        fleet.register_worker(worker_id)
    return fleet, handles
