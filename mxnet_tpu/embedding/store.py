"""Server-side sharded embedding table store.

One :class:`EmbeddingStore` rides inside each async parameter server
(async_server.py dispatches every ``emb_*`` op here): it holds the rows
this server OWNS under the consistent-hash placement — never the full
table — plus their per-row optimizer state, and applies sparse pushes
with the real :class:`~..optimizer.Optimizer` object so server-side
updates bit-match the local ``update_on_kvstore`` path (the lazy
``sparse_sgd/adagrad/adam/ftrl_update`` kernels from sparse.py, with the
table-level update count driving Adam's bias correction exactly like
``Optimizer._update_count``).

Fencing (the PR 3 design extended to row-granular sparse pushes):

- frames reach :meth:`handle` only after the transport's membership
  credential check, so a fenced zombie's delayed gradient rows are
  refused with :class:`~..membership.StaleWorkerError` before any row
  is touched;
- every mutating frame additionally carries the sender's *ring epoch*
  (the membership epoch its hash ring was built from). When a server
  inherits rows during a reshard (``emb_load``) it adopts that epoch as
  the table's minimum — a push stamped from before the reshard is
  refused typed instead of applying a stale gradient to migrated rows
  (the rendezvous-sequence adoption of ``_adopt_rendezvous_seqs``, for
  rows).

Durability: ``snapshot_dir`` makes the shard restartable — rows, state,
update counts, adopted epochs and the optimizer all round-trip through
one pickle under a CRC manifest (the membership snapshot idiom), so a
killed server rejoins the fleet with its shard intact.
"""
from __future__ import annotations

import os
import pickle
import threading
import zlib

import numpy as np

from ..base import MXNetError
from ..membership import StaleWorkerError

__all__ = ["EmbeddingStore"]

_MUTATING_OPS = frozenset((
    "emb_init", "emb_init_lazy", "emb_load", "emb_push",
    "emb_set_optimizer"))


def _lazy_row(seed, row_id, row_shape, scale, dtype):
    """Deterministic on-demand row materialization: the full table never
    exists anywhere — a row is a pure function of (seed, row_id), so any
    server (or a rejoining one) regenerates identical cold rows."""
    rng = np.random.RandomState((int(seed) * 1000003 + int(row_id))
                                % (2 ** 32))
    return rng.normal(0.0, scale, size=row_shape).astype(dtype)


class _Table:
    __slots__ = ("shape", "dtype", "rows", "state", "lazy", "min_epoch",
                 "nleaves")

    def __init__(self, shape, dtype, lazy=None):
        self.shape = tuple(int(s) for s in shape)
        self.dtype = np.dtype(dtype)
        self.rows = {}     # row_id -> np.ndarray(shape[1:])
        self.state = {}    # row_id -> [np.ndarray(shape[1:]), ...]
        self.lazy = lazy   # (seed, scale) or None
        self.min_epoch = 0
        self.nleaves = None  # optimizer state leaves per row (lazy probe)

    @property
    def row_shape(self):
        return self.shape[1:]


class EmbeddingStore:
    """The rows one embedding server owns, plus their optimizer state."""

    def __init__(self, snapshot_dir=None, server_id=None):
        self._lock = threading.Lock()
        self._tables = {}       # key -> _Table
        self._optimizer = None
        self._counts = {}       # key -> table-level update count (Adam t)
        self.server_id = server_id
        self.snapshot_dir = snapshot_dir
        if snapshot_dir:
            self._load_snapshot()

    # -- dispatch ----------------------------------------------------------
    def handle(self, op, key, payload):
        """One ``emb_*`` request -> one reply tuple. The transport has
        already validated the membership credential; epoch fencing for
        mutations happens here."""
        with self._lock:
            if op == "emb_set_optimizer":
                opt = pickle.loads(payload)
                if not getattr(opt, "sparse_capable", False):
                    raise MXNetError(
                        "optimizer %s has no row_sparse update path; "
                        "embedding servers need sgd/adam/adagrad/ftrl"
                        % type(opt).__name__)
                if getattr(opt, "multi_precision", False):
                    raise MXNetError(
                        "multi_precision optimizers are not supported "
                        "server-side (row state is kept at table dtype)")
                self._optimizer = opt
                for t in self._tables.values():
                    t.nleaves = None  # re-probe the state layout
                return ("ok", None)
            if op == "emb_init":
                return self._init(key, payload)
            if op == "emb_init_lazy":
                return self._init_lazy(key, payload)
            if op == "emb_load":
                return self._load(key, payload)
            if op == "emb_push":
                return self._push(key, payload)
            if op == "emb_pull":
                return self._pull(key, payload)
            if op == "emb_info":
                return ("ok", self._info())
            if op == "emb_snapshot":
                return ("ok", self._save_snapshot())
        raise MXNetError("unknown embedding op %r" % (op,))

    # -- tables ------------------------------------------------------------
    def _table(self, key, shape=None, dtype="float32", lazy=None):
        t = self._tables.get(key)
        if t is None:
            if shape is None:
                raise MXNetError(
                    "embedding table %r does not exist on this server — "
                    "init it first" % (key,))
            t = self._tables[key] = _Table(shape, dtype, lazy=lazy)
        return t

    def _init(self, key, payload):
        shape, dtype, ids, rows, epoch = payload
        t = self._table(key, shape, dtype)
        rows = np.asarray(rows, dtype=t.dtype)  # sync-ok: server-side shard storage is host memory by design
        for i, rid in enumerate(np.asarray(ids, dtype=np.int64)):  # sync-ok: host id metadata
            # first writer wins, like the dense server's 'init'
            t.rows.setdefault(int(rid), np.array(rows[i]))
        del epoch  # init may come from any epoch; fencing starts at load
        return ("ok", len(t.rows))

    def _init_lazy(self, key, payload):
        shape, dtype, seed, scale, epoch = payload
        del epoch
        self._table(key, shape, dtype,
                    lazy=(int(seed), float(scale)))  # sync-ok: host config scalars
        return ("ok", None)

    def _materialize(self, t, rid):
        row = t.rows.get(rid)
        if row is None and t.lazy is not None:
            seed, scale = t.lazy
            row = t.rows[rid] = _lazy_row(seed, rid, t.row_shape, scale,
                                          t.dtype)
        return row

    def _check_epoch(self, t, key, epoch):
        if int(epoch) < t.min_epoch:
            raise StaleWorkerError(
                "stale ring epoch %d for embedding table %r (server "
                "adopted epoch %d when it inherited rows in a reshard) "
                "— refresh the ring and re-send" %
                (int(epoch), key, t.min_epoch))

    def _load(self, key, payload):
        """Force-install rows (reshard migration / operator restore).
        Adopts the sender's ring epoch and update count, so gradients
        delayed from before the reshard are fenced from here on."""
        if len(payload) == 3:
            (ids, rows, epoch), num_update = payload, None
        else:
            ids, rows, epoch, num_update = payload
        t = self._tables.get(key)
        if t is None:
            raise MXNetError("emb_load before init for table %r" % (key,))
        rows = np.asarray(rows, dtype=t.dtype)  # sync-ok: server-side shard storage is host memory by design
        for i, rid in enumerate(np.asarray(ids, dtype=np.int64)):  # sync-ok: host id metadata
            rid = int(rid)
            t.rows[rid] = np.array(rows[i])
            # migrated rows arrive without optimizer state: like a
            # checkpoint resume without states, their slots restart cold
            t.state.pop(rid, None)
        t.min_epoch = max(t.min_epoch, int(epoch))
        if num_update is not None:
            self._counts[key] = max(self._counts.get(key, 0),
                                    int(num_update))
        return ("ok", len(t.rows))

    # -- pull --------------------------------------------------------------
    def _pull(self, key, payload):
        ids, epoch = payload
        del epoch  # reads are never fenced (matches dense pull)
        t = self._tables.get(key)
        if t is None:
            return ("ok", (np.zeros((0,), np.int64), None,
                           np.asarray(ids, dtype=np.int64)))  # sync-ok: host id metadata
        found, rows, missing = [], [], []
        for rid in np.asarray(ids, dtype=np.int64):  # sync-ok: host id metadata
            rid = int(rid)
            row = self._materialize(t, rid)
            if row is None:
                missing.append(rid)
            else:
                found.append(rid)
                rows.append(row)
        return ("ok", (np.asarray(found, dtype=np.int64),  # sync-ok: reply serialization (host bytes)
                       np.stack(rows).astype(t.dtype) if rows else None,
                       np.asarray(missing, dtype=np.int64)))  # sync-ok: reply serialization (host bytes)

    # -- push --------------------------------------------------------------
    def _push(self, key, payload):
        """Apply one worker's gradient rows with the server-side sparse
        optimizer. Reply carries the UPDATED row values (the client's
        hot-row cache writes them back) plus any ids this server does
        not own a row for (the client recovers those)."""
        ids, grads, epoch = payload
        t = self._tables.get(key)
        if t is None:
            raise MXNetError("emb_push before init for table %r" % (key,))
        self._check_epoch(t, key, epoch)
        ids = np.asarray(ids, dtype=np.int64)  # sync-ok: host id metadata
        grads = np.asarray(grads)  # sync-ok: frame payload is already host bytes
        known, missing = [], []
        for pos, rid in enumerate(ids):
            rid = int(rid)
            if self._materialize(t, rid) is None:
                missing.append(rid)
            else:
                known.append(pos)
        if not known:
            return ("ok", (np.zeros((0,), np.int64), None,
                           np.asarray(missing, dtype=np.int64)))  # sync-ok: reply serialization (host bytes)
        kpos = np.asarray(known, dtype=np.int64)  # sync-ok: host position metadata
        kids = ids[kpos]
        new_rows = self._apply(t, key, kids, grads[kpos])
        for i, rid in enumerate(kids):
            t.rows[int(rid)] = np.array(new_rows[i])
        return ("ok", (kids, new_rows,
                       np.asarray(missing, dtype=np.int64)))  # sync-ok: reply serialization (host bytes)

    def _state_layout(self, t, key):
        """Probe the optimizer's per-row state structure once per table
        (None / single array / tuple — all leaves are row-shaped for the
        sparse-capable optimizers)."""
        if t.nleaves is not None:
            return t.nleaves
        if self._optimizer is None:
            t.nleaves = 0
            return 0
        from ..ndarray.ndarray import NDArray
        import jax.numpy as jnp

        probe = self._optimizer.create_state(
            key, NDArray(jnp.zeros((1,) + t.row_shape, t.dtype)))
        if probe is None:
            t.nleaves = 0
        elif isinstance(probe, tuple):
            t.nleaves = len(probe)
        else:
            t.nleaves = 1
        return t.nleaves

    @staticmethod
    def _bucket_rows(n):
        """Next power of two ≥ n — the server-side half of the sparse
        row-count shape buckets (embedding/client.py bucket_rows): the
        sparse-optimizer kernels dispatch per (k, row) shape, and an
        unbucketed data-dependent k recompiled them nearly every push."""
        n = max(1, int(n))
        p = 1
        while p < n:
            p <<= 1
        return p

    def _apply(self, t, key, kids, grad_rows):
        """Run the optimizer over COMPACT (k, *row) arrays: gather the
        touched rows + their state, wrap as NDArrays, and drive the real
        ``Optimizer.update_multi_precision`` with a row_sparse gradient
        whose indices are ``arange(k)`` — identical arithmetic to the
        local update_on_kvstore path applying the same rows out of the
        full table, including the table-level Adam bias-correction
        count. The row axis pads to a pow2 bucket (zero rows + zero
        grads + zero state — every sparse kernel is row-wise, so pad
        rows never touch real ones) and results slice back."""
        opt = self._optimizer
        if opt is None:
            # replace semantics, matching the dense server's no-updater
            # push (CopyFromTo(merged, &local))
            return np.asarray(grad_rows, dtype=t.dtype)  # sync-ok: frame payload is already host bytes
        from ..ndarray.ndarray import NDArray
        from ..sparse import RowSparseNDArray
        import jax.numpy as jnp

        k = len(kids)
        kb = self._bucket_rows(k)
        cshape = (kb,) + t.row_shape
        wrows = np.zeros(cshape, t.dtype)
        wrows[:k] = np.stack([t.rows[int(r)] for r in kids])
        w = NDArray(jnp.asarray(wrows))
        nleaves = self._state_layout(t, key)
        leaves = []
        for li in range(nleaves):
            srows = np.zeros(cshape, t.dtype)
            srows[:k] = np.stack(
                [t.state[int(r)][li] if int(r) in t.state
                 else np.zeros(t.row_shape, t.dtype) for r in kids])
            leaves.append(NDArray(jnp.asarray(srows)))
        state = None if nleaves == 0 else \
            (leaves[0] if nleaves == 1 else tuple(leaves))
        grows = np.zeros(cshape, np.float32)
        grows[:k] = np.asarray(grad_rows, dtype=np.float32)  # sync-ok: frame payload is already host bytes
        grad = RowSparseNDArray(
            jnp.asarray(grows), jnp.arange(kb, dtype=jnp.int64), cshape)
        # resume the table-level update count (snapshot/load adoption)
        prev = self._counts.get(key)
        if prev is not None and \
                opt._index_update_count.get(key, -1) < prev:
            opt._index_update_count[key] = prev
        opt.update_multi_precision(key, w, grad, state)
        self._counts[key] = opt._index_update_count.get(key, 0)
        new_rows = np.asarray(w.data).astype(t.dtype)[:k]  # sync-ok: server-side shard storage is host memory by design
        if nleaves:
            leaf_np = [np.asarray(l.data) for l in leaves]  # sync-ok: server-side shard storage is host memory by design
            for i, rid in enumerate(kids):
                t.state[int(rid)] = [np.array(l[i]) for l in leaf_np]
        return new_rows

    # -- views / durability ------------------------------------------------
    def _info(self):
        return {key: {"rows": len(t.rows), "shape": t.shape,
                      "min_epoch": t.min_epoch, "lazy": t.lazy is not None,
                      "num_update": self._counts.get(key, 0)}
                for key, t in self._tables.items()}

    def info(self):
        with self._lock:
            return self._info()

    def rows_resident(self):
        with self._lock:
            return sum(len(t.rows) for t in self._tables.values())

    def _snapshot_path(self):
        name = "emb_shard_%s.pkl" % (self.server_id
                                     if self.server_id is not None
                                     else "srv")
        return os.path.join(self.snapshot_dir, name)

    def _save_snapshot(self):
        """Persist the shard (rows + state + counts + epochs + the
        optimizer) under a CRC manifest; returns the path (None without
        a snapshot_dir)."""
        if not self.snapshot_dir:
            return None
        payload = pickle.dumps({
            "tables": {
                key: {"shape": t.shape, "dtype": str(t.dtype),
                      "rows": t.rows, "state": t.state, "lazy": t.lazy,
                      "min_epoch": t.min_epoch}
                for key, t in self._tables.items()},
            "counts": dict(self._counts),
            "optimizer": pickle.dumps(self._optimizer)
            if self._optimizer is not None else None,
        }, protocol=pickle.HIGHEST_PROTOCOL)
        os.makedirs(self.snapshot_dir, exist_ok=True)
        path = self._snapshot_path()
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(np.uint32(zlib.crc32(payload) & 0xFFFFFFFF).tobytes())
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        from .. import diagnostics

        diagnostics.record_event("embedding_snapshot", server=self.server_id,
                                 path=path,
                                 rows=sum(len(t.rows)
                                          for t in self._tables.values()))
        return path

    def save_snapshot(self):
        with self._lock:
            return self._save_snapshot()

    def _load_snapshot(self):
        path = self._snapshot_path()
        if not os.path.exists(path):
            return False
        with open(path, "rb") as f:
            crc = int(np.frombuffer(f.read(4), np.uint32)[0])
            payload = f.read()
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            raise MXNetError(
                "embedding shard snapshot %s failed CRC verification "
                "(corrupt file)" % path)
        data = pickle.loads(payload)
        for key, td in data["tables"].items():
            t = _Table(td["shape"], td["dtype"], lazy=td["lazy"])
            t.rows = td["rows"]
            t.state = td["state"]
            t.min_epoch = td["min_epoch"]
            self._tables[key] = t
        self._counts = dict(data["counts"])
        if data.get("optimizer") is not None:
            self._optimizer = pickle.loads(data["optimizer"])
        return True
