"""Hot-row device cache for sharded embedding tables.

An LRU cache of embedding rows resident in device HBM: the full table
lives sharded across the server fleet (terabyte-class in the rec-sys
scenario), and the TPU holds only the working set. Reads are
read-through (a miss batch is fetched from the owning servers and
inserted); pushes write back the server-updated row values so the next
lookup of a just-trained row is a device-side hit instead of a refetch.

Accounting: the backing buffer registers as the ``hot_row_cache`` pool
in the diagnostics HBM ledger (sized from shape metadata — never a
device read), and every lookup feeds
``mxt_embedding_cache_{hits,misses,evictions}_total`` plus the
``mxt_embedding_cache_hit_ratio`` / ``mxt_embedding_rows_resident``
gauges that `mxt_top`'s embedding section renders.

Host-side bookkeeping (id->slot map, LRU order) is pure metadata; row
VALUES move only device-to-device (`buf[slots]`, `.at[slots].set`).
"""
from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np

from ..base import MXNetError

__all__ = ["HotRowCache"]

_POOL = "hot_row_cache"


def _metrics():
    from .. import telemetry

    hits = telemetry.counter(
        "mxt_embedding_cache_hits_total",
        "Hot-row cache lookups served from device HBM.", ("table",))
    misses = telemetry.counter(
        "mxt_embedding_cache_misses_total",
        "Hot-row cache lookups that went to the server fleet.",
        ("table",))
    evict = telemetry.counter(
        "mxt_embedding_cache_evictions_total",
        "Rows evicted from the hot-row cache (LRU).", ("table",))
    ratio = telemetry.gauge(
        "mxt_embedding_cache_hit_ratio",
        "Lifetime hot-row cache hit ratio per table.", ("table",))
    resident = telemetry.gauge(
        "mxt_embedding_rows_resident",
        "Embedding rows currently resident in the device cache.",
        ("table",))
    return hits, misses, evict, ratio, resident


class HotRowCache:
    """Fixed-capacity LRU over one table's rows, backed by a single
    preallocated ``(capacity, dim)`` device buffer."""

    def __init__(self, name, capacity, dim, dtype="float32"):
        import jax.numpy as jnp

        if capacity < 1:
            raise MXNetError("hot-row cache capacity must be >= 1")
        self.name = str(name)
        self.capacity = int(capacity)
        self.dim = int(dim)
        self._buf = jnp.zeros((self.capacity, self.dim), dtype=dtype)
        self._slot = {}              # row_id -> slot
        self._lru = OrderedDict()    # row_id -> None, oldest first
        self._free = list(range(self.capacity))
        self._lock = threading.Lock()
        self._hits = 0
        self._misses = 0
        (self._c_hits, self._c_miss, self._c_evict,
         self._g_ratio, self._g_resident) = _metrics()
        from .. import diagnostics

        diagnostics.hbm_set(_POOL, self.name,
                            self.capacity * self.dim
                            * np.dtype(dtype).itemsize)

    # -- bookkeeping -------------------------------------------------------
    def __len__(self):
        with self._lock:
            return len(self._slot)

    @property
    def hit_ratio(self):
        total = self._hits + self._misses
        return self._hits / total if total else 0.0

    def _publish(self):
        self._g_ratio.labels(self.name).set(self.hit_ratio)
        self._g_resident.labels(self.name).set(len(self._slot))

    # -- lookup / fill -----------------------------------------------------
    def lookup(self, row_ids):
        """Split a unique-id batch into hits and misses.

        Returns ``(hit_pos, hit_slots, miss_pos)`` — positions index
        into ``row_ids``; ``hit_slots`` are rows of the device buffer
        (gather with :meth:`gather`). Hits refresh LRU recency."""
        hit_pos, hit_slots, miss_pos = [], [], []
        with self._lock:
            for pos, rid in enumerate(np.asarray(row_ids,  # sync-ok: host id metadata (cache keys, not device values)
                                                 dtype=np.int64).ravel()):
                rid = int(rid)
                slot = self._slot.get(rid)
                if slot is None:
                    miss_pos.append(pos)
                else:
                    hit_pos.append(pos)
                    hit_slots.append(slot)
                    self._lru.move_to_end(rid)
            self._hits += len(hit_pos)
            self._misses += len(miss_pos)
        if hit_pos:
            self._c_hits.labels(self.name).inc(len(hit_pos))
        if miss_pos:
            self._c_miss.labels(self.name).inc(len(miss_pos))
        self._publish()
        return (np.asarray(hit_pos, dtype=np.int64),  # sync-ok: host position metadata
                np.asarray(hit_slots, dtype=np.int64),  # sync-ok: host slot metadata
                np.asarray(miss_pos, dtype=np.int64))  # sync-ok: host position metadata

    def gather(self, slots):
        """Device gather of cached rows (no host transfer)."""
        import jax.numpy as jnp

        return self._buf[jnp.asarray(np.asarray(slots, dtype=np.int64))]  # sync-ok: slot indices are host metadata; the gather itself stays on device

    def insert(self, row_ids, rows):
        """Install rows (device or host values) for the given unique ids,
        evicting LRU rows when capacity binds. Also the write-back path:
        a pushed row's server-updated value lands here so the next
        lookup hits."""
        import jax.numpy as jnp

        ids = [int(r) for r in np.asarray(row_ids, dtype=np.int64).ravel()]  # sync-ok: host id metadata (cache keys)
        if not ids:
            return
        if len(ids) > self.capacity:
            # keep only the tail (the most recent capacity-many ids):
            # inserting more than capacity would immediately self-evict
            rows = rows[len(ids) - self.capacity:]
            ids = ids[len(ids) - self.capacity:]
        evicted = 0
        slots = []
        with self._lock:
            for rid in ids:
                slot = self._slot.get(rid)
                if slot is None:
                    if self._free:
                        slot = self._free.pop()
                    else:
                        old, _ = self._lru.popitem(last=False)
                        slot = self._slot.pop(old)
                        evicted += 1
                    self._slot[rid] = slot
                self._lru[rid] = None
                self._lru.move_to_end(rid)
                slots.append(slot)
        # pow2 row bucket: the insert count is data-dependent (miss
        # batches, push write-backs), and an unbucketed scatter shape
        # recompiled per step; pad slots out of range (dropped) and
        # rows with zeros. Padding happens host-side — both callers
        # (server fetch, push-reply write-back) hand rows that are
        # already host bytes off the RPC reply.
        from .client import bucket_rows as _bucket

        nb = _bucket(len(slots))
        pslots = np.full((nb,), self.capacity, np.int64)
        pslots[:len(slots)] = slots
        prows = np.zeros((nb, self.dim), dtype=str(self._buf.dtype))
        prows[:len(slots)] = np.asarray(rows)  # sync-ok: RPC reply rows are already host bytes
        self._buf = self._buf.at[jnp.asarray(pslots)].set(
            jnp.asarray(prows), mode="drop")
        if evicted:
            self._c_evict.labels(self.name).inc(evicted)
        self._publish()

    def invalidate(self, row_ids=None):
        """Drop rows (all rows when ``row_ids`` is None) — the fallback
        when a push cannot write back (e.g. the server reply carried no
        updated values)."""
        with self._lock:
            if row_ids is None:
                self._slot.clear()
                self._lru.clear()
                self._free = list(range(self.capacity))
            else:
                for rid in np.asarray(row_ids, dtype=np.int64).ravel():  # sync-ok: host id metadata (cache keys)
                    slot = self._slot.pop(int(rid), None)
                    if slot is not None:
                        self._lru.pop(int(rid), None)
                        self._free.append(slot)
        self._publish()

    def close(self):
        from .. import diagnostics

        diagnostics.hbm_release(_POOL, self.name)
