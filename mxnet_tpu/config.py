"""Runtime configuration — the ``MXT_*`` env-var tier (SURVEY §5 config
tier 2; ref: docs/faq/env_var.md — ~80 MXNET_* vars read via dmlc::GetEnv
at use sites. Here every variable is DECLARED in one registry with type,
default, and doc, read via :func:`get`).

Variables whose reference meaning is owned by XLA/JAX (engine thread
counts, GPU memory pool knobs, exec bulking) have no analog — the XLA
runtime owns scheduling and memory. What remains meaningful on TPU is
declared below; ``describe()`` prints the table (the env_var.md analog).
"""
from __future__ import annotations

import os
from collections import namedtuple

from .base import MXNetError

__all__ = ["get", "set_default", "describe", "variables", "naive_engine",
           "is_set", "change_epoch"]

_Var = namedtuple("_Var", ["name", "type", "default", "doc"])

_REGISTRY = {}


def _declare(name, typ, default, doc):
    _REGISTRY[name] = _Var(name, typ, default, doc)


_declare("MXT_TEST_SEED", int, None,
         "Seed forced into @with_seed tests for exact repro "
         "(ref: MXNET_TEST_SEED).")
_declare("MXT_PROFILER_AUTOSTART", bool, False,
         "Start a jax.profiler trace at import "
         "(ref: MXNET_PROFILER_AUTOSTART).")
_declare("MXT_ENGINE_TYPE", str, "XLA",
         "'NaiveEngine' disables jit for op-by-op debugging "
         "(ref: MXNET_ENGINE_TYPE=NaiveEngine).")
_declare("MXT_DEFAULT_DTYPE", str, "float32",
         "Default dtype for creation ops without an explicit dtype.")
_declare("MXT_SAFE_ACCUMULATION", bool, True,
         "Accumulate bf16/f16 reductions in float32 "
         "(ref: MXNET_SAFE_ACCUMULATION).")
_declare("MXT_TEST_TPU", bool, False,
         "Enable the hardware test lane (pytest -m tpu).")
_declare("MXT_COORDINATOR", str, None,
         "jax.distributed coordinator address, set by tools/launch.py "
         "(ref: DMLC_PS_ROOT_URI/PORT).")
_declare("MXT_NUM_WORKERS", int, 1,
         "World size under tools/launch.py (ref: DMLC_NUM_WORKER).")
_declare("MXT_WORKER_ID", int, 0,
         "This process's rank under tools/launch.py "
         "(ref: DMLC_WORKER_ID).")
_declare("MXT_KVSTORE_BIGARRAY_BOUND", int, 1000000,
         "Size above which dist pushes chunk the array "
         "(ref: MXNET_KVSTORE_BIGARRAY_BOUND; advisory — XLA collectives "
         "handle chunking internally).")

_declare("MXT_FUSED_TRAINER", bool, True,
         "Fuse Trainer.step's per-parameter optimizer updates into ONE "
         "donated XLA launch when eligible (sgd/nag/adam/adamw, dense "
         "grads, no dist kvstore). 0 falls back to eager per-param "
         "updates.")

_declare("MXT_FUSED_STEP", bool, True,
         "Fuse the whole canonical Gluon train step (forward + backward + "
         "optimizer update) into ONE donated XLA launch via "
         "gluon.CachedTrainStep / Trainer.fuse_step, and fuse "
         "Module.update's per-param loop the same way. Eligibility mirrors "
         "MXT_FUSED_TRAINER (supported optimizer, dense grads, single "
         "process, no dist kvstore); 0 forces the eager "
         "record/backward/step path everywhere.")

_declare("MXT_RNN_WAVEFRONT", bool, False,
         "Run multi-layer unidirectional LSTM as a diagonal wavefront: "
         "all layers' recurrent gemms batch into one einsum per diagonal "
         "(serial chain T+L-1 instead of L*T). Off until measured on "
         "chip; numerics identical to the sequential path.")

_declare("MXT_RNN_UNROLL", int, None,
         "Unroll factor for the fused-RNN recurrent scan (0 disables "
         "unrolling; unset = auto: full unroll up to T=128, else 16). "
         "Unrolling amortizes per-iteration loop overhead on the TPU.")

_declare("MXT_KVSTORE_SECRET", str, None,
         "Shared secret authenticating dist_async parameter-server "
         "frames (HMAC-SHA256 over nonce|dir|seq|payload). Required for "
         "any non-loopback server bind; see async_server.py threat "
         "model.")

_declare("MXT_FLASH_BLOCK_Q", int, 128,
         "Flash-attention query block rows. Setting it (env or "
         "set_default) pins ALL shapes to this block — the A/B knob for "
         "the chip runbook; leave unset to let the tuning table pick a "
         "shape-aware config per call (tuning/autotune.py). Re-read on "
         "every kernel dispatch, so sweeps can change it without a "
         "fresh process.")
_declare("MXT_FLASH_BLOCK_K", int, 128,
         "Flash-attention key/value block rows (same pinning/override "
         "semantics as MXT_FLASH_BLOCK_Q).")

_declare("MXT_TUNE_TABLE", str, None,
         "Path of the persistent kernel-tuning table (tuning/table.py): "
         "per-(op, shape-bucket, dtype, device) block configs, "
         "XLA-vs-Pallas decisions, and recorded warmup shape "
         "signatures, as versioned JSON. Unset keeps the table "
         "in-memory only (decisions still cached for the process).")
_declare("MXT_TUNE_MODE", str, "auto",
         "Kernel autotuner policy (ref: MXNET_CUDNN_AUTOTUNE_DEFAULT): "
         "'auto' = timed micro-benchmarks on a real TPU, deterministic "
         "heuristic cost model elsewhere (CPU/CI); 'heuristic' = never "
         "measure; 'measure' = measure even off-TPU (tests/sweeps); "
         "'off' = bypass the tuning table entirely (legacy global "
         "MXT_FLASH_BLOCK_* / MXT_BN_PALLAS behavior).")
_declare("MXT_TUNE_ITERS", int, 10,
         "Timing iterations per candidate config in the autotuner's "
         "measurement loop.")

_declare("MXT_COMPILE_CACHE_DIR", str, None,
         "Directory for JAX's persistent compilation cache. When set, "
         "every XLA compile is cached on disk keyed by program+config, "
         "so a resumed trainer or fresh serving replica deserializes "
         "instead of recompiling (PERF.md: 63 s of attention JIT on a "
         "4-layer GPT until hand-caching). tuning.warmup() plus this "
         "cache = zero hot-path JIT in a warm-started process.")

_declare("MXT_BN_PALLAS", bool, False,
         "Use the fused Pallas BatchNorm backward on channel-last "
         "activations (ops/bn_pallas.py): both reductions in one joint "
         "read of (x, dy). Default off until chip-measured vs the XLA "
         "custom-VJP path (the A/B is staged in the recovery runbook).")

_declare("MXT_MAX_INFLIGHT", int, 2,
         "Depth of the async dispatch window (engine.py): the host may "
         "run up to K fused steps ahead of the device before a deferred "
         "host read (non-finite flag, step token) retires the oldest "
         "in-flight step. 1 = synchronous (one host read per step, the "
         "pre-async behavior); capped at 15 (the flag-mask width). "
         "engine.bulk/set_bulk_size override it per scope — the "
         "ThreadedEngine bulking knob made real.")

_declare("MXT_SKIP_NONFINITE", bool, False,
         "Skip the optimizer update (weights, optimizer state, step "
         "counter all untouched) whenever any gradient is non-finite. "
         "Eager Trainer.step/Module.update run one fused multi_all_finite "
         "check; the fused CachedTrainStep compiles the guard into its "
         "single launch via lax.cond (read when the fused program builds). "
         "Skips land in the 'skipped_nonfinite_steps' profiler counter.")

_declare("MXT_FAULT", str, None,
         "Deterministic fault injection (resilience.py), e.g. "
         "'kv_drop:p=0.5,seed=7,n=10;kv_delay:p=0.2,ms=5;"
         "ckpt_crash:at=manifest,n=1'. kv_drop/kv_delay hit kvstore "
         "network ops; ckpt_crash raises SimulatedCrash at a named "
         "CheckpointManager write phase (params|states|manifest|rotate); "
         "hb_drop loses membership heartbeats on the wire, "
         "worker_freeze:worker=I[,after=K] freezes worker I's heartbeat "
         "thread (zombie emulation), rejoin_race:ms=N widens the "
         "server-side re-registration fencing window; "
         "replica_kill:replica=I[,after=K] kills serving replica I at "
         "its Kth router tick (in-flight requests fail over), "
         "replica_slow:replica=I,ms=N[,after=K] stalls replica I's "
         "decode for N ms (hedge bait); "
         "data_host_kill:host=I[,after=K] kills host I's data-plane "
         "decode fleet at its Kth chunk-commit boundary (survivors "
         "steal its reclaimed chunks), "
         "data_worker_slow:host=I,ms=N slows host I's decode by N ms "
         "per chunk (steal bait); "
         "traffic_storm:rps=N,after=K[,tenant=T] flips the synthetic "
         "serving TrafficGenerator to N req/s after its Kth tick "
         "(optionally all attributed to tenant T) — the seeded flash "
         "crowd the autoscaler must absorb; "
         "replica_spawn_slow:ms=N makes every autoscaler-spawned spare "
         "take N ms extra to warm before it may go routable (the "
         "router must keep serving off the existing tier meanwhile); "
         "grad_spike:layer=N,after=K[,scale=S] multiplies layer N's "
         "gradient by S (default 1e4) ON DEVICE once the fused step's "
         "dispatch count passes K — the seeded anomaly the training-"
         "health detectors (health.py) must catch within one "
         "InflightWindow retirement.")

_declare("MXT_HEALTH", bool, False,
         "Training-health plane (health.py): the fused train step "
         "computes per-layer grad-norm / param-norm / update-ratio "
         "stats INSIDE its one donated launch and stages them into the "
         "async dispatch window, so K steps of stats cost the SAME one "
         "deferred read the engine already performs (syncs/step is "
         "bit-equal on vs off — bench training_health_ab asserts it). "
         "Host-side detectors run at window retirement: loss-spike "
         "(z-score vs EMA), grad-explosion/vanish, dead-layer. Read "
         "when the fused program builds, like MXT_SKIP_NONFINITE.")
_declare("MXT_HEALTH_SPIKE_Z", float, 6.0,
         "Loss-spike z-score threshold: |loss - EMA| > z * stddev "
         "(after the EMA warmup) fires a 'loss_spike' anomaly.")
_declare("MXT_HEALTH_EXPLODE", float, 1e3,
         "Per-layer gradient-norm ceiling: a grad L2 norm above this "
         "(or non-finite) fires a 'grad_explosion' anomaly.")
_declare("MXT_HEALTH_VANISH", float, 1e-8,
         "Per-layer gradient-norm floor: a grad L2 norm below this "
         "counts one vanish tick; MXT_HEALTH_DEAD_STEPS consecutive "
         "ticks fire a 'dead_layer' anomaly.")
_declare("MXT_HEALTH_DEAD_STEPS", int, 3,
         "Consecutive vanished-gradient steps before a layer is "
         "declared dead (health.py dead-layer detector).")
_declare("MXT_HEALTH_EMA_DECAY", float, 0.9,
         "EMA decay for the host-side loss mean/variance tracker the "
         "loss-spike detector compares against.")
_declare("MXT_HEALTH_GUARD_HOOK", bool, False,
         "Let health anomalies join the MXT_SKIP_NONFINITE guard "
         "bookkeeping: a grad_explosion anomaly also lands in the "
         "skipped_nonfinite_steps counter path (host bookkeeping only "
         "— numerics are NEVER touched by the detector; the on-device "
         "skip remains the guard's own lax.cond).")
_declare("MXT_HEALTH_SKEW_RATIO", float, 1.5,
         "Fleet skew-watch straggler threshold: slowest member step "
         "time / fleet median above this ratio reads as a straggler "
         "verdict (health.fleet_skew over the FleetCollector's merged "
         "registry).")
_declare("MXT_HEALTH_DIVERGENCE", float, 0.5,
         "Fleet skew-watch divergence threshold: a member grad-norm "
         "fingerprint differing from the fleet median by more than "
         "this relative fraction reads as numeric divergence (data-"
         "parallel replicas should see near-identical global grad "
         "norms).")
_declare("MXT_HEALTH_POSTMORTEM", bool, True,
         "Dump a diagnostics post-mortem on the FIRST health anomaly "
         "of each kind (per monitor) so the flight-recorder tail "
         "around the anomaly is preserved; 0 records events/counters "
         "only.")

_declare("MXT_MEMBERSHIP", bool, True,
         "Elastic membership for the dist kvstore (membership.py): "
         "workers register with the coordinator-side server, heartbeat "
         "on a background thread, and every data frame is fenced by "
         "(worker_id, generation) so a zombie or restarted-but-"
         "unregistered worker can never corrupt server state. 0 "
         "disables registration/fencing (pre-membership behavior).")
_declare("MXT_ELASTIC", bool, False,
         "Route dist_sync reductions through the membership server "
         "(kvstore 'reduce' rendezvous) instead of XLA collectives so "
         "sync mode DEGRADES over survivors when a worker dies instead "
         "of hanging in a collective. Opt-in: the collective path is "
         "faster but cannot drop a dead peer.")
_declare("MXT_MESH_SHAPE", str, None,
         "Comma-separated global mesh shape for no-arg "
         "parallel.make_mesh() calls (e.g. '16,2' for dp×tp, "
         "'2,1,2,2' for the full dp×tp×pp×ep; one -1 wildcard "
         "allowed). Exported per worker by tools/launch.py --mesh so "
         "the same training script scales from 1 host to N without "
         "code changes.")
_declare("MXT_MESH_AXES", str, None,
         "Comma-separated mesh axis names paired with MXT_MESH_SHAPE "
         "(default: 'data,model,pipe,expert' truncated to the shape's "
         "rank; dp/tp/pp/ep spellings are accepted wherever an axis "
         "role is resolved). Set by tools/launch.py --mesh-axes.")
_declare("MXT_ZERO_STAGE", int, None,
         "Default ZeRO weight-update sharding stage (0-3) for "
         "parallel.ShardedTrainStep when the constructor doesn't pass "
         "zero_stage (arXiv:2004.13336: 1 shards optimizer states over "
         "the data axis, 2 adds gradient reduce-scatter + sharded "
         "updates, 3 shards the params themselves FSDP-style). "
         "Exported by tools/launch.py --zero-stage.")
_declare("MXT_HEARTBEAT_INTERVAL", float, 2.0,
         "Seconds between membership heartbeats (membership.py; ref: "
         "ps-lite Van's heartbeat timer).")
_declare("MXT_LIVENESS_TIMEOUT", float, 10.0,
         "Seconds without a heartbeat before the membership reaper "
         "declares a worker dead, fences its generation, and bumps the "
         "membership epoch (lost_workers profiler counter).")
_declare("MXT_BARRIER_TIMEOUT", float, None,
         "Deadline in seconds for KVStore barriers (both the membership "
         "barrier and the jax.distributed sync path). Unset falls back "
         "to MXT_KV_DEADLINE; exceeding it raises KVStoreError instead "
         "of hanging on a peer that will never arrive. Rendezvous "
         "requests give the transport this window plus a small margin "
         "so the server's typed timeout reply beats the client-side "
         "retry (no duplicate waiters).")

_declare("MXT_KV_RETRIES", int, 4,
         "Max retries for a kvstore network op (dist push reduction, "
         "async client request) before raising KVStoreError.")
_declare("MXT_KV_RETRY_BASE", float, 0.05,
         "Base seconds for kvstore retry exponential backoff "
         "(base * 2^(attempt-1), plus jitter).")
_declare("MXT_KV_RETRY_MAX", float, 2.0,
         "Cap in seconds on a single kvstore retry backoff delay.")
_declare("MXT_KV_DEADLINE", float, 30.0,
         "Per-op deadline in seconds for kvstore network ops; exceeding "
         "it raises KVStoreError instead of hanging the worker.")

_declare("MXT_TELEMETRY_JSONL", str, None,
         "Path of the telemetry JSONL event/metric sink (telemetry.py): "
         "step-phase spans, RPC spans, and epoch metric snapshots append "
         "as JSON lines via a buffered writer thread; nd.waitall() and "
         "the estimator's epoch end flush it. Unset disables the sink "
         "(metrics registry stays live either way).")
_declare("MXT_TELEMETRY_PORT", int, None,
         "Serve telemetry.render_prometheus() on 127.0.0.1:<port> "
         "(stdlib HTTP, daemon thread, loopback only). tools/mxt_top.py "
         "tails it for a live console. Unset disables the endpoint; "
         "0 picks a free port (telemetry.http_port() reports it).")

_declare("MXT_PAGE_SIZE", int, 16,
         "Tokens per KV-cache page in the serving stack "
         "(serving/kv_cache.py). The ragged paged attention kernel "
         "streams one page per grid step, so this is also its KV block "
         "size; must be a multiple of 8 (TPU sublane).")
_declare("MXT_SERVING_PAGES", int, 256,
         "KV-cache pool size in pages preallocated per serving engine "
         "(one extra scratch page is always added for masked writes of "
         "inactive batch slots). HBM cost per layer is "
         "2 * pages * page_size * heads * head_dim * itemsize.")
_declare("MXT_SERVING_SLOTS", int, 8,
         "Decode batch slots in the serving engine: the continuous "
         "batcher recomposes requests into this fixed-shape batch every "
         "step, so the decode program compiles once regardless of "
         "traffic (inactive slots are masked, not reshaped away).")

_declare("MXT_FLEET_HEDGE_DELAY", float, None,
         "Hedge delay in seconds for the serving fleet router "
         "(serving/router.py): a dispatched request with no result "
         "after this long is speculatively duplicated onto a second "
         "replica — first completion wins, the loser is cancelled "
         "through the replica's eviction path. Unset derives the delay "
         "per request as half its deadline (or half the router's "
         "slo=); requests with neither never hedge.")
_declare("MXT_FLEET_HEDGE_BUDGET", int, None,
         "Max concurrently-hedged requests fleet-wide: bounds the "
         "extra load a brownout can recruit, so hedging can never "
         "double the fleet's work. 0 disables hedging; unset derives "
         "max(1, fleet slot capacity // 4).")

_declare("MXT_FLEET_PREFILL_THRESHOLD", int, 64,
         "Prompt length (tokens) at which the fleet router dispatches "
         "a request through the disaggregated prefill/decode handoff "
         "(serving/router.py): prefill on a prefill-role replica, KV "
         "pages shipped over the transport, adopted into a decode-role "
         "replica. Shorter prompts route straight to the decode tier; "
         "pools without both roles always dispatch directly.")

_declare("MXT_FLEET_SCRAPE_TIMEOUT", float, 5.0,
         "Per-member transport deadline in seconds for the fleet "
         "telemetry collector's tel_snapshot/tel_spans scrapes "
         "(telemetry_fleet.py): a dead or hung member costs at most "
         "this long and is then marked stale with its last-seen age — "
         "the collector never hangs on a member.")

_declare("MXT_FLEET_SCRAPE_INTERVAL", float, 2.0,
         "Background scrape period in seconds for "
         "telemetry_fleet.FleetCollector.start() — how often the "
         "collector refreshes membership and re-scrapes every member's "
         "registry and trace spans.")

_declare("MXT_AUTOSCALE_INTERVAL", float, 1.0,
         "Control-loop period in seconds for the serving fleet "
         "autoscaler's background thread (serving/autoscaler.py "
         "FleetAutoscaler.start()) — how often the merged fleet page "
         "is re-read and a scale decision considered.")
_declare("MXT_AUTOSCALE_COOLDOWN", float, 5.0,
         "Minimum seconds between autoscaler actuations in the SAME "
         "replica pool (and per attached worker fleet): after an "
         "up/down decision the loop observes only, so a scale-up's "
         "effect lands in the signals before the next decision — the "
         "anti-flap half of the hysteresis pair.")
_declare("MXT_AUTOSCALE_MIN_REPLICAS", int, 1,
         "Serving-replica floor: the autoscaler refuses typed "
         "(AutoscalerError) any decision or scale_to() that would drop "
         "the routable+warming population below this.")
_declare("MXT_AUTOSCALE_MAX_REPLICAS", int, 8,
         "Serving-replica ceiling: scale-up stops here; scale_to() "
         "above it refuses typed.")
_declare("MXT_AUTOSCALE_QUEUE_HIGH", float, 2.0,
         "Scale-up pressure threshold: queued requests (router backlog "
         "+ merged replica admission queues) >= this many per slot of "
         "fleet capacity reads as hot, as does p99 latency above the "
         "SLO.")
_declare("MXT_AUTOSCALE_OCC_LOW", float, 0.3,
         "Scale-down calm threshold: mean routable-replica occupancy "
         "at or below this fraction, with an empty queue and p99 "
         "within SLO, counts one calm tick.")
_declare("MXT_AUTOSCALE_CALM_TICKS", int, 3,
         "Consecutive calm observations required before the "
         "autoscaler shrinks by one replica — the hysteresis half that "
         "keeps a brief lull from draining capacity a flash crowd "
         "would immediately need back.")
_declare("MXT_AUTOSCALE_SLO", float, None,
         "Target p99 routed-request latency in seconds for the "
         "autoscaler's error signal when the FleetRouter has no slo= "
         "of its own. Unset means latency never reads as hot (queue "
         "pressure still scales).")

_declare("MXT_TENANT_QUOTA_REQUESTS", int, None,
         "Default per-tenant cap on OUTSTANDING requests (admitted, "
         "not yet finished) for serving QoS (serving/qos.py) when a "
         "tenant has no explicit TenantSpec. Unset means unlimited.")
_declare("MXT_TENANT_QUOTA_TOKENS", int, None,
         "Default per-tenant cap on outstanding token budget "
         "(prompt + max_new_tokens summed over in-flight requests). "
         "Unset means unlimited.")

_declare("MXT_WATCHDOG_TIMEOUT", float, None,
         "Hang-watchdog stall threshold in seconds (diagnostics.py): a "
         "progress source (engine window retires, KVStore RPC "
         "completions, membership heartbeats, the serving decode loop) "
         "with outstanding work and no counter movement for this long "
         "triggers a stall report (thread stacks + in-flight window "
         "state + flight-recorder tail + post-mortem file). Unset "
         "disables the watchdog; setting it also arms the post-mortem "
         "handlers at import.")
_declare("MXT_WATCHDOG_ACTION", str, "report",
         "What a watchdog stall does: 'report' keeps the process alive "
         "and re-reports every timeout window; 'abort' dumps the "
         "post-mortem then exits with diagnostics.WATCHDOG_EXIT_CODE "
         "(134) so tools/launch.py --respawn or the membership reaper "
         "can respawn the worker — a typed death instead of a silent "
         "hang.")
_declare("MXT_WATCHDOG_INTERVAL", float, None,
         "Watchdog check period in seconds (default: timeout/4, floor "
         "50 ms). Checks read host heartbeat counters only — never a "
         "device value.")
_declare("MXT_POSTMORTEM_DIR", str, ".",
         "Directory where diagnostics post-mortems "
         "(mxt-postmortem-<ts>.json: flight-recorder ring, thread "
         "stacks, window state, HBM ledger, goodput, config + metrics "
         "snapshots) are written on fatal signal, unhandled exception, "
         "watchdog stall, OOM, or demand.")
_declare("MXT_FLIGHT_RECORDER_SIZE", int, 2048,
         "Bounded ring capacity (events) of the diagnostics flight "
         "recorder. Every telemetry event — step spans, RPC spans, "
         "membership/reshard/checkpoint events — lands here; the tail "
         "rides every post-mortem and /debug/flightrecorder.")

_declare("MXT_AG_LEAN_TAPE", bool, False,
         "Skip storing per-node replay state (forward fn + primal "
         "inputs) on the autograd tape. Saves peak memory on very long "
         "eager recordings whose ops' vjp residuals don't already retain "
         "their inputs, at the cost of grad(create_graph=True) raising.")

_declare("MXT_DATA_WORKERS", int, 2,
         "Decode workers per host in the streaming data plane "
         "(data_plane/workers.py) — the ImageRecordIter "
         "preprocess_threads analog, pulling leased shard chunks "
         "instead of a shared cursor.")
_declare("MXT_DATA_BUFFER_BATCHES", int, 8,
         "Bounded decoded-batch buffer per host (the data plane's "
         "backpressure boundary): decode workers block when the "
         "consumer falls this many batches behind instead of growing "
         "host memory; resident bytes are accounted in the HBM "
         "ledger's 'prefetch' pool.")
_declare("MXT_DATA_CHUNK_RECORDS", int, 256,
         "Records per data-plane chunk — the unit of lease, steal, and "
         "batch formation (batches never cross a chunk, so keep this a "
         "multiple of the batch size). Smaller chunks steal/resume at "
         "finer grain; larger chunks read more sequentially.")
_declare("MXT_DATA_STEAL", bool, True,
         "Cross-host work stealing in the data plane: a host whose "
         "lease queue runs dry steals unleased chunks from the slowest "
         "peer (reclaimed dead-host chunks first). 0 pins every chunk "
         "to its original owner (a dead host's tail is then lost until "
         "it rejoins).")

_declare("MXT_EMBEDDING_SERVERS", str, None,
         "Comma-separated host:port list of a running sharded-embedding "
         "server fleet (embedding/). When unset, kvstore 'dist_embedding' "
         "spins MXT_EMBEDDING_LOCAL_SERVERS in-process servers instead.")
_declare("MXT_EMBEDDING_LOCAL_SERVERS", int, 1,
         "Size of the in-process embedding server fleet started by "
         "kvstore 'dist_embedding' when MXT_EMBEDDING_SERVERS is unset.")
_declare("MXT_EMBEDDING_CACHE_ROWS", int, 4096,
         "Hot-row device cache capacity (rows per embedding table) for "
         "the sharded embedding client; 0 disables the cache "
         "(every lookup goes to the fleet).")
_declare("MXT_EMBEDDING_SNAPSHOT_DIR", str, None,
         "Directory where embedding servers persist their shard "
         "(rows + optimizer state, CRC-manifested) and restore it from "
         "on restart.")

_overrides = {}
# bumped by set_default so value caches (e.g. the flash kernel's block
# memo) can notice a config change without re-reading every variable
_change_epoch = 0


def variables():
    return dict(_REGISTRY)


def change_epoch():
    """Monotone counter bumped by every set_default call — cheap staleness
    check for caches built over config values. Env-var mutations cannot be
    observed this way; callers that must honor them re-read via get()."""
    return _change_epoch


def is_set(name):
    """True when the variable has an explicit value (env var or
    set_default override) rather than its declared default — how the
    tuning layer tells 'user pinned this knob' from 'free to tune'."""
    if name not in _REGISTRY:
        raise MXNetError("unknown config variable %r" % (name,))
    return name in os.environ or name in _overrides


def _coerce(var, raw):
    if raw is None:
        return None
    if var.type is bool:
        return str(raw).lower() in ("1", "true", "yes", "on")
    try:
        return var.type(raw)
    except (TypeError, ValueError) as e:
        raise MXNetError("config %s expects %s, got %r"
                         % (var.name, var.type.__name__, raw)) from e


def get(name):
    """Typed value: env var > set_default override > declared default."""
    if name not in _REGISTRY:
        raise MXNetError("unknown config variable %r (declare it in "
                         "mxnet_tpu/config.py)" % (name,))
    var = _REGISTRY[name]
    raw = os.environ.get(name)
    if raw is not None:
        return _coerce(var, raw)
    if name in _overrides:
        return _overrides[name]
    return var.default


def set_default(name, value):
    """Process-level override (below env in precedence)."""
    global _change_epoch
    if name not in _REGISTRY:
        raise MXNetError("unknown config variable %r" % (name,))
    _overrides[name] = _coerce(_REGISTRY[name], value)
    _change_epoch += 1


def describe():
    """Human-readable table of every variable (env_var.md analog)."""
    lines = ["%-32s %-8s %-12s %s" % ("Variable", "Type", "Current",
                                      "Description")]
    for name in sorted(_REGISTRY):
        var = _REGISTRY[name]
        lines.append("%-32s %-8s %-12s %s"
                     % (name, var.type.__name__, get(name), var.doc))
    return "\n".join(lines)


class naive_engine:
    """Context manager: run ops one-by-one without jit — the debugging
    analog of MXNET_ENGINE_TYPE=NaiveEngine (SURVEY §5 race/debug
    posture)."""

    def __enter__(self):
        import jax
        self._ctx = jax.disable_jit()
        self._ctx.__enter__()
        return self

    def __exit__(self, *exc):
        return self._ctx.__exit__(*exc)
