"""NDArray — mutable tensor handle over immutable jax.Array.

Re-design of the reference NDArray (ref: include/mxnet/ndarray.h,
src/ndarray/ndarray.cc). The reference pairs each array with an engine
variable for async dependency tracking; here XLA's async dispatch plays the
ThreadedEngine, so the handle only needs to solve *mutation and aliasing*:

- the handle owns a swappable ``jax.Array`` (in-place ops rebind it);
- basic slicing returns a *view* holding (base, key): reads materialize
  ``base.data[key]`` lazily, writes funnel through ``base`` via ``.at[]`` —
  so view/base mutation stays coherent like the reference's shared Chunk;
- ``asnumpy``/``wait_to_read`` are the sync points; deferred XLA errors
  surface there (matching test_exc_handling semantics);
- autograd participation via ``_ag_node`` (see autograd.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError, get_dtype, dtype_name, numeric_types
from ..context import Context, current_context, cpu
from ..ops.registry import apply_op, get_op

__all__ = ["NDArray", "array", "empty", "zeros", "ones", "full", "arange",
           "linspace", "eye", "concatenate", "waitall", "save", "load",
           "zeros_like", "ones_like", "moveaxis", "_wrap_outputs"]


def _unwrap(x):
    return x.data if isinstance(x, NDArray) else x


def _leaf_type():
    from .. import autograd as ag

    return ag.AGLeaf


def _norm_key(key):
    """Normalize an index key; NDArray indices become jax arrays."""
    if isinstance(key, NDArray):
        return key.data.astype(jnp.int32)
    if isinstance(key, tuple):
        return tuple(_norm_key(k) for k in key)
    if isinstance(key, (list, np.ndarray)):
        return jnp.asarray(key)
    return key


def _is_basic_key(key):
    if isinstance(key, tuple):
        return all(_is_basic_key(k) for k in key)
    return isinstance(key, (int, np.integer, slice, type(None), type(Ellipsis)))


class NDArray:
    __slots__ = ("_data", "_base", "_key", "_grad", "_ag_node", "__weakref__")

    def __init__(self, data, ctx=None, dtype=None, _base=None, _key=None):
        self._base = _base
        self._key = _key
        self._grad = None
        self._ag_node = None
        if _base is not None:
            self._data = None
            return
        if isinstance(data, NDArray):
            data = data.data
        if not isinstance(data, jax.Array):
            if dtype is None and not isinstance(data, np.ndarray):
                # reference behavior: non-ndarray sources default to float32
                # (ndarray sources keep their dtype)
                npd = np.asarray(data).astype(np.float32)
            else:
                npd = np.asarray(data, dtype=get_dtype(dtype) if dtype else None)
            dev = (ctx or current_context()).jax_device
            data = jax.device_put(npd, dev)
        else:
            if dtype is not None and data.dtype != get_dtype(dtype):
                data = data.astype(get_dtype(dtype))
            if ctx is not None:
                dev = ctx.jax_device
                if data.device != dev:
                    data = jax.device_put(data, dev)
        self._data = data

    # -- storage protocol --------------------------------------------------
    @property
    def data(self):
        if self._base is None:
            return self._data
        return self._base.data[self._key]

    def _set_data(self, new):
        """Rebind the whole buffer (in-place op semantics)."""
        if self._base is None:
            self._data = new
        else:
            self._base._write(self._key, new)

    def _write(self, key, value):
        if self._base is None:
            self._data = self._data.at[key].set(value)
        else:
            sub = self.data.at[key].set(value)
            self._base._write(self._key, sub)

    # -- basic properties --------------------------------------------------
    @property
    def shape(self):
        return tuple(self.data.shape)

    @property
    def dtype(self):
        return np.dtype(self.data.dtype)

    @property
    def size(self):
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def ndim(self):
        return self.data.ndim

    @property
    def stype(self):
        return "default"

    @property
    def context(self):
        d = self.data.device
        try:
            platform = d.platform
        except AttributeError:  # sharded array: take first device
            d = list(self.data.devices())[0]
            platform = d.platform
        if platform == "cpu":
            return Context("cpu", d.id)
        return Context("tpu", d.id)

    ctx = context

    @property
    def grad(self):
        return self._grad

    @property
    def T(self):
        return apply_op("transpose", self)

    def __repr__(self):
        return "%s\n<NDArray %s @%s>" % (
            np.asarray(self.data),
            "x".join(str(s) for s in self.shape),
            self.context,
        )

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of 0-d NDArray")
        return self.shape[0]

    def __bool__(self):
        if self.size != 1:
            raise ValueError("ambiguous truth value of multi-element NDArray")
        _note_host_sync()
        return bool(np.asarray(self.data))

    def __float__(self):
        _note_host_sync()
        return float(np.asarray(self.data).reshape(())[()])

    def __int__(self):
        _note_host_sync()
        return int(np.asarray(self.data).reshape(())[()])

    def __index__(self):
        _note_host_sync()
        return int(np.asarray(self.data).reshape(())[()])

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # -- sync points -------------------------------------------------------
    # jax.block_until_ready returns before compute finishes on the axon
    # PJRT tunnel (measured: 10 chained 8k matmuls "ready" in 0.4 ms, real
    # completion 1.5 s) — only a host read truly waits. wait_to_read
    # therefore reads ONE element through a cached jitted pick, forcing the
    # producing computation to finish without transferring the array.
    def asnumpy(self):
        """Blocking copy to host (ref: MXNDArraySyncCopyToCPU — the sync
        point where deferred errors surface)."""
        _note_host_sync()
        return np.asarray(self.data)

    def asscalar(self):
        if self.size != 1:
            raise ValueError("the array is not scalar-sized")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def wait_to_read(self):
        d = self.data
        jax.block_until_ready(d)
        _device_sync(d)
        return self

    wait_to_write = wait_to_read

    # -- placement / dtype -------------------------------------------------
    def as_in_context(self, ctx):
        if ctx == self.context:
            return self
        return NDArray(jax.device_put(self.data, ctx.jax_device))

    as_in_ctx = as_in_context

    def copyto(self, other):
        if isinstance(other, NDArray):
            other._set_data(jax.device_put(self.data, other.data.device))
            return other
        if isinstance(other, Context):
            return NDArray(jax.device_put(self.data, other.jax_device))
        raise TypeError("copyto target must be NDArray or Context")

    def copy(self):
        return NDArray(jnp.copy(self.data))

    def astype(self, dtype, copy=True):
        dt = get_dtype(dtype)
        if not copy and self.dtype == dt:
            return self
        return NDArray(self.data.astype(dt))

    def tostype(self, stype):
        if stype == "default":
            return self
        from ..sparse import cast_storage

        return cast_storage(self, stype)

    def detach(self):
        out = NDArray(self.data)
        return out

    def attach_grad(self, grad_req="write", stype=None):
        """Make this array an autograd leaf (ref: ndarray.py attach_grad)."""
        del stype
        from .. import autograd as ag

        self._grad = NDArray(jnp.zeros(self.shape, self.dtype))
        self._ag_node = (ag.AGLeaf(self, grad_req), 0)

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd as ag

        ag.backward(self, out_grad, retain_graph=retain_graph,
                    train_mode=train_mode)

    # -- indexing ----------------------------------------------------------
    def __getitem__(self, key):
        from .. import autograd as ag

        nkey = _norm_key(key)
        if _is_basic_key(nkey) and not ag.is_recording():
            return NDArray(None, _base=self, _key=nkey)
        # recorded or advanced indexing → op (gradient flows)
        data = self.data[nkey] if not ag.is_recording() else None
        if data is not None:
            return NDArray(data)

        def _index_fn(x, _key=nkey):
            return x[_key]

        from ..ops.registry import Op

        return apply_op(Op("_getitem", _index_fn), self)

    def __setitem__(self, key, value):
        nkey = _norm_key(key)
        if isinstance(value, NDArray):
            value = value.data
        elif isinstance(value, numeric_types):
            value = jnp.asarray(value, self.dtype)
        else:
            value = jnp.asarray(value, self.dtype)
        self._write(nkey, value.astype(self.dtype))
        # mutation invalidates recorded op history, but an attach_grad leaf
        # stays a leaf (reference: params are initialized by slice-assign
        # after attach_grad and must still receive gradients)
        if self._ag_node is not None and not isinstance(
            self._ag_node[0], _leaf_type()
        ):
            self._ag_node = None

    # -- arithmetic --------------------------------------------------------
    def _binary(self, other, op_name, scalar_op, rscalar_op=None, reverse=False):
        if isinstance(other, NDArray):
            a, b = (other, self) if reverse else (self, other)
            return apply_op(op_name, a, b)
        if isinstance(other, numeric_types):
            name = (rscalar_op or scalar_op) if reverse else scalar_op
            return apply_op(name, self, scalar=float(other))
        if isinstance(other, np.ndarray):
            o = NDArray(other, dtype=self.dtype)
            a, b = (o, self) if reverse else (self, o)
            return apply_op(op_name, a, b)
        return NotImplemented

    def __add__(self, o):
        return self._binary(o, "broadcast_add", "_plus_scalar")

    def __radd__(self, o):
        return self._binary(o, "broadcast_add", "_plus_scalar", reverse=True)

    def __sub__(self, o):
        return self._binary(o, "broadcast_sub", "_minus_scalar", "_rminus_scalar")

    def __rsub__(self, o):
        return self._binary(o, "broadcast_sub", "_minus_scalar", "_rminus_scalar",
                            reverse=True)

    def __mul__(self, o):
        return self._binary(o, "broadcast_mul", "_mul_scalar")

    def __rmul__(self, o):
        return self._binary(o, "broadcast_mul", "_mul_scalar", reverse=True)

    def __truediv__(self, o):
        return self._binary(o, "broadcast_div", "_div_scalar", "_rdiv_scalar")

    def __rtruediv__(self, o):
        return self._binary(o, "broadcast_div", "_div_scalar", "_rdiv_scalar",
                            reverse=True)

    def __mod__(self, o):
        return self._binary(o, "broadcast_mod", "_mod_scalar", "_rmod_scalar")

    def __rmod__(self, o):
        return self._binary(o, "broadcast_mod", "_mod_scalar", "_rmod_scalar",
                            reverse=True)

    def __pow__(self, o):
        return self._binary(o, "broadcast_power", "_power_scalar", "_rpower_scalar")

    def __rpow__(self, o):
        return self._binary(o, "broadcast_power", "_power_scalar",
                            "_rpower_scalar", reverse=True)

    def __neg__(self):
        return apply_op("negative", self)

    def __abs__(self):
        return apply_op("abs", self)

    def __eq__(self, o):
        if o is None:
            return False
        return self._binary(o, "broadcast_equal", "_equal_scalar")

    def __ne__(self, o):
        if o is None:
            return True
        return self._binary(o, "broadcast_not_equal", "_not_equal_scalar")

    def __lt__(self, o):
        return self._binary(o, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, o):
        return self._binary(o, "broadcast_lesser_equal", "_lesser_equal_scalar")

    def __gt__(self, o):
        return self._binary(o, "broadcast_greater", "_greater_scalar")

    def __ge__(self, o):
        return self._binary(o, "broadcast_greater_equal", "_greater_equal_scalar")

    __hash__ = object.__hash__

    def _inplace(self, res):
        # keep autograd history coherent: the in-place result replaces both
        # the buffer and the recorded node (a dropped node would make
        # backward silently use the pre-mutation graph)
        self._set_data(res.data)
        if not isinstance(self._ag_node, tuple) or not isinstance(
            self._ag_node[0], _leaf_type()
        ):
            self._ag_node = res._ag_node
        return self

    def __iadd__(self, o):
        return self._inplace(self.__add__(o))

    def __isub__(self, o):
        return self._inplace(self.__sub__(o))

    def __imul__(self, o):
        return self._inplace(self.__mul__(o))

    def __itruediv__(self, o):
        return self._inplace(self.__truediv__(o))

    # -- op-method fallback ------------------------------------------------
    def __getattr__(self, name):
        # called only when normal lookup fails; route to registered ops so
        # x.relu(), x.sum(axis=1), x.reshape(...) etc. all work.
        try:
            op = get_op(name)
        except KeyError:
            raise AttributeError(
                "'NDArray' object has no attribute %r" % (name,)
            ) from None
        import functools

        return functools.partial(apply_op, op, self)

    # explicit methods whose names differ from op names or need sugar
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        if not shape and "shape" in kwargs:
            shape = tuple(kwargs.pop("shape"))
        return apply_op("reshape", self, shape=shape, **kwargs)

    def reshape_like(self, other):
        return apply_op("reshape", self, shape=other.shape)

    def broadcast_to(self, shape):
        return apply_op("broadcast_to", self, shape=tuple(shape))

    def broadcast_like(self, other):
        return apply_op("broadcast_like", self, other)

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return apply_op("transpose", self, axes=axes if axes else None)

    def astype_like(self, other):
        return self.astype(other.dtype)

    def dot(self, other, **kwargs):
        return apply_op("dot", self, other, **kwargs)

    def norm(self, **kwargs):
        return apply_op("norm", self, **kwargs)

    def square(self):
        return apply_op("square", self)

    def as_np_ndarray(self):
        return self

    def tolist(self):
        return self.asnumpy().tolist()


def _wrap_outputs(raw):
    if isinstance(raw, (tuple, list)):
        return [NDArray(r) for r in raw]
    return NDArray(raw)


# --------------------------------------------------------------------------
# creation (ref: src/operator/tensor/init_op.cc + python ndarray/utils.py)
# --------------------------------------------------------------------------
def _creation_ctx(ctx):
    return (ctx or current_context()).jax_device


def array(source_array, ctx=None, dtype=None):
    return NDArray(source_array, ctx=ctx, dtype=dtype)


def empty(shape, ctx=None, dtype=None):
    return zeros(shape, ctx=ctx, dtype=dtype)


def zeros(shape, ctx=None, dtype=None, **kwargs):
    if isinstance(shape, int):
        shape = (shape,)
    with jax.default_device(_creation_ctx(ctx)):
        return NDArray(jnp.zeros(tuple(shape), get_dtype(dtype)))


def ones(shape, ctx=None, dtype=None, **kwargs):
    if isinstance(shape, int):
        shape = (shape,)
    with jax.default_device(_creation_ctx(ctx)):
        return NDArray(jnp.ones(tuple(shape), get_dtype(dtype)))


def full(shape, val, ctx=None, dtype=None):
    if isinstance(shape, int):
        shape = (shape,)
    with jax.default_device(_creation_ctx(ctx)):
        return NDArray(jnp.full(tuple(shape), val, get_dtype(dtype)))


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None):
    with jax.default_device(_creation_ctx(ctx)):
        out = jnp.arange(start, stop, step, get_dtype(dtype))
        if repeat != 1:
            out = jnp.repeat(out, repeat)
        return NDArray(out)


def linspace(start, stop, num, endpoint=True, ctx=None, dtype=None):
    with jax.default_device(_creation_ctx(ctx)):
        return NDArray(jnp.linspace(start, stop, num, endpoint=endpoint,
                                    dtype=get_dtype(dtype)))


def eye(N, M=0, k=0, ctx=None, dtype=None):
    with jax.default_device(_creation_ctx(ctx)):
        return NDArray(jnp.eye(N, M if M else None, k, get_dtype(dtype)))


def zeros_like(arr):
    return NDArray(jnp.zeros_like(arr.data))


def ones_like(arr):
    return NDArray(jnp.ones_like(arr.data))


def moveaxis(arr, source, destination):
    return NDArray(jnp.moveaxis(arr.data, source, destination))


def concatenate(arrays, axis=0):
    return apply_op("concat", *arrays, dim=axis)


_sync_pick = None
_record_host_sync = None


def _note_host_sync():
    """Bump the profiler's host_syncs counter (lazy import: profiler is
    not yet importable while this module loads)."""
    global _record_host_sync
    if _record_host_sync is None:
        from .. import profiler

        _record_host_sync = profiler.record_host_sync
    _record_host_sync()


def _device_sync(d):
    """Force the computation producing ``d`` to complete by reading one
    element to host (the only reliable wait on the axon tunnel — see the
    sync-points note above). The pick is a cached jit, so per-call cost is
    one tiny executable launch + a 1-element transfer."""
    global _sync_pick
    if getattr(d, "size", 0) == 0:
        return
    if _sync_pick is None:
        _sync_pick = jax.jit(
            lambda x: jax.lax.slice(x.ravel(), (0,), (1,)))
    _note_host_sync()
    np.asarray(_sync_pick(d))  # sync-ok: wait_to_read's 1-element pick


def waitall():
    """Global sync barrier (ref: Engine::WaitForAll). Drains the async
    engine's in-flight step window first — deferred guard flags and their
    bookkeeping (update counts, loss-scale, skipped-step counter) land
    before this returns, so tests and chaos_matrix.sh can rely on it as
    a barrier — then blocks on XLA's effects barrier. Also flushes the
    telemetry JSONL sink: everything observed up to the barrier is on
    disk when this returns."""
    from .. import engine

    engine.wait_all()
    try:
        jax.effects_barrier()
    except Exception:
        pass
    from .. import telemetry

    telemetry.flush()


# --------------------------------------------------------------------------
# save / load (ref: src/ndarray/ndarray.cc — NDArray::Save/Load; C API
# MXNDArraySave/MXNDArrayLoad). Writes the reference's magic-tagged binary
# list format (mx_binary.py) so ``.params`` files cross the boundary in
# both directions; ``load`` additionally still reads the npz files earlier
# rounds of this framework wrote (format detected from the first bytes).
# --------------------------------------------------------------------------
def save(fname, data):
    from . import mx_binary
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, (list, tuple)):
        arrays, names = list(data), []
    elif isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    else:
        raise TypeError("save expects NDArray, list, or dict")
    for a in arrays:
        if not isinstance(a, NDArray):
            raise TypeError("save expects NDArray values, got %r" % (a,))
    with open(fname, "wb") as f:
        f.write(mx_binary.dumps(arrays, names))


def load(fname):
    from . import mx_binary
    with open(fname, "rb") as f:
        head = f.read(8)
        if mx_binary.is_mx_binary(head):
            arrays, names = mx_binary.loads(head + f.read())
            if names:
                return dict(zip(names, arrays))
            return arrays
    # npz fallback (this framework's pre-r5 byte format)
    with np.load(fname, allow_pickle=False) as zf:
        keys = list(zf.keys())
        if keys and all(k.startswith("__mxt_list_") for k in keys):
            keys.sort(key=lambda k: int(k.rsplit("_", 1)[1]))
            return [NDArray(zf[k]) for k in keys]
        return {k: NDArray(zf[k]) for k in keys}
