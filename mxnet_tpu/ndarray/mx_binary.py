"""Reference binary NDArray serialization (ref: src/ndarray/ndarray.cc —
NDArray::Save/Load; c_api.cc — MXNDArraySave/MXNDArrayLoad).

This is the byte format every MXNet 1.x ``.params`` / ``nd.save`` file uses,
re-implemented in pure Python (struct + numpy) so checkpoints cross the
reference boundary in both directions:

  file  := uint64 0x112 (kMXAPINDArrayListMagic)
           uint64 0     (reserved)
           uint64 N
           N * ndarray_record
           uint64 M                       (number of names; 0 for list saves)
           M * (uint64 len, len bytes)    (dmlc::Stream string serialization)

  ndarray_record (V2/V3, what 1.x writes) :=
           uint32 magic (0xF993FAC9 V2 | 0xF993FACA V3-np-shape)
           int32  stype (0 dense, 1 row_sparse, 2 csr)
           [stype!=dense] storage_shape           (shape of the value blob)
           shape                                  (uint32 ndim, int64 * ndim)
           int32 dev_type, int32 dev_id           (Context::Save; cpu=1)
           int32 type_flag                        (mshadow dtype enum)
           [stype!=dense] nad * (int32 aux_type, aux_shape)
           raw value bytes (little-endian, C order; size from shape)
           [stype!=dense] nad * raw aux bytes

Aux-array order matches the reference enums: row_sparse → (indices,);
csr → (indptr, indices)  (ref: include/mxnet/ndarray.h — rowsparse::kIdx,
csr::kIndPtr/kIdx).  Older records are also readable: V1 magic
(0xF993FAC8, int64 shape, no stype field) and legacy (first uint32 is
ndim, uint32 dims).

bfloat16 has no slot in the 1.x enum table; we write it as type_flag 12
(the value oneDNN-era builds used) and read 12 back as bfloat16 — a file
containing bf16 therefore only round-trips through this implementation.
"""
from __future__ import annotations

import struct

import numpy as np

from ..base import MXNetError

NDLIST_MAGIC = 0x112
_V1 = 0xF993FAC8
_V2 = 0xF993FAC9
_V3 = 0xF993FACA

# mshadow type_flag enum (ref: 3rdparty/mshadow/mshadow/base.h)
_FLAG_TO_DTYPE = {
    0: np.dtype("float32"),
    1: np.dtype("float64"),
    2: np.dtype("float16"),
    3: np.dtype("uint8"),
    4: np.dtype("int32"),
    5: np.dtype("int8"),
    6: np.dtype("int64"),
    7: np.dtype("bool"),
}
_DTYPE_TO_FLAG = {v.name: k for k, v in _FLAG_TO_DTYPE.items()}
_BF16_FLAG = 12  # kBfloat16 in oneDNN-era builds; our extension slot

_STYPE_DENSE, _STYPE_ROW_SPARSE, _STYPE_CSR = 0, 1, 2


def _np_of(x):
    """Host numpy view of an NDArray-like (handles bf16 → uint16 bits)."""
    # NB: not ascontiguousarray — it silently promotes 0-d to 1-d;
    # tobytes() below C-orders regardless of memory layout.
    return np.asarray(x.asnumpy() if hasattr(x, "asnumpy") else x)


def _write_shape(out, shape):
    out.append(struct.pack("<I", len(shape)))
    if shape:
        out.append(struct.pack("<%dq" % len(shape), *shape))


def _dtype_flag(dt):
    name = np.dtype(dt).name
    if name == "bfloat16":
        return _BF16_FLAG
    if name not in _DTYPE_TO_FLAG:
        raise MXNetError("cannot serialize dtype %s to the reference "
                         "binary format" % name)
    return _DTYPE_TO_FLAG[name]


def _blob_bytes(arr):
    """Raw little-endian bytes of a numpy (or bf16 jax-backed) array."""
    if arr.dtype.name == "bfloat16":  # ml_dtypes bfloat16: 2-byte items
        arr = arr.view(np.uint16)
    if arr.dtype.byteorder == ">":
        arr = arr.astype(arr.dtype.newbyteorder("<"))
    return arr.tobytes(order="C")


def _save_dense(out, arr):
    np_a = _np_of(arr)
    # V2 for ndim>=1 (what 1.x writes); V3 (np-shape semantics) for true
    # scalars, where ndim 0 means "scalar", not "uninitialized".
    out.append(struct.pack("<I", _V2 if np_a.ndim else _V3))
    out.append(struct.pack("<i", _STYPE_DENSE))
    _write_shape(out, np_a.shape)
    out.append(struct.pack("<ii", 1, 0))  # Context: cpu(1), dev_id 0
    out.append(struct.pack("<i", _dtype_flag(np_a.dtype)))
    out.append(_blob_bytes(np_a))


def _save_sparse(out, arr):
    from ..sparse import RowSparseNDArray
    values = np.ascontiguousarray(np.asarray(arr.data.asnumpy()))
    if isinstance(arr, RowSparseNDArray):
        stype, aux = _STYPE_ROW_SPARSE, [np.asarray(arr.indices.asnumpy())]
    else:  # CSR: aux order is (indptr, indices) — ref csr::kIndPtr, kIdx
        stype = _STYPE_CSR
        aux = [np.asarray(arr.indptr.asnumpy()),
               np.asarray(arr.indices.asnumpy())]
    out.append(struct.pack("<I", _V2))
    out.append(struct.pack("<i", stype))
    _write_shape(out, values.shape)          # storage_shape
    _write_shape(out, arr.shape)             # dense shape
    out.append(struct.pack("<ii", 1, 0))
    out.append(struct.pack("<i", _dtype_flag(values.dtype)))
    for a in aux:
        out.append(struct.pack("<i", _dtype_flag(a.dtype)))
        _write_shape(out, a.shape)
    out.append(_blob_bytes(values))
    for a in aux:
        out.append(_blob_bytes(np.ascontiguousarray(a)))


def dumps(arrays, names):
    """Serialize a list of (sparse) NDArrays + parallel name list (possibly
    empty) to reference-format bytes."""
    from ..sparse import BaseSparseNDArray
    out = [struct.pack("<QQQ", NDLIST_MAGIC, 0, len(arrays))]
    for a in arrays:
        if isinstance(a, BaseSparseNDArray):
            _save_sparse(out, a)
        else:
            _save_dense(out, a)
    out.append(struct.pack("<Q", len(names)))
    for n in names:
        b = n.encode("utf-8")
        out.append(struct.pack("<Q", len(b)))
        out.append(b)
    return b"".join(out)


class _Reader:
    def __init__(self, buf):
        self.buf, self.pos = buf, 0

    def read(self, n):
        b = self.buf[self.pos:self.pos + n]
        if len(b) != n:
            raise MXNetError("truncated NDArray file (wanted %d bytes at "
                             "offset %d)" % (n, self.pos))
        self.pos += n
        return b

    def u32(self):
        return struct.unpack("<I", self.read(4))[0]

    def i32(self):
        return struct.unpack("<i", self.read(4))[0]

    def u64(self):
        return struct.unpack("<Q", self.read(8))[0]

    def shape64(self):
        ndim = self.u32()
        if ndim == 0xFFFFFFFF:  # np-shape "unknown" → none
            return None
        return struct.unpack("<%dq" % ndim, self.read(8 * ndim)) \
            if ndim else ()

    def shape32(self):
        ndim = self.u32()
        return struct.unpack("<%dI" % ndim, self.read(4 * ndim)) \
            if ndim else ()


def _read_blob(r, shape, flag):
    if flag == _BF16_FLAG:
        import ml_dtypes
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        raw = np.frombuffer(r.read(2 * n), dtype=np.uint16)
        return raw.view(ml_dtypes.bfloat16).reshape(shape)
    if flag not in _FLAG_TO_DTYPE:
        raise MXNetError("unknown type_flag %d in NDArray file" % flag)
    dt = _FLAG_TO_DTYPE[flag]
    n = int(np.prod(shape, dtype=np.int64)) if shape else 1
    return np.frombuffer(r.read(dt.itemsize * n),
                         dtype=dt.newbyteorder("<")).astype(
                             dt, copy=False).reshape(shape)


def _load_one(r):
    """One ndarray_record → NDArray / RowSparseNDArray / CSRNDArray."""
    from ..ndarray.ndarray import NDArray
    from ..sparse import RowSparseNDArray, CSRNDArray
    magic = r.u32()
    if magic in (_V2, _V3):
        stype = r.i32()
        storage_shape = None
        if stype != _STYPE_DENSE:
            storage_shape = r.shape64()
        shape = r.shape64()
        if shape is None or (magic == _V2 and shape == ()
                             and stype == _STYPE_DENSE):
            return NDArray(np.zeros((0,), np.float32))  # uninitialized slot
        r.i32(); r.i32()  # Context dev_type/dev_id — device is ours to pick
        flag = r.i32()
        if stype == _STYPE_DENSE:
            return NDArray(_read_blob(r, shape, flag))
        nad = 1 if stype == _STYPE_ROW_SPARSE else 2
        aux_meta = [(r.i32(), r.shape64()) for _ in range(nad)]
        values = _read_blob(r, storage_shape, flag)
        aux = [_read_blob(r, s, f) for f, s in aux_meta]
        if stype == _STYPE_ROW_SPARSE:
            return RowSparseNDArray(values, aux[0], shape)
        return CSRNDArray(values, aux[1], aux[0], shape)
    if magic == _V1:
        shape = r.shape64()
        if not shape:  # uninitialized slot: no context/dtype/blob follow
            return NDArray(np.zeros((0,), np.float32))
    else:  # legacy: `magic` itself was ndim, dims are uint32
        ndim = magic
        shape = struct.unpack("<%dI" % ndim, r.read(4 * ndim)) \
            if ndim else ()
        if not shape:
            return NDArray(np.zeros((0,), np.float32))
    r.i32(); r.i32()
    flag = r.i32()
    return NDArray(_read_blob(r, shape, flag))


def loads(buf):
    """Parse reference-format bytes → (list_of_arrays, list_of_names)."""
    r = _Reader(buf)
    if r.u64() != NDLIST_MAGIC:
        raise MXNetError("not a reference NDArray file (bad magic)")
    r.u64()  # reserved
    arrays = [_load_one(r) for _ in range(r.u64())]
    names = []
    if r.pos < len(buf):
        for _ in range(r.u64()):
            names.append(r.read(r.u64()).decode("utf-8"))
    if names and len(names) != len(arrays):
        raise MXNetError("name count %d != array count %d"
                         % (len(names), len(arrays)))
    return arrays, names


def is_mx_binary(head8):
    """True if the first 8 bytes are the reference list magic."""
    return len(head8) >= 8 and \
        struct.unpack("<Q", head8[:8])[0] == NDLIST_MAGIC
