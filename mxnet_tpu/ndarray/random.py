"""``mx.nd.random`` — sampling namespace
(ref: python/mxnet/ndarray/random.py). Scalar params route to _random_*,
NDArray params to the _sample_* broadcasting variants, like the reference.
"""
from __future__ import annotations

from .ndarray import NDArray
from ..ops.registry import apply_op

__all__ = ["uniform", "normal", "randn", "randint", "gamma", "exponential",
           "poisson", "negative_binomial", "multinomial", "shuffle",
           "bernoulli"]


def _shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


def uniform(low=0.0, high=1.0, shape=None, dtype=None, ctx=None, out=None):
    if isinstance(low, NDArray) or isinstance(high, NDArray):
        return apply_op("_sample_uniform", low, high, shape=_shape(shape),
                        dtype=dtype, out=out)
    return apply_op("_random_uniform", low=low, high=high, shape=_shape(shape),
                    dtype=dtype, out=out)


def normal(loc=0.0, scale=1.0, shape=None, dtype=None, ctx=None, out=None):
    if isinstance(loc, NDArray) or isinstance(scale, NDArray):
        return apply_op("_sample_normal", loc, scale, shape=_shape(shape),
                        dtype=dtype, out=out)
    return apply_op("_random_normal", loc=loc, scale=scale, shape=_shape(shape),
                    dtype=dtype, out=out)


def randn(*shape, loc=0.0, scale=1.0, dtype=None, ctx=None):
    return normal(loc=loc, scale=scale, shape=shape, dtype=dtype, ctx=ctx)


def randint(low, high, shape=None, dtype="int32", ctx=None, out=None):
    return apply_op("_random_randint", low=low, high=high, shape=_shape(shape),
                    dtype=dtype, out=out)


def gamma(alpha=1.0, beta=1.0, shape=None, dtype=None, ctx=None, out=None):
    if isinstance(alpha, NDArray) or isinstance(beta, NDArray):
        return apply_op("_sample_gamma", alpha, beta, shape=_shape(shape),
                        dtype=dtype, out=out)
    return apply_op("_random_gamma", alpha=alpha, beta=beta,
                    shape=_shape(shape), dtype=dtype, out=out)


def exponential(scale=1.0, shape=None, dtype=None, ctx=None, out=None):
    return apply_op("_random_exponential", lam=1.0 / scale,
                    shape=_shape(shape), dtype=dtype, out=out)


def poisson(lam=1.0, shape=None, dtype=None, ctx=None, out=None):
    return apply_op("_random_poisson", lam=lam, shape=_shape(shape),
                    dtype=dtype, out=out)


def negative_binomial(k=1, p=0.5, shape=None, dtype=None, ctx=None, out=None):
    return apply_op("_random_negative_binomial", k=k, p=p,
                    shape=_shape(shape), dtype=dtype, out=out)


def multinomial(data, shape=None, get_prob=False, dtype="int32", out=None):
    return apply_op("_sample_multinomial", data,
                    shape=_shape(shape) if shape is not None else (),
                    get_prob=get_prob, dtype=dtype, out=out)


def shuffle(data, out=None):
    return apply_op("_shuffle", data, out=out)


def bernoulli(prob=0.5, shape=None, dtype="float32", ctx=None, out=None):
    return apply_op("bernoulli", prob=prob, shape=_shape(shape), dtype=dtype,
                    out=out)
