"""PendingValue — the deferred-handle half of the async engine.

The reference NDArray is a *future*: every op returns immediately and the
ThreadedEngine resolves the value later; only ``asnumpy()``/``asscalar()``
block (ref: include/mxnet/ndarray.h — engine variable + WaitToRead).
``jax.Array`` already gives device values that behavior, but host-side
*scalars the framework itself consumes* (the non-finite step flag, a
deferred loss, a metric sum) used to be read eagerly with
``np.asarray(...)`` — one full tunnel round-trip per step.

:class:`PendingValue` makes those reads explicit and lazy: it wraps a
device array and only transfers it to host on the first ``get()`` /
``float()`` / ``asnumpy()``. Callbacks registered with :meth:`on_ready`
run exactly once, at materialization — the engine's in-flight window
(engine.StepStream) retires tokens by materializing their PendingValues,
which is where deferred bookkeeping (optimizer update counts, the
loss-scale backoff, the skipped-step counter) catches up.

Every materialization records one ``host_syncs`` profiler tick, so
``bench.py`` can report host_syncs_per_step and
``tools/check_host_syncs.py`` can treat this module as the ONE sanctioned
sync funnel for deferred values.
"""
from __future__ import annotations

import threading

import numpy as np

__all__ = ["PendingValue"]


class PendingValue:
    """A device value whose host copy is produced lazily, once.

    ``dev`` may be a ``jax.Array`` or an :class:`NDArray` (unwrapped).
    Reading (``get``/``float``/``int``/``bool``/``asnumpy``) blocks until
    the producing computation finishes — the ``wait_to_read`` analog —
    and fires any :meth:`on_ready` callbacks with the host value.
    """

    __slots__ = ("_dev", "_host", "_callbacks", "_lock")

    def __init__(self, dev):
        data = getattr(dev, "data", None)
        self._dev = data if data is not None and hasattr(dev, "asnumpy") \
            else dev
        self._host = None
        self._callbacks = []
        self._lock = threading.Lock()

    @property
    def materialized(self):
        """True once the host copy exists (no blocking)."""
        return self._host is not None

    def ready(self):
        """Non-blocking: True if reading would not block (best-effort —
        falls back to ``materialized`` when the backend can't tell)."""
        if self._host is not None:
            return True
        probe = getattr(self._dev, "is_ready", None)
        try:
            return bool(probe()) if probe is not None else False
        except Exception:  # deleted/donated buffer: a read would raise too
            return False

    def on_ready(self, fn):
        """Run ``fn(host_value)`` at materialization (immediately if the
        value already materialized)."""
        with self._lock:
            if self._host is None:
                self._callbacks.append(fn)
                return
            host = self._host
        fn(host)

    def get(self):
        """The host value (numpy). First call blocks and fires callbacks."""
        with self._lock:
            if self._host is None:
                from .. import profiler

                profiler.record_host_sync()
                self._host = np.asarray(self._dev)  # sync-ok: the protocol's one read
                callbacks, self._callbacks = self._callbacks, []
            else:
                callbacks = []
            host = self._host
        for fn in callbacks:
            fn(host)
        return host

    def asnumpy(self):
        return self.get()

    def item(self):
        return self.get().reshape(-1)[0]

    def __float__(self):
        return float(self.item())  # sync-ok: conversion of the materialized host value

    def __int__(self):
        return int(self.item())  # sync-ok: conversion of the materialized host value

    def __bool__(self):
        return bool(self.item())  # sync-ok: conversion of the materialized host value

    def __repr__(self):
        state = "ready" if self._host is not None else "pending"
        return "PendingValue(%s)" % state
