"""``mx.nd`` — the imperative NDArray namespace.

The reference code-generates this namespace from C-API op metadata at import
(ref: python/mxnet/ndarray/register.py — _init_op_module). Here the same
thing happens against the native op registry: every registered op becomes a
module-level function taking NDArrays.
"""
from __future__ import annotations

import functools as _functools
import sys as _sys

from .ndarray import (
    NDArray, array, empty, zeros, ones, full, arange, linspace, eye,
    concatenate, waitall, save, load, zeros_like, ones_like, moveaxis,
)
from .pending import PendingValue
from ..ops import registry as _registry
from ..ops.registry import apply_op as _apply_op


def _make_op_func(op):
    def fn(*args, **kwargs):
        return _apply_op(op, *args, **kwargs)

    fn.__name__ = op.name
    fn.__qualname__ = op.name
    fn.__doc__ = (op.fn.__doc__ or "") + "\n(registered op: %s)" % op.name
    return fn


_mod = _sys.modules[__name__]
for _name in _registry.list_ops():
    _op = _registry.get_op(_name)
    if not hasattr(_mod, _name):
        setattr(_mod, _name, _make_op_func(_op))
for _alias, _target in list(_registry._ALIASES.items()):
    if not hasattr(_mod, _alias):
        setattr(_mod, _alias, getattr(_mod, _target))

from . import random  # noqa: E402  (needs op funcs above)
from ..ops.matrix import infer_reshape  # noqa: E402,F401
from ..ops.optimizer_ops import install_inplace_wrappers as _iow  # noqa: E402

_iow(_mod)

# creation-op names the reference exposes under nd.*
maximum = getattr(_mod, "broadcast_maximum")
minimum = getattr(_mod, "broadcast_minimum")
add = getattr(_mod, "broadcast_add")
subtract = getattr(_mod, "broadcast_sub")
multiply = getattr(_mod, "broadcast_mul")
divide = getattr(_mod, "broadcast_div")
power = getattr(_mod, "broadcast_power")
equal = getattr(_mod, "broadcast_equal")
not_equal = getattr(_mod, "broadcast_not_equal")
greater = getattr(_mod, "broadcast_greater")
lesser = getattr(_mod, "broadcast_lesser")

# sparse storage namespace (ref: python/mxnet/ndarray/sparse.py is exposed
# as mx.nd.sparse); imported late to avoid a cycle with ndarray.ndarray
from .. import sparse  # noqa: E402,F401


# ``mx.nd.contrib`` sub-namespace (ref: register.py generates op modules
# per prefix: _contrib_X -> nd.contrib.X); both the _contrib_-prefixed
# registry names and their unprefixed aliases resolve here
class _ContribNamespace:
    """Attribute view over the registry's contrib ops."""

    def __init__(self, mod):
        self._mod = mod

    def __getattr__(self, name):
        mod = object.__getattribute__(self, "_mod")
        fn = getattr(mod, "_contrib_%s" % name, None)
        if fn is not None and callable(fn):
            return fn
        raise AttributeError("contrib op %r is not registered" % (name,))

    def __dir__(self):
        mod = object.__getattribute__(self, "_mod")
        return sorted({n[len("_contrib_"):] for n in dir(mod)
                       if n.startswith("_contrib_")})


contrib = _ContribNamespace(_mod)
