"""Sharded training step over a Gluon block — SPMD data/tensor parallel.

This is the TPU-native core that replaces the reference's entire
DataParallelExecutorGroup + KVStore push/pull machinery
(ref: python/mxnet/module/executor_group.py, src/kvstore/*): the whole
train step (forward, backward, optimizer) is ONE jitted XLA program over a
Mesh; gradient reduction across the data axis and any tensor-parallel
collectives are inserted by GSPMD and ride ICI.

Params live as jax arrays placed with NamedSharding; PartitionSpec rules
(regex on parameter name) give tensor parallelism, default is replicated
(pure data parallel). Aux states (BatchNorm running stats) are carried as
non-differentiated inputs and returned updated — the same rebind-capture
protocol as CachedOp (gluon/block.py — _build_cached).
"""
from __future__ import annotations

import re
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..base import MXNetError
from .. import autograd as ag
from .. import random as _random
from ..ndarray.ndarray import NDArray
from ..gluon.block import Block, _trace_depth
from ..gluon.parameter import param_trace_scope
from .mesh import make_mesh

__all__ = ["ShardedTrainStep", "shard_params", "sharding_rule",
           "allreduce_across_processes"]


def sharding_rule(*pairs):
    """Build a rule list: (name_regex, PartitionSpec) applied first-match."""
    return [(re.compile(pat), spec) for pat, spec in pairs]


def _spec_for(name, rules):
    if rules:
        for pat, spec in rules:
            if pat.search(name):
                return spec
    return P()  # replicated


def shard_params(params, mesh, rules=None):
    """Place Parameter buffers on the mesh per the rules (replicated unless
    a rule names a tensor-parallel layout)."""
    for name, p in params.items():
        spec = _spec_for(name, rules)
        sharded = jax.device_put(p.data().data, NamedSharding(mesh, spec))
        p.data()._set_data(sharded)


def _make_opt_update(optimizer, optimizer_params):
    """Per-tensor pure update fn + state-init, from the fused optimizer ops
    (the same kernels the eager Updater uses)."""
    from ..ops.registry import get_op

    hp = dict(optimizer_params or {})
    lr = hp.pop("learning_rate", 0.01)
    wd = hp.pop("wd", 0.0)
    momentum = hp.pop("momentum", 0.0)
    rescale = hp.pop("rescale_grad", 1.0)
    clip = hp.pop("clip_gradient", None)

    if optimizer == "sgd":
        if momentum:
            fn = get_op("sgd_mom_update").fn

            def init(w):
                return (jnp.zeros_like(w),)

            def update(w, g, s, t):
                w2, m2 = fn(w, g, s[0], lr=lr, momentum=momentum, wd=wd,
                            rescale_grad=rescale, clip_gradient=clip)
                return w2, (m2,)
        else:
            fn = get_op("sgd_update").fn

            def init(w):
                return ()

            def update(w, g, s, t):
                return fn(w, g, lr=lr, wd=wd, rescale_grad=rescale,
                          clip_gradient=clip), ()
    elif optimizer == "adam":
        beta1 = hp.pop("beta1", 0.9)
        beta2 = hp.pop("beta2", 0.999)
        eps = hp.pop("epsilon", 1e-8)
        fn = get_op("adam_update").fn

        def init(w):
            return (jnp.zeros_like(w), jnp.zeros_like(w))

        def update(w, g, s, t):
            # bias correction folded into lr, as the eager Adam does
            coef1 = 1.0 - beta1 ** t
            coef2 = 1.0 - beta2 ** t
            lr_t = lr * jnp.sqrt(coef2) / coef1
            w2, m2, v2 = fn(w, g, s[0], s[1], lr=lr_t, beta1=beta1,
                            beta2=beta2, epsilon=eps, wd=wd,
                            rescale_grad=rescale, clip_gradient=clip)
            return w2, (m2, v2)
    else:
        raise MXNetError(
            "ShardedTrainStep supports 'sgd' and 'adam'; got %r (use the "
            "eager Trainer for other optimizers)" % (optimizer,))
    return init, update


class ShardedTrainStep:
    """One-program SPMD training step for a Gluon block.

    Usage::

        mesh = parallel.make_mesh((dp, tp), ("data", "model"))
        step = ShardedTrainStep(net, loss_fn, "sgd",
                                {"learning_rate": 0.1}, mesh=mesh,
                                rules=sharding_rule((r"dense\\d+_weight",
                                                     P("model", None))))
        loss = step(x_batch, y_batch)   # params update in place

    The batch is sharded along the mesh's data axis; XLA emits the grad
    psum over that axis (data parallel) and whatever collectives the rules
    imply (tensor parallel).
    """

    def __init__(self, block, loss_fn, optimizer="sgd", optimizer_params=None,
                 mesh=None, rules=None, data_axis="data", remat=None,
                 shard_update=False):
        """remat: None (save all intermediates — XLA default), "full"
        (recompute the whole forward in backward; ~1/3 more FLOPs for far
        less saved-activation HBM traffic — the jax.checkpoint analog of
        the reference's mirror/memonger), or any name from
        jax.checkpoint_policies (e.g. "dots_saveable").

        shard_update: ZeRO-1-style cross-replica weight-update sharding
        (Xu et al., arXiv:2004.13336 — a capability the reference never
        had): optimizer states shard dim-0 over the data axis and the
        update math runs sharded, turning the gradient all-reduce into
        reduce-scatter + sharded update + weight all-gather (same
        communication volume, but optimizer state memory and update HBM
        traffic divide by the dp degree). Params whose dim 0 doesn't
        divide the data axis (or that rules already shard) stay
        replicated, per the paper's fallback."""
        self.block = block
        self.loss_fn = loss_fn
        if remat not in (None, "full") and \
                not hasattr(jax.checkpoint_policies, str(remat)):
            valid = [n for n in dir(jax.checkpoint_policies)
                     if not n.startswith("_")]
            raise MXNetError(
                "unknown remat %r — use None, 'full', or one of %s"
                % (remat, valid))
        self._remat = remat
        self.mesh = mesh or make_mesh(axis_names=(data_axis,))
        self.data_axis = data_axis
        self._all_params = OrderedDict(
            sorted(block.collect_params().items()))
        for name, p in self._all_params.items():
            if p._data is None:
                raise MXNetError(
                    "parameter %s is not initialized (run net.initialize() "
                    "and one eager forward for deferred shapes)" % name)
        self._train_names = [n for n, p in self._all_params.items()
                             if p.grad_req != "null"]
        self._aux_names = [n for n, p in self._all_params.items()
                           if p.grad_req == "null"]
        shard_params(self._all_params, self.mesh, rules)
        self._init_s, self._update = _make_opt_update(
            optimizer, optimizer_params)
        # ZeRO-1 (shard_update): pick the update sharding per param —
        # dim 0 over the data axis where it divides and isn't already
        # mesh-sharded — BEFORE creating states, so sharded states are
        # materialized directly at 1/dp size (a replicated-then-reshard
        # init would peak at the full footprint per device, exactly the
        # memory ZeRO-1 exists to avoid)
        self._zero_shardings = {n: None for n in self._train_names}
        if shard_update:
            dp = self.mesh.shape[self.data_axis]
            for n in self._train_names:
                d = self._all_params[n].data().data
                cur = getattr(getattr(d, "sharding", None), "spec",
                              P()) or P()
                cur = tuple(cur) + (None,) * (d.ndim - len(tuple(cur)))
                if (d.ndim == 0 or d.shape[0] % dp != 0
                        or any(s is not None for s in cur)):
                    continue
                self._zero_shardings[n] = NamedSharding(
                    self.mesh, P(self.data_axis, *cur[1:]))
        self._states = {}
        for n in self._train_names:
            d = self._all_params[n].data().data
            zshard = self._zero_shardings[n]
            if zshard is not None:
                n_state = len(jax.eval_shape(self._init_s, d))
                self._states[n] = jax.jit(
                    self._init_s, out_shardings=(zshard,) * n_state)(d) \
                    if n_state else ()
            else:
                self._states[n] = self._init_s(d)
        # base RNG key is drawn lazily on the first step so a
        # mx.random.seed() between construction and training still takes
        # effect; per-step keys are then fold_in(base, t) ON DEVICE (a
        # host-side split per step is a separate executable launch — ~3.4ms
        # each on the axon tunnel)
        self._base_key = None
        # device-resident step counter, carried/donated through the jit
        self._t_dev = jnp.zeros((), jnp.int32)
        self._batch_cache = {}
        self._aot_compiled = {}  # (x sig, y sig) -> compiled (see _compile)
        self._jit = self._build()

    # ------------------------------------------------------------------
    def _pure_loss(self, train_vals, aux_vals, x, y, key):
        """Forward + loss as a pure function; aux rebinds captured."""
        wrappers = {}
        for n, v in zip(self._train_names, train_vals):
            wrappers[n] = NDArray(v)
        for n, v in zip(self._aux_names, aux_vals):
            wrappers[n] = NDArray(v)
        mapping = {self._all_params[n]: w for n, w in wrappers.items()}
        _trace_depth.depth += 1
        try:
            with ag.pause(train_mode=True), _random.key_scope(key), \
                    param_trace_scope(mapping):
                out = Block.__call__(self.block, NDArray(x))
                loss = self.loss_fn(out, NDArray(y))
                loss = loss.mean()
        finally:
            _trace_depth.depth -= 1
        new_aux = tuple(
            jax.lax.stop_gradient(wrappers[n].data) for n in self._aux_names)
        return loss.data, new_aux

    def _loss_for_grad(self):
        if self._remat is None:
            return self._pure_loss
        if self._remat == "full":
            return jax.checkpoint(self._pure_loss)
        policy = getattr(jax.checkpoint_policies, self._remat)
        return jax.checkpoint(self._pure_loss, policy=policy)

    def _build(self):
        loss_fn = self._loss_for_grad()
        zero = [self._zero_shardings[n] for n in self._train_names]
        wshard = [self._all_params[n].data().data.sharding
                  for n in self._train_names]

        def step(train_vals, states, aux_vals, x, y, base_key, t):
            # RNG key and step count are derived ON DEVICE from the carried
            # t — one launch per step, no per-step host->device transfers.
            t = t + 1
            key = jax.random.fold_in(base_key, t)
            (loss, new_aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(train_vals, aux_vals, x, y, key)
            new_train = []
            new_states = []
            for w, g, s, z, ws in zip(train_vals, grads, states, zero,
                                      wshard):
                if z is not None:
                    # ZeRO-1: constrain the grad to the update sharding
                    # (GSPMD fuses the dp all-reduce into reduce-scatter),
                    # run the update on shards, all-gather the weight back
                    g = jax.lax.with_sharding_constraint(g, z)
                w2, s2 = self._update(w, g, s, t)
                if z is not None:
                    s2 = tuple(
                        jax.lax.with_sharding_constraint(si, z)
                        for si in s2)
                    w2 = jax.lax.with_sharding_constraint(w2, ws)
                new_train.append(w2)
                new_states.append(s2)
            return loss, tuple(new_train), tuple(new_states), new_aux, t

        # params/states keep their placement; donate them so XLA reuses the
        # buffers (the static_alloc analog); t is donated too so the step
        # counter lives on device across steps
        return jax.jit(step, donate_argnums=(0, 1, 2, 6))

    # ------------------------------------------------------------------
    def _shard_batch(self, arr):
        data = arr.data if isinstance(arr, NDArray) else jnp.asarray(arr)
        spec = P(self.data_axis, *([None] * (data.ndim - 1)))
        sharding = NamedSharding(self.mesh, spec)
        if getattr(data, "sharding", None) == sharding:
            return data
        # memoize by source buffer: train loops pass the same batch array
        # for many steps (and bench reuses one batch for all of them) —
        # re-sharding it every step burns host time for an identical result.
        # Only the latest (x, y) pair is kept: a bigger cache pins dropped
        # batches in HBM until eviction (they hold strong refs).
        cached = self._batch_cache.get(id(data))
        if cached is not None and cached[0] is data:
            return cached[1]
        out = jax.device_put(data, sharding)
        while len(self._batch_cache) >= 2:
            self._batch_cache.pop(next(iter(self._batch_cache)))
        self._batch_cache[id(data)] = (data, out)
        return out

    def dump_hlo(self, x, y, path, optimized=True):
        """Write the step's HLO to ``path`` for offline analysis (the
        round-4 ResNet backward work: finding dgrad/wgrad layout copies
        needs the post-optimization module). optimized=False dumps the
        pre-optimization lowering instead. The AOT compile (one per
        process, shared with flops_per_step's accounting) is separate
        from the traced-call executable."""
        if optimized:
            compiled = self._compile(x, y)
            try:
                modules = compiled.runtime_executable().hlo_modules()
                text = "\n\n".join(m.to_string() for m in modules)
            except Exception:  # noqa: BLE001 — backend-dependent surface
                text = compiled.as_text()
        else:
            text = self._lower(x, y).as_text()
        with open(path, "w") as f:
            f.write(text)
        return path

    def _gather(self):
        """The exact (train, states, aux) operands __call__ passes —
        lowering helpers must stay in lockstep with execution."""
        train_vals = tuple(self._all_params[n].data().data
                           for n in self._train_names)
        aux_vals = tuple(self._all_params[n].data().data
                         for n in self._aux_names)
        states = tuple(self._states[n] for n in self._train_names)
        return train_vals, states, aux_vals

    def _lower(self, x, y):
        train_vals, states, aux_vals = self._gather()
        return self._jit.lower(
            train_vals, states, aux_vals, self._shard_batch(x),
            self._shard_batch(y), self._ensure_key(), self._t_dev)

    def _compile(self, x, y, lowered=None):
        """AOT-compiled step, memoized per input signature so
        flops_per_step + dump_hlo share ONE compile (ResNet-50 compiles
        are minutes on the tunnel). Pass ``lowered`` to reuse an
        already-lowered module instead of tracing again."""
        def sig(a):
            d = a.data if isinstance(a, NDArray) else a
            return tuple(d.shape), str(d.dtype)

        key = (sig(x), sig(y))
        if key not in self._aot_compiled:
            self._aot_compiled[key] = \
                (lowered or self._lower(x, y)).compile()
        return self._aot_compiled[key]

    def flops_per_step(self, x, y):
        """Total FLOPs of one compiled step per XLA cost analysis, or None
        if the backend doesn't report it. Used by bench.py for MFU."""
        try:
            lowered = self._lower(x, y)
            try:
                cost = lowered.cost_analysis()  # no compile needed
            except Exception:  # noqa: BLE001 — older backends
                cost = None
            if not cost:  # axon returns None from the lowered analysis
                cost = self._compile(x, y, lowered=lowered).cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            flops = float(cost.get("flops", 0.0)) if cost else 0.0
            return flops or None
        except Exception:  # noqa: BLE001 — cost analysis is best-effort
            return None

    def _ensure_key(self):
        if self._base_key is None:
            self._base_key = _random.new_key()
        return self._base_key

    def __call__(self, x, y):
        train_vals, states, aux_vals = self._gather()
        loss, new_train, new_states, new_aux, self._t_dev = self._jit(
            train_vals, states, aux_vals, self._shard_batch(x),
            self._shard_batch(y), self._ensure_key(), self._t_dev)
        from .. import profiler
        profiler.record_launch()
        for n, v in zip(self._train_names, new_train):
            self._all_params[n].data()._set_data(v)
        for n, s in zip(self._train_names, new_states):
            self._states[n] = s
        for n, v in zip(self._aux_names, new_aux):
            self._all_params[n].data()._set_data(v)
        return NDArray(loss)


def allreduce_across_processes(value):
    """Sum an array across processes (used by the dist kvstore facade).
    Single-process: identity."""
    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils

    sparse_stype = None
    if getattr(value, "stype", "default") != "default":
        # workers' index sets differ, so positional allgather of value
        # blocks would sum misaligned rows — reduce densely, re-sparsify
        sparse_stype = value.stype
        value = value.tostype("default")
    data = value.data if isinstance(value, NDArray) else value
    gathered = multihost_utils.process_allgather(data)
    # materialize on host: the allgather result is a GLOBAL (replicated)
    # array, and letting it flow into single-device NDArray ops trips
    # "Cannot reshard an input that is not fully addressable" — a host
    # copy re-enters as a plain process-local array
    out = jnp.asarray(np.asarray(gathered).sum(axis=0))
    if sparse_stype is not None:
        from ..sparse import cast_storage
        return cast_storage(NDArray(out), sparse_stype)
    return NDArray(out) if isinstance(value, NDArray) else out
