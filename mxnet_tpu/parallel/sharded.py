"""Sharded training step over a Gluon block — SPMD data/tensor parallel
with ZeRO weight-update sharding and elastic mesh rebinding.

This is the TPU-native core that replaces the reference's entire
DataParallelExecutorGroup + KVStore push/pull machinery
(ref: python/mxnet/module/executor_group.py, src/kvstore/*): the whole
train step (forward, backward, optimizer) is ONE jitted XLA program over a
Mesh; gradient reduction across the data axis and any tensor-parallel
collectives are inserted by GSPMD and ride ICI.

Params live as jax arrays placed with NamedSharding; PartitionSpec rules
(regex on parameter name) give tensor parallelism, default is replicated
(pure data parallel). Aux states (BatchNorm running stats, MoE router
accounting) are carried as non-differentiated inputs and returned
updated — the same rebind-capture protocol as CachedOp (gluon/block.py —
_build_cached).

The mesh is not limited to dp×tp: the same step runs a full
dp×tp×pp×ep mesh (``make_mesh((2, 1, 2, 2), ("dp", "tp", "pp",
"ep"))`` or the launch line's ``--mesh 2,1,2,2 --mesh-axes
dp,tp,pp,ep``) where pipeline stages and MoE experts are RULE-SHARDED
stacked parameters and the schedule/routing are ordinary ops inside
this one donated program — parallel/unified.py builds such a block;
the step only sees more named axes. ZeRO eligibility stays a per-axis
decision: dim 0 must divide dp AND no rule may already shard the param
on any axis (tp/pp/ep exclusion); optimizer state for rule-sharded
params follows the weight's own layout instead.

The sharding annotations are END-TO-END (the SNIPPETS "8 chips to
6000-chip superclusters without changing application code" pattern): the
batch is pinned to the data axis and the loss to replicated INSIDE the
program, params/states carry explicit NamedSharding placements, and the
mesh itself may span processes (parallel.init_distributed + a launch-line
``--mesh``) — the training script is identical at 1 host and at N.

ZeRO weight-update sharding (Xu et al., arXiv:2004.13336) is a stage
ladder over the data axis, ``zero_stage=``:

====== ===================================================================
stage  per-device effect (eligible params: dim 0 divides dp, not already
       tensor-parallel-sharded by a rule)
====== ===================================================================
0      pure data parallel — everything replicated (the baseline).
1      optimizer states shard dim-0 over the data axis (~dp× less state
       memory); gradients still all-reduce replicated.
2      + gradients are pinned to the update sharding, so GSPMD fuses the
       dp all-reduce into reduce-scatter and each replica updates only
       its slice (the paper's full weight-update sharding; the legacy
       ``shard_update=True`` flag maps here).
3      + the params THEMSELVES live dim-0-sharded (~dp× less param
       memory); GSPMD all-gathers at use in the forward, FSDP-style.
====== ===================================================================

Every stage is numerically exact vs. stage 0 — only layout and collective
choice change, never the math (tests assert <=1e-6 over 5 steps).
"""
from __future__ import annotations

import json
import re
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..base import MXNetError
from .. import autograd as ag
from .. import random as _random
from ..ndarray.ndarray import NDArray
from ..gluon.block import Block, _trace_depth
from ..gluon.parameter import param_trace_scope
from .mesh import make_mesh

__all__ = ["ShardedTrainStep", "shard_params", "sharding_rule",
           "allreduce_across_processes"]


def sharding_rule(*pairs):
    """Build a rule list: (name_regex, PartitionSpec) applied first-match."""
    return [(re.compile(pat), spec) for pat, spec in pairs]


def _spec_for(name, rules):
    if rules:
        for pat, spec in rules:
            if pat.search(name):
                return spec
    return P()  # replicated


def shard_params(params, mesh, rules=None, shardings=None):
    """Place Parameter buffers on the mesh per the rules (replicated
    unless a rule names a tensor-parallel layout), or per an explicit
    ``shardings`` {name: NamedSharding} map.

    Placements are BATCHED into one ``jax.device_put`` call and arrays
    whose layout already matches are skipped entirely — a resume or
    reshard pass over a mostly-placed model moves only what changed
    instead of blocking on a fresh transfer of every buffer (the old
    one-device_put-per-param loop re-transferred everything).
    Returns the number of arrays actually moved."""
    names, vals, targets = [], [], []
    for name, p in params.items():
        if shardings is not None:
            target = shardings[name]
        else:
            target = NamedSharding(mesh, _spec_for(name, rules))
        d = p.data().data
        cur = getattr(d, "sharding", None)
        if cur is not None and cur.is_equivalent_to(target, d.ndim):
            continue
        names.append(name)
        vals.append(d)
        targets.append(target)
    if not names:
        return 0
    for name, v in zip(names, jax.device_put(vals, targets)):
        params[name].data()._set_data(v)
    return len(names)


def _make_opt_update(optimizer, optimizer_params):
    """Per-tensor pure update fn + state-init, from the fused optimizer ops
    (the same kernels the eager Updater uses)."""
    from ..ops.registry import get_op

    hp = dict(optimizer_params or {})
    lr = hp.pop("learning_rate", 0.01)
    wd = hp.pop("wd", 0.0)
    momentum = hp.pop("momentum", 0.0)
    rescale = hp.pop("rescale_grad", 1.0)
    clip = hp.pop("clip_gradient", None)

    if optimizer == "sgd":
        if momentum:
            fn = get_op("sgd_mom_update").fn

            def init(w):
                return (jnp.zeros_like(w),)

            def update(w, g, s, t):
                w2, m2 = fn(w, g, s[0], lr=lr, momentum=momentum, wd=wd,
                            rescale_grad=rescale, clip_gradient=clip)
                return w2, (m2,)
        else:
            fn = get_op("sgd_update").fn

            def init(w):
                return ()

            def update(w, g, s, t):
                return fn(w, g, lr=lr, wd=wd, rescale_grad=rescale,
                          clip_gradient=clip), ()
    elif optimizer == "adam":
        beta1 = hp.pop("beta1", 0.9)
        beta2 = hp.pop("beta2", 0.999)
        eps = hp.pop("epsilon", 1e-8)
        fn = get_op("adam_update").fn

        def init(w):
            return (jnp.zeros_like(w), jnp.zeros_like(w))

        def update(w, g, s, t):
            # bias correction folded into lr, as the eager Adam does
            coef1 = 1.0 - beta1 ** t
            coef2 = 1.0 - beta2 ** t
            lr_t = lr * jnp.sqrt(coef2) / coef1
            w2, m2, v2 = fn(w, g, s[0], s[1], lr=lr_t, beta1=beta1,
                            beta2=beta2, epsilon=eps, wd=wd,
                            rescale_grad=rescale, clip_gradient=clip)
            return w2, (m2, v2)
    else:
        raise MXNetError(
            "ShardedTrainStep supports 'sgd' and 'adam'; got %r (use the "
            "eager Trainer for other optimizers)" % (optimizer,))
    return init, update


class ShardedTrainStep:
    """One-program SPMD training step for a Gluon block.

    Usage::

        mesh = parallel.make_mesh((dp, tp), ("data", "model"))
        step = ShardedTrainStep(net, loss_fn, "sgd",
                                {"learning_rate": 0.1}, mesh=mesh,
                                zero_stage=2,
                                rules=sharding_rule((r"dense\\d+_weight",
                                                     P("model", None))))
        loss = step(x_batch, y_batch)   # params update in place

    The batch is sharded along the mesh's data axis; XLA emits the grad
    psum over that axis (data parallel) and whatever collectives the rules
    imply (tensor parallel). ``zero_stage`` (0-3, module docstring) shards
    the weight update itself; the legacy ``shard_update=True`` maps to
    stage 2.

    The step also slots into the resilience/elasticity stack: it speaks
    the CheckpointManager ``trainer`` protocol (:meth:`save_states` /
    :meth:`load_states` restore onto the step's CURRENT mesh, whatever
    its shape), registers with ``tuning`` for AOT warm-start
    (:meth:`aot_warmup`), and can be re-homed onto a survivor mesh in
    place via :meth:`rebind_mesh` (parallel/reshard.py drives this when
    the membership reaper fences a host).
    """

    def __init__(self, block, loss_fn, optimizer="sgd", optimizer_params=None,
                 mesh=None, rules=None, data_axis="data", remat=None,
                 shard_update=False, zero_stage=None):
        """remat: None (save all intermediates — XLA default), "full"
        (recompute the whole forward in backward; ~1/3 more FLOPs for far
        less saved-activation HBM traffic — the jax.checkpoint analog of
        the reference's mirror/memonger), or any name from
        jax.checkpoint_policies (e.g. "dots_saveable").

        zero_stage: cross-replica weight-update sharding stage (0-3, see
        the module docstring); defaults to ``MXT_ZERO_STAGE`` (0 when
        unset). Params whose dim 0 doesn't divide the data axis (or that
        rules already shard) stay replicated at every stage, per the
        paper's fallback. ``shard_update=True`` is the legacy spelling
        of stage 2."""
        self.block = block
        self.loss_fn = loss_fn
        if remat not in (None, "full") and \
                not hasattr(jax.checkpoint_policies, str(remat)):
            valid = [n for n in dir(jax.checkpoint_policies)
                     if not n.startswith("_")]
            raise MXNetError(
                "unknown remat %r — use None, 'full', or one of %s"
                % (remat, valid))
        self._remat = remat
        if zero_stage is None:
            if shard_update:
                zero_stage = 2
            else:
                from .. import config

                zero_stage = int(config.get("MXT_ZERO_STAGE") or 0)
        zero_stage = int(zero_stage)
        if not 0 <= zero_stage <= 3:
            raise MXNetError(
                "zero_stage must be 0..3 (got %r)" % (zero_stage,))
        self.zero_stage = zero_stage
        self.mesh = mesh or make_mesh(axis_names=(data_axis,))
        if data_axis not in self.mesh.axis_names:
            if data_axis == "data":
                # the default name against a mesh that spells its axes
                # differently (the 4D launch convention dp,tp,pp,ep):
                # the FIRST mesh axis is the data axis by construction
                # (slowest-varying — make_mesh keeps dp outermost)
                data_axis = self.mesh.axis_names[0]
            else:
                raise MXNetError(
                    "mesh has no %r axis (axes: %s)"
                    % (data_axis, self.mesh.axis_names))
        self.data_axis = data_axis
        self._rules = rules
        # blocks that pin internal layouts (parallel/unified.py) resolve
        # their sharding axes against the step's LIVE mesh
        rebind = getattr(block, "rebind_mesh", None)
        if callable(rebind):
            rebind(self.mesh)
        self._all_params = OrderedDict(
            sorted(block.collect_params().items()))
        for name, p in self._all_params.items():
            if p._data is None:
                raise MXNetError(
                    "parameter %s is not initialized (run net.initialize() "
                    "and one eager forward for deferred shapes)" % name)
        self._train_names = [n for n, p in self._all_params.items()
                             if p.grad_req != "null"]
        self._aux_names = [n for n, p in self._all_params.items()
                           if p.grad_req == "null"]
        self._init_s, self._update = _make_opt_update(
            optimizer, optimizer_params)
        # derive placement + ZeRO shardings BEFORE creating states, so
        # sharded states are materialized directly at 1/dp size (a
        # replicated-then-reshard init would peak at the full footprint
        # per device, exactly the memory ZeRO exists to avoid)
        self._compute_shardings()
        shard_params(self._all_params, self.mesh,
                     shardings=self._param_shardings)
        self._states = {}
        for n in self._train_names:
            d = self._all_params[n].data().data
            # states materialize directly AT their storage sharding:
            # ZeRO-eligible params at 1/dp, rule-sharded (tp/pp/ep)
            # params matching the weight's own placement — never a
            # replicated-then-reshard peak
            sshard = self._state_shardings[n]
            n_state = len(jax.eval_shape(self._init_s, d))
            self._states[n] = jax.jit(
                self._init_s, out_shardings=(sshard,) * n_state)(d) \
                if n_state else ()
        # base RNG key is drawn lazily on the first step so a
        # mx.random.seed() between construction and training still takes
        # effect; per-step keys are then fold_in(base, t) ON DEVICE (a
        # host-side split per step is a separate executable launch — ~3.4ms
        # each on the axon tunnel)
        self._base_key = None
        # device-resident step counter, carried/donated through the jit.
        # Placed mesh-replicated from birth: the jit RETURNS it that way,
        # so an uncommitted initial value would change the argument
        # sharding between call 0 and call 1 and force a full recompile
        # of the step program on the second step.
        self._t_dev = jax.device_put(
            jnp.zeros((), jnp.int32),
            NamedSharding(self.mesh, P()))
        self._batch_cache = {}
        self._aot_compiled = {}  # (x sig, y sig) -> compiled (see _compile)
        self._last_sig = None
        self._ncalls = 0         # host dispatch counter (chaos timing)
        self._stream = None      # engine.StepStream (health staging only)
        self._health = False     # stat row compiled into the program
        self._health_mon = None  # health.HealthMonitor (retirement consumer)
        self._spike = False      # grad_spike chaos rule compiled in
        self._jit = self._build()
        from .. import tuning

        tuning.register_step(self)  # tuning.warmup() AOT-compiles us
        self._publish_mesh_telemetry()

    # ------------------------------------------------------------------
    # sharding derivation
    # ------------------------------------------------------------------
    def _compute_shardings(self):
        """(Re)derive per-parameter storage + ZeRO update shardings for
        the CURRENT mesh and stage. Called at build and again by
        rebind_mesh: a survivor mesh changes dp, so eligibility (dim-0
        divisibility) must be re-decided, never copied."""
        dp = self.mesh.shape[self.data_axis]
        train = set(self._train_names)
        self._param_shardings = {}
        self._zero_shardings = {n: None for n in self._train_names}
        self._state_shardings = {}
        for n, p in self._all_params.items():
            d = p.data().data
            spec = _spec_for(n, self._rules)
            # rule validation (typed, at derivation time — not a cryptic
            # XLA error at trace time): every named axis must exist on
            # THIS mesh and the spec must fit the tensor's rank, else a
            # 4D rule on a 2D mesh would silently replicate (or crash)
            for ax in tuple(spec):
                for a in (ax if isinstance(ax, tuple) else (ax,)):
                    if a is not None and a not in self.mesh.axis_names:
                        raise MXNetError(
                            "sharding rule for %s names mesh axis %r, "
                            "but the mesh has axes %s"
                            % (n, a, self.mesh.axis_names))
            if len(tuple(spec)) > d.ndim:
                raise MXNetError(
                    "sharding rule for %s has %d dims but the parameter "
                    "is rank %d" % (n, len(tuple(spec)), d.ndim))
            padded = tuple(spec) + (None,) * (d.ndim - len(tuple(spec)))
            zspec = None
            if (self.zero_stage >= 1 and n in train and d.ndim >= 1
                    and d.shape[0] % dp == 0
                    and not any(s is not None for s in padded)):
                zspec = P(self.data_axis, *padded[1:])
                self._zero_shardings[n] = NamedSharding(self.mesh, zspec)
            # ZeRO-3: the param ITSELF lives dim-0-sharded; GSPMD
            # all-gathers at use (FSDP-style). Stages 0-2 store per the
            # tensor-parallel rule (replicated by default).
            pspec = zspec if (self.zero_stage >= 3 and zspec is not None) \
                else spec
            self._param_shardings[n] = NamedSharding(self.mesh, pspec)
            if n in train:
                # optimizer state follows the UPDATE sharding when ZeRO
                # owns the param, else the weight's own storage layout —
                # a momentum/adam slot for a pp/ep-rule-sharded expert
                # weight must live sharded like the weight, never
                # silently replicated (the non-dp-axis regression)
                self._state_shardings[n] = self._zero_shardings[n] \
                    or self._param_shardings[n]

    def _batch_sharding(self, ndim):
        return NamedSharding(
            self.mesh, P(self.data_axis, *([None] * (ndim - 1))))

    # ------------------------------------------------------------------
    def _pure_loss(self, train_vals, aux_vals, x, y, key):
        """Forward + loss as a pure function; aux rebinds captured."""
        wrappers = {}
        for n, v in zip(self._train_names, train_vals):
            wrappers[n] = NDArray(v)
        for n, v in zip(self._aux_names, aux_vals):
            wrappers[n] = NDArray(v)
        mapping = {self._all_params[n]: w for n, w in wrappers.items()}
        _trace_depth.depth += 1
        try:
            with ag.pause(train_mode=True), _random.key_scope(key), \
                    param_trace_scope(mapping):
                out = Block.__call__(self.block, NDArray(x))
                loss = self.loss_fn(out, NDArray(y))
                loss = loss.mean()
        finally:
            _trace_depth.depth -= 1
        new_aux = tuple(
            jax.lax.stop_gradient(wrappers[n].data) for n in self._aux_names)
        return loss.data, new_aux

    def _loss_for_grad(self):
        if self._remat is None:
            return self._pure_loss
        if self._remat == "full":
            return jax.checkpoint(self._pure_loss)
        policy = getattr(jax.checkpoint_policies, self._remat)
        return jax.checkpoint(self._pure_loss, policy=policy)

    def _build(self):
        loss_fn = self._loss_for_grad()
        zero = [self._zero_shardings[n] for n in self._train_names]
        sshard = [self._state_shardings[n] for n in self._train_names]
        wshard = [self._param_shardings[n] for n in self._train_names]
        ashard = [self._param_shardings[n] for n in self._aux_names]
        stage = self.zero_stage
        replicated = NamedSharding(self.mesh, P())
        # training-health plane: the stat row and the grad_spike chaos
        # rule compile INTO the program at build (like the guard in the
        # single-host step); re-read on rebind_mesh's rebuild
        from .. import health as _health
        from .. import resilience as _resilience
        self._health = _health.enabled()
        health = self._health
        self._spike = _resilience.fault_point().rule("grad_spike") \
            is not None
        spike = self._spike
        train_names = self._train_names

        def step(train_vals, states, aux_vals, x, y, base_key, t,
                 spike_scale=1.0):
            # explicit end-to-end annotations (the GSPMD scale-out
            # contract): batch pinned to the data axis, loss replicated,
            # INSIDE the program — the same step placed on a 1-host or
            # an N-host mesh lays out identically with no script change.
            x = jax.lax.with_sharding_constraint(
                x, self._batch_sharding(x.ndim))
            y = jax.lax.with_sharding_constraint(
                y, self._batch_sharding(y.ndim))
            # RNG key and step count are derived ON DEVICE from the carried
            # t — one launch per step, no per-step host->device transfers.
            t = t + 1
            key = jax.random.fold_in(base_key, t)
            (loss, new_aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(train_vals, aux_vals, x, y, key)
            if spike:
                # seeded chaos: ONE layer's gradient scaled on device
                # (scale is 1.0 on every non-firing step)
                grads = _health.apply_grad_spike(grads, train_names,
                                                 spike_scale)
            loss = jax.lax.with_sharding_constraint(loss, replicated)
            # aux (BN running stats) pinned to their STORAGE sharding:
            # without this, ZeRO's sharded states pressure the GSPMD
            # solver into dim-0-sharding the aux outputs too, and the
            # layout change after step 1 forces a silent recompile
            new_aux = tuple(
                jax.lax.with_sharding_constraint(a, sh)
                for a, sh in zip(new_aux, ashard))
            new_train = []
            new_states = []
            for w, g, s, z, ss, ws in zip(train_vals, grads, states,
                                          zero, sshard, wshard):
                if z is not None and stage >= 2:
                    # ZeRO-2/3: pin the grad to the update sharding —
                    # GSPMD fuses the dp all-reduce into reduce-scatter
                    # and each replica updates only its slice
                    g = jax.lax.with_sharding_constraint(g, z)
                w2, s2 = self._update(w, g, s, t)
                # optimizer state stays pinned to its STORAGE sharding
                # across the update (ZeRO slice, or the weight's own
                # tp/pp/ep layout); the weight returns to ITS storage
                # (all-gather under ZeRO-1/2, stays dim-0-sharded under
                # ZeRO-3 where ws == z)
                s2 = tuple(
                    jax.lax.with_sharding_constraint(si, ss)
                    for si in s2)
                if z is not None:
                    w2 = jax.lax.with_sharding_constraint(w2, ws)
                new_train.append(w2)
                new_states.append(s2)
            if health:
                # per-layer stats packed ON DEVICE, replicated like the
                # loss: every host stages the identical small row into
                # its window, so per-host publication needs no gather
                row = _health.stat_row(loss, grads, train_vals,
                                       tuple(new_train))
                row = jax.lax.with_sharding_constraint(row, replicated)
                return (loss, tuple(new_train), tuple(new_states),
                        new_aux, t, row)
            return loss, tuple(new_train), tuple(new_states), new_aux, t

        if health and self._stream is None:
            from .. import engine

            # health stats ride a StepStream value channel: K steps of
            # rows cost ONE deferred read at retirement (and zero when
            # health is off — the stream itself only exists when armed)
            self._health_mon = _health.HealthMonitor(
                self._train_names, stream="sharded_step")
            self._stream = engine.StepStream(
                name="sharded_step", on_values=self._health_mon.consume)
        # params/states keep their placement; donate them so XLA reuses the
        # buffers (the static_alloc analog); t is donated too so the step
        # counter lives on device across steps
        return jax.jit(step, donate_argnums=(0, 1, 2, 6))

    # ------------------------------------------------------------------
    def _shard_batch(self, arr):
        data = arr.data if isinstance(arr, NDArray) else jnp.asarray(arr)
        sharding = self._batch_sharding(data.ndim)
        if getattr(data, "sharding", None) == sharding:
            return data
        # memoize by source buffer: train loops pass the same batch array
        # for many steps (and bench reuses one batch for all of them) —
        # re-sharding it every step burns host time for an identical result.
        # Only the latest (x, y) pair is kept: a bigger cache pins dropped
        # batches in HBM until eviction (they hold strong refs).
        cached = self._batch_cache.get(id(data))
        if cached is not None and cached[0] is data:
            return cached[1]
        if jax.process_count() > 1:
            # multi-host: every process holds its LOCAL slice of the
            # global batch; assemble the global array with no cross-host
            # transfer (each host feeds its own devices)
            out = jax.make_array_from_process_local_data(
                sharding, np.asarray(data))  # sync-ok: local batch is host data
        else:
            out = jax.device_put(data, sharding)
        while len(self._batch_cache) >= 2:
            self._batch_cache.pop(next(iter(self._batch_cache)))
        self._batch_cache[id(data)] = (data, out)
        return out

    def dump_hlo(self, x, y, path, optimized=True):
        """Write the step's HLO to ``path`` for offline analysis (the
        round-4 ResNet backward work: finding dgrad/wgrad layout copies
        needs the post-optimization module). optimized=False dumps the
        pre-optimization lowering instead. The AOT compile (one per
        process, shared with flops_per_step's accounting) is separate
        from the traced-call executable."""
        if optimized:
            compiled = self._compile(x, y)
            try:
                modules = compiled.runtime_executable().hlo_modules()
                text = "\n\n".join(m.to_string() for m in modules)
            except Exception:  # noqa: BLE001 — backend-dependent surface
                text = compiled.as_text()
        else:
            text = self._lower(x, y).as_text()
        with open(path, "w") as f:
            f.write(text)
        return path

    def _gather(self):
        """The exact (train, states, aux) operands __call__ passes —
        lowering helpers must stay in lockstep with execution."""
        train_vals = tuple(self._all_params[n].data().data
                           for n in self._train_names)
        aux_vals = tuple(self._all_params[n].data().data
                         for n in self._aux_names)
        states = tuple(self._states[n] for n in self._train_names)
        return train_vals, states, aux_vals

    def _lower(self, x, y):
        train_vals, states, aux_vals = self._gather()
        return self._jit.lower(
            train_vals, states, aux_vals, self._shard_batch(x),
            self._shard_batch(y), self._ensure_key(), self._t_dev)

    @staticmethod
    def _sig(a):
        d = a.data if isinstance(a, NDArray) else a
        return tuple(d.shape), str(d.dtype)

    def _compile(self, x, y, lowered=None):
        """AOT-compiled step, memoized per input signature so
        flops_per_step + dump_hlo share ONE compile (ResNet-50 compiles
        are minutes on the tunnel). Pass ``lowered`` to reuse an
        already-lowered module instead of tracing again."""
        key = (self._sig(x), self._sig(y))
        if key not in self._aot_compiled:
            self._aot_compiled[key] = \
                (lowered or self._lower(x, y)).compile()
        return self._aot_compiled[key]

    def aot_warmup(self):
        """AOT-lower-and-compile the donated step program from the live
        parameter shapes + the last seen batch signature (falling back to
        the tuning table's recorded ``sharded_step`` signatures), so a
        resumed — or freshly RESHARDED — step pays its XLA compile here
        instead of inside the next training step. With a persistent
        compile cache the traced call then replays as a cache hit.
        Returns False when no batch signature is known yet."""
        sig = self._last_sig
        if sig is None:
            from .. import tuning

            dp = self.mesh.shape[self.data_axis]
            # only signatures whose batch divides THIS mesh's data axis
            # (the table may carry shapes recorded on another mesh)
            recorded = [s for s in tuning.signatures("sharded_step")
                        if s.get("x_shape") and s["x_shape"][0] % dp == 0]
            if not recorded:
                return False
            spec = recorded[-1]
            sig = ((tuple(spec["x_shape"]), spec["x_dtype"]),
                   (tuple(spec["y_shape"]), spec["y_dtype"]))
        (xs, xd), (ys, yd) = sig

        def sds(a):
            return jax.ShapeDtypeStruct(a.shape, a.dtype,
                                        sharding=a.sharding)

        train_vals, states, aux_vals = self._gather()
        lowered = self._jit.lower(
            jax.tree.map(sds, train_vals), jax.tree.map(sds, states),
            jax.tree.map(sds, aux_vals),
            jax.ShapeDtypeStruct(xs, xd,
                                 sharding=self._batch_sharding(len(xs))),
            jax.ShapeDtypeStruct(ys, yd,
                                 sharding=self._batch_sharding(len(ys))),
            self._ensure_key(), self._t_dev)
        self._aot_compiled[sig] = lowered.compile()
        return True

    def flops_per_step(self, x, y):
        """Total FLOPs of one compiled step per XLA cost analysis, or None
        if the backend doesn't report it. Used by bench.py for MFU."""
        try:
            lowered = self._lower(x, y)
            try:
                cost = lowered.cost_analysis()  # no compile needed
            except Exception:  # noqa: BLE001 — older backends
                cost = None
            if not cost:  # axon returns None from the lowered analysis
                cost = self._compile(x, y, lowered=lowered).cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            flops = float(cost.get("flops", 0.0)) if cost else 0.0  # sync-ok: host cost dict
            return flops or None
        except Exception:  # noqa: BLE001 — cost analysis is best-effort
            return None

    def _ensure_key(self):
        if self._base_key is None:
            self._base_key = _random.new_key()
        return self._base_key

    def __call__(self, x, y):
        sig = (self._sig(x), self._sig(y))
        if sig != self._last_sig:
            self._last_sig = sig
            from .. import tuning

            # recorded signature -> a NEW process (warm resume) can AOT-
            # compile this step before its first batch ever arrives
            tuning.record_signature("sharded_step", {
                "x_shape": list(sig[0][0]), "x_dtype": sig[0][1],
                "y_shape": list(sig[1][0]), "y_dtype": sig[1][1]})
        train_vals, states, aux_vals = self._gather()
        # seeded chaos: scale is 1.0 except on the one firing dispatch
        # (same weak-float aval either way — no retrace)
        self._ncalls += 1
        spike_scale = 1.0
        if self._spike:
            from .. import health as _health
            spike_scale = _health.grad_spike_scale(self._ncalls)
        if self._health:
            (loss, new_train, new_states, new_aux, self._t_dev,
             row) = self._jit(
                train_vals, states, aux_vals, self._shard_batch(x),
                self._shard_batch(y), self._ensure_key(), self._t_dev,
                spike_scale)
            # stats stage into the window: the ONE deferred read per K
            # steps at retirement covers them, the hot path reads nothing
            self._stream.push(loss, value=row)
        else:
            loss, new_train, new_states, new_aux, self._t_dev = self._jit(
                train_vals, states, aux_vals, self._shard_batch(x),
                self._shard_batch(y), self._ensure_key(), self._t_dev,
                spike_scale)
        from .. import profiler
        profiler.record_launch()
        for n, v in zip(self._train_names, new_train):
            self._all_params[n].data()._set_data(v)
        for n, s in zip(self._train_names, new_states):
            self._states[n] = s
        for n, v in zip(self._aux_names, new_aux):
            self._all_params[n].data()._set_data(v)
        return NDArray(loss)

    # ------------------------------------------------------------------
    # memory accounting + telemetry
    # ------------------------------------------------------------------
    @property
    def step_count(self):
        """Completed optimizer steps. A host read of the carried device
        counter — a control-plane cursor for checkpoints/reshards, never
        read in the hot loop."""
        return int(self._t_dev)  # sync-ok: rare control-plane cursor read

    def per_device_bytes(self):
        """Bytes ONE device holds: ``{'param_bytes', 'opt_state_bytes'}``.
        Replicated tensors count full size per device; ZeRO/tp-sharded
        tensors count only the local shard — the quantity the ZeRO
        ladder shrinks ~dp× (bench's zero_stage_ab row asserts it)."""
        def dev0(a):
            return a.addressable_shards[0].data.nbytes

        params = sum(dev0(self._all_params[n].data().data)
                     for n in self._all_params)
        opt = sum(dev0(s) for n in self._train_names
                  for s in self._states[n])
        return {"param_bytes": int(params), "opt_state_bytes": int(opt)}

    def _publish_mesh_telemetry(self):
        """Mesh-shape / ZeRO / per-device-bytes gauges. mxt_top's mesh
        section renders only when these exist; reshards re-publish."""
        from .. import telemetry

        telemetry.gauge(
            "mxt_mesh_devices",
            "Devices in the active training mesh.").set(
                int(self.mesh.devices.size))
        ax = telemetry.gauge("mxt_mesh_axis_size",
                             "Mesh extent per named axis.", ("axis",))
        for name, size in self.mesh.shape.items():
            ax.labels(str(name)).set(int(size))
        telemetry.gauge(
            "mxt_zero_stage",
            "Active ZeRO weight-update sharding stage (0-3)."
        ).set(self.zero_stage)
        b = self.per_device_bytes()
        telemetry.gauge(
            "mxt_per_device_param_bytes",
            "Model parameter bytes held by ONE device (shrinks ~dp× "
            "under ZeRO-3).").set(b["param_bytes"])
        telemetry.gauge(
            "mxt_per_device_opt_bytes",
            "Optimizer-state bytes held by ONE device (shrinks ~dp× "
            "under ZeRO-1/2/3).").set(b["opt_state_bytes"])
        from .. import diagnostics

        # the HBM ledger tracks ONE device's working set (that is what
        # an OOM post-mortem must explain); reshards re-publish
        diagnostics.hbm_set("params", "sharded_step", b["param_bytes"])
        diagnostics.hbm_set("optimizer", "sharded_step",
                            b["opt_state_bytes"])

    # ------------------------------------------------------------------
    # checkpoint protocol (CheckpointManager's `trainer` slot) + reshard
    # ------------------------------------------------------------------
    def save_states(self, fname):
        """Optimizer states + step cursor + PRNG base key, in the
        CheckpointManager writer protocol (one path argument): a
        ShardedTrainStep slots straight into ``CheckpointManager`` as
        its ``trainer``, so sharded runs checkpoint through the same
        CRC-manifested atomic machinery as eager ones. Shards are
        gathered to host numpy — the checkpoint IS the cross-mesh
        transfer format the elastic reshard path rides."""
        arrays = {}
        for n in self._train_names:
            for i, s in enumerate(self._states[n]):
                arrays["s:%d:%s" % (i, n)] = np.asarray(s)  # sync-ok: checkpoint spill
        if self._base_key is not None:
            arrays["base_key"] = np.asarray(  # sync-ok: control-plane key snapshot
                jax.random.key_data(self._base_key))
        meta = {"t": self.step_count, "zero_stage": self.zero_stage,
                "mesh": {str(k): int(v)
                         for k, v in self.mesh.shape.items()}}
        arrays["__meta__"] = np.frombuffer(
            json.dumps(meta).encode("utf-8"), dtype=np.uint8)
        # open file handle: np.savez(path) appends .npz, which would
        # break CheckpointManager's tmp -> os.replace publish
        with open(fname, "wb") as f:
            np.savez(f, **arrays)

    def load_states(self, fname):
        """Inverse of :meth:`save_states` onto the CURRENT mesh: every
        state shard is re-placed per THIS step's (possibly different)
        dp×tp layout — a checkpoint written on an 8-device mesh restores
        onto a 6-device survivor mesh with no renormalization. Params
        (which CheckpointManager reloads just before this, replicated on
        the default device) are re-placed too; already-correct buffers
        are skipped."""
        with open(fname, "rb") as f:
            data = np.load(f)
            blob = {k: data[k] for k in data.files}
        meta = json.loads(blob.pop("__meta__").tobytes().decode("utf-8"))
        key_data = blob.pop("base_key", None)
        per = {n: {} for n in self._train_names}
        for k, v in blob.items():
            _, i, n = k.split(":", 2)
            if n not in per:
                raise MXNetError(
                    "sharded state checkpoint names unknown parameter %r"
                    % n)
            per[n][int(i)] = v
        replicated = NamedSharding(self.mesh, P())
        for n in self._train_names:
            vals = [per[n][i] for i in sorted(per[n])]
            if not vals:
                self._states[n] = ()
                continue
            # state storage sharding, NOT `zero or replicated`: a state
            # for a pp/ep/tp-rule-sharded weight re-places onto the
            # weight's layout (the old fallback silently replicated it,
            # dp×-ing its per-device bytes on every restore)
            z = self._state_shardings[n]
            self._states[n] = tuple(jax.device_put(vals, [z] * len(vals)))
        if key_data is not None:
            self._base_key = jax.random.wrap_key_data(
                jnp.asarray(key_data))
        self._t_dev = jax.device_put(
            jnp.asarray(int(meta["t"]), jnp.int32), replicated)
        shard_params(self._all_params, self.mesh,
                     shardings=self._param_shardings)
        self._batch_cache.clear()
        self._publish_mesh_telemetry()

    def rebind_mesh(self, new_mesh, transfer=True):
        """Re-home this step on a different mesh in place (the elastic
        reshard primitive). Recomputes every sharding for the new dp×tp
        shape (ZeRO eligibility is re-decided for the new dp), rebuilds
        the donated step program, and — with ``transfer=True`` — moves
        live params/optimizer state device-to-device. ``transfer=False``
        leaves value movement to a CheckpointManager restore: the spill
        path reshard.reshard_step uses when the old mesh's hosts may be
        dead (their buffers unreachable)."""
        if new_mesh.axis_names != self.mesh.axis_names:
            raise MXNetError(
                "rebind_mesh must keep the axis names (%s -> %s)"
                % (self.mesh.axis_names, new_mesh.axis_names))
        self.mesh = new_mesh
        rebind = getattr(self.block, "rebind_mesh", None)
        if callable(rebind):
            # mesh-aware blocks (parallel/unified.py) re-resolve their
            # internal sharding constraints against the survivor mesh
            rebind(new_mesh)
        self._compute_shardings()
        replicated = NamedSharding(self.mesh, P())
        if transfer:
            shard_params(self._all_params, self.mesh,
                         shardings=self._param_shardings)
            for n in self._train_names:
                ss = list(self._states[n])
                if ss:
                    z = self._state_shardings[n]
                    self._states[n] = tuple(
                        jax.device_put(ss, [z] * len(ss)))
            self._t_dev = jax.device_put(self._t_dev, replicated)
            if self._base_key is not None:
                self._base_key = jax.device_put(self._base_key, replicated)
        self._batch_cache.clear()
        self._aot_compiled.clear()
        self._jit = self._build()
        self._publish_mesh_telemetry()
        return self


def allreduce_across_processes(value):
    """Sum an array across processes (used by the dist kvstore facade).
    Single-process: identity."""
    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils

    sparse_stype = None
    if getattr(value, "stype", "default") != "default":
        # workers' index sets differ, so positional allgather of value
        # blocks would sum misaligned rows — reduce densely, re-sparsify
        sparse_stype = value.stype
        value = value.tostype("default")
    data = value.data if isinstance(value, NDArray) else value
    gathered = multihost_utils.process_allgather(data)
    # materialize on host: the allgather result is a GLOBAL (replicated)
    # array, and letting it flow into single-device NDArray ops trips
    # "Cannot reshard an input that is not fully addressable" — a host
    # copy re-enters as a plain process-local array
    out = jnp.asarray(np.asarray(gathered).sum(axis=0))  # sync-ok: host re-entry
    if sparse_stype is not None:
        from ..sparse import cast_storage
        return cast_storage(NDArray(out), sparse_stype)
    return NDArray(out) if isinstance(value, NDArray) else out
