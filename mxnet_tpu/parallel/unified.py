"""Unified 4D parallelism — pipeline stages + mixture-of-experts as
SHARDINGS inside the one-launch sharded step.

parallel/pipeline.py and parallel/moe.py are tested islands: each is a
correct shard_map program on its own mesh, but they compose with
nothing — a model that wants both pays one launch per pipeline_apply /
moe_apply call, can't ride ZeRO, elastic reshard, the checkpoint
protocol, or AOT warmup. This module folds both into plain GSPMD ops on
ONE dp×tp×pp×ep mesh so :class:`~.sharded.ShardedTrainStep` runs the
whole thing — forward through the microbatched pipeline schedule, MoE
dispatch, loss, backward, optimizer — as its single donated jit
(``launches_per_step == 1``).

How each subsystem becomes a sharding:

- **Pipeline**: stage parameters are STACKED with leading axis S and
  rule-sharded ``P(pp)``; the GPipe schedule is python-unrolled masked
  ticks (the PR 8 idiom — no ``lax.scan`` carries, no
  dynamic_update_slice, no gather-of-traced-index: all three miscompile
  under spmd-partitioning on some backends). The per-tick stage hop is
  ``jnp.roll`` over the pp-sharded stage axis, which GSPMD lowers to a
  collective-permute (the manual ``ppermute`` of pipeline_apply).
  Bubble ticks compute garbage that the one-hot masked output writes
  never read, so their gradient contribution is exactly zero.
- **MoE**: expert parameters stack as (S, E, ...) sharded ``P(pp, ep)``;
  capacity-factor top-1 routing (Switch-style cumsum positions, the
  moe_apply math) runs per stage, and the dispatch/combine einsums over
  the ep-sharded expert dim are GSPMD's all_to_all analog. Router
  accounting (per-expert token load + over-capacity drops) accumulates
  ON DEVICE into aux parameters carried through the donated step — the
  BatchNorm running-stats protocol — and leaves the device only through
  :func:`publish_moe_telemetry`, one deferred read per window.

Because the schedule computes exactly the serial composition
``stage_{S-1}(...stage_0(x_m))`` per microbatch, the unified step is
bit-exact vs stepping the same math as separate launches — bench's
``parallel_4d_ab`` row asserts it.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..base import MXNetError
from ..gluon.block import Block, _trace_depth
from ..ndarray.ndarray import NDArray

__all__ = ["PipelineMoEBlock", "pipeline_moe_forward", "moe_capacity",
           "publish_moe_telemetry", "resolve_mesh_axis"]

# axis-name synonyms: the 4D launch convention is dp,tp,pp,ep
# (tools/launch.py --mesh-axes dp,tp,pp,ep); the long-standing island
# spellings data/model/pipe/expert keep working.
_AXIS_SYNONYMS = {
    "dp": ("dp", "data"),
    "tp": ("tp", "model"),
    "pp": ("pp", "pipe"),
    "ep": ("ep", "expert"),
}


def resolve_mesh_axis(mesh, role):
    """The mesh axis name filling ``role`` ('dp'/'tp'/'pp'/'ep'), or
    None when the mesh has no such axis (that parallelism is off)."""
    for cand in _AXIS_SYNONYMS[role]:
        if cand in mesh.axis_names:
            return cand
    return None


def moe_capacity(tokens, num_experts, capacity_factor):
    """Per-expert capacity for ``tokens`` routed across ``num_experts``
    (Switch/GShard ceil rounding — the factor always buys headroom)."""
    return max(1, -(-int(tokens * capacity_factor) // num_experts))


def pipeline_moe_forward(vals, x, num_microbatches, capacity_factor,
                         mesh=None, dp=None, pp=None, ep=None):
    """The pp×ep toy-LM forward: microbatched pipeline schedule with a
    Switch-style MoE FFN inside every stage, as PURE jnp ops.

    ``vals``: dict of parameter arrays (see :class:`PipelineMoEBlock`
    for shapes — stage params stacked (S, ...), experts (S, E, ...)).
    ``x``: (B, in_units) batch. Returns ``(logits, expert_load,
    drops)`` where expert_load is the (E,) count of real tokens each
    expert kept this pass and drops the scalar count routed over
    capacity (bubble garbage excluded from both).

    With ``mesh`` given, activations are pinned to the named axes via
    with_sharding_constraint (the end-to-end GSPMD contract); without
    it the same math runs on one device. BOTH bench legs call exactly
    this function, which is what makes the island-vs-unified A/B
    bit-exact: same ops, only launch structure differs.
    """
    s_stages, d, e_experts = vals["router_w"].shape
    b = x.shape[0]
    m = int(num_microbatches or s_stages)
    if b % m:
        raise MXNetError("batch %d not divisible into %d microbatches"
                         % (b, m))
    mb = b // m
    capacity = moe_capacity(mb, e_experts, capacity_factor)

    def cst(v, *axes):
        if mesh is None:
            return v
        spec = tuple(axes) + (None,) * (v.ndim - len(axes))
        return jax.lax.with_sharding_constraint(
            v, NamedSharding(mesh, P(*spec)))

    h = x @ vals["w_in"] + vals["b_in"]                  # (B, D)
    x_mb = cst(h.reshape(m, mb, d), None, dp)            # (M, mb, D)
    state = cst(jnp.zeros((s_stages, mb, d), h.dtype), pp, dp)
    outs = cst(jnp.zeros((m, mb, d), h.dtype), None, dp)
    load = jnp.zeros((e_experts,), h.dtype)
    drops = jnp.zeros((), h.dtype)
    stage0 = (np.arange(s_stages) == 0).reshape(s_stages, 1, 1)
    last = (np.arange(s_stages) == s_stages - 1).reshape(s_stages, 1, 1)

    for t in range(m + s_stages - 1):
        # stage hop: each stage receives its predecessor's activation.
        # roll over the pp-sharded stage axis == GSPMD collective-permute
        # (ppermute's VJP is the reverse roll — the backward wave).
        recv = cst(jnp.roll(state, 1, axis=0), pp, dp)
        # feed: tick t hands microbatch t to stage 0 (static slice — the
        # tick loop is python-unrolled, so there is no traced index to
        # gather on); drain ticks feed zeros that nothing reads.
        feed = x_mb[t] if t < m else jnp.zeros((mb, d), h.dtype)
        inp = jnp.where(stage0, feed[None], recv)
        hd = jnp.tanh(jnp.einsum("smd,sde->sme", inp, vals["stage_w"])
                      + vals["stage_b"][:, None, :])
        hd = cst(hd, pp, dp)
        # --- Switch MoE inside the stage (the moe_apply math, batched
        # over the pp-sharded stage axis) --------------------------------
        gates = jax.nn.softmax(
            jnp.einsum("smd,sde->sme", hd, vals["router_w"]), axis=-1)
        onehot = jax.nn.one_hot(jnp.argmax(gates, axis=-1), e_experts,
                                dtype=hd.dtype)            # (S, mb, E)
        # token's position in its expert's capacity; one_hot is all-zero
        # for positions >= capacity, which IS the over-capacity drop
        pos = (jnp.cumsum(onehot, axis=1) - 1.0) * onehot
        pos_oh = jax.nn.one_hot(pos.sum(-1).astype(jnp.int32), capacity,
                                dtype=hd.dtype)            # (S, mb, C)
        dispatch = onehot[..., :, None] * pos_oh[..., None, :]
        dispatch = cst(dispatch, pp, dp)                   # (S, mb, E, C)
        gate_val = (gates * onehot).sum(-1)                # (S, mb)
        # dispatch/combine einsums over the ep-sharded expert slabs: the
        # token movement GSPMD lowers to the all_to_all of moe_apply
        slabs = cst(jnp.einsum("smec,smd->secd", dispatch, hd), pp, ep)
        eh = jax.nn.relu(
            jnp.einsum("secd,sedh->sech", slabs, vals["expert_w1"])
            + vals["expert_b1"][:, :, None, :])
        eo = jnp.einsum("sech,sehd->secd", eh, vals["expert_w2"]) \
            + vals["expert_b2"][:, :, None, :]
        eo = cst(eo, pp, ep)
        moe = jnp.einsum("smec,secd->smd", dispatch, eo) \
            * gate_val[..., None]
        h2 = cst(hd + moe, pp, dp)
        # on-device router accounting, REAL microbatches only: stage s
        # holds microbatch t-s, which is real iff 0 <= t-s < M (bubble
        # garbage must not pollute the load/overflow telemetry)
        real = np.array([1.0 if 0 <= t - s < m else 0.0
                         for s in range(s_stages)], np.float32)
        kept = dispatch.sum(axis=(2, 3))                   # (S, mb) 0/1
        load = load + (dispatch
                       * real.reshape(-1, 1, 1, 1)).sum(axis=(0, 1, 3))
        drops = drops + ((1.0 - kept) * real.reshape(-1, 1)).sum()
        # the last stage finishes microbatch t-(S-1) at tick t: one-hot
        # masked write (where, not .at[]/DUS — the spmd-safe store), and
        # masked-sum extraction of the last stage's row (not h2[-1] — the
        # slice of the pp-partitioned dim is the gather-transpose hazard)
        out_t = jnp.sum(jnp.where(last, h2, 0.0), axis=0)  # (mb, D)
        slot = t - (s_stages - 1)
        if slot >= 0:
            wmask = (np.arange(m) == slot).reshape(m, 1, 1)
            outs = jnp.where(wmask, out_t[None], outs)
        state = h2
    logits = outs.reshape(b, d) @ vals["w_out"] + vals["b_out"]
    return logits, load, drops


class PipelineMoEBlock(Block):
    """A pp×ep toy LM as ONE Gluon block the sharded step can own.

    ``in_units -> D`` projection, then ``num_stages`` pipeline stages
    (dense + Switch-MoE FFN with ``num_experts`` experts each), then a
    ``D -> num_classes`` head. Stage parameters stack along a leading S
    axis, expert parameters along (S, E) — :meth:`sharding_rules` pins
    them to the mesh's pp/ep axes, and
    :class:`~.sharded.ShardedTrainStep` then runs the whole schedule
    inside its single donated jit.

    Router accounting rides two ``grad_req='null'`` aux parameters
    (``expert_load`` (E,), ``router_drops`` (1,)) that accumulate on
    device through the donated step — zero per-step host syncs; read
    them per window with :func:`publish_moe_telemetry`.

    The block resolves its mesh axes lazily: ShardedTrainStep calls
    :meth:`rebind_mesh` at construction AND at every elastic reshard,
    so the sharding constraints always name the live mesh.
    """

    def __init__(self, num_stages=2, num_experts=2, in_units=8,
                 hidden=8, expert_hidden=16, num_classes=8,
                 num_microbatches=None, capacity_factor=1.25,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        s, e, d, hh = (int(num_stages), int(num_experts), int(hidden),
                       int(expert_hidden))
        self.num_stages, self.num_experts = s, e
        self.num_microbatches = int(num_microbatches or s)
        self.capacity_factor = float(capacity_factor)  # sync-ok: host config scalar
        self._mesh = None
        self._axes = {}
        with self.name_scope():
            g = self.params.get
            self._p = {
                "w_in": g("w_in", shape=(int(in_units), d)),
                "b_in": g("b_in", shape=(d,), init="zeros"),
                "stage_w": g("stage_w", shape=(s, d, d)),
                "stage_b": g("stage_b", shape=(s, d), init="zeros"),
                "router_w": g("router_w", shape=(s, d, e)),
                "expert_w1": g("expert_w1", shape=(s, e, d, hh)),
                "expert_b1": g("expert_b1", shape=(s, e, hh),
                               init="zeros"),
                "expert_w2": g("expert_w2", shape=(s, e, hh, d)),
                "expert_b2": g("expert_b2", shape=(s, e, d),
                               init="zeros"),
                "w_out": g("w_out", shape=(d, int(num_classes))),
                "b_out": g("b_out", shape=(int(num_classes),),
                           init="zeros"),
            }
            self.expert_load = g("expert_load", shape=(e,),
                                 init="zeros", grad_req="null")
            self.router_drops = g("router_drops", shape=(1,),
                                  init="zeros", grad_req="null")
        # register every weight as a block ATTRIBUTE too: Block's
        # structural walk (_collect_params_with_prefix) only sees
        # _reg_params, and save_parameters/checkpoint spills ride that
        # walk — a dict-only param would silently drop out of every
        # checkpoint (and the elastic-reshard spill would restore
        # initial weights)
        for k, p in self._p.items():
            setattr(self, k, p)

    def param_values(self):
        """{short_name: placed jax array} snapshot — bench/tests feed
        these straight to :func:`pipeline_moe_forward` (the island leg
        of the A/B starts from the very same placed initial params)."""
        return {k: p.data().data for k, p in self._p.items()}

    # -- mesh binding ---------------------------------------------------
    def rebind_mesh(self, mesh):
        """Resolve this block's sharding axes against ``mesh`` (called
        by ShardedTrainStep at build and after every reshard — the
        constraints must always name the LIVE mesh's axes)."""
        self._mesh = mesh
        self._axes = {r: resolve_mesh_axis(mesh, r)
                      for r in ("dp", "pp", "ep")}
        pp, ep = self._axes["pp"], self._axes["ep"]
        if pp is not None and mesh.shape[pp] not in (1, self.num_stages):
            raise MXNetError(
                "mesh %r axis extent %d does not match %d pipeline "
                "stages" % (pp, mesh.shape[pp], self.num_stages))
        if ep is not None and self.num_experts % mesh.shape[ep]:
            raise MXNetError(
                "%d experts do not shard over %r axis extent %d"
                % (self.num_experts, ep, mesh.shape[ep]))
        return self

    def sharding_rules(self, mesh=None):
        """First-match rule list pinning stage params to pp and expert
        params to (pp, ep), for ShardedTrainStep's ``rules=``."""
        from .sharded import sharding_rule

        mesh = mesh if mesh is not None else self._mesh
        if mesh is None:
            raise MXNetError("sharding_rules needs a mesh — pass one or "
                             "call rebind_mesh first")
        pp = resolve_mesh_axis(mesh, "pp")
        ep = resolve_mesh_axis(mesh, "ep")
        rules = []
        if pp is not None and ep is not None:
            rules.append((r"expert_(w1|b1|w2|b2)$", P(pp, ep)))
        if pp is not None:
            rules.append((r"(stage_w|stage_b|router_w)$", P(pp)))
        return sharding_rule(*rules)

    # -- forward --------------------------------------------------------
    def forward(self, x):
        vals = {k: p.data().data for k, p in self._p.items()}
        data = x.data if isinstance(x, NDArray) else jnp.asarray(x)
        # constraints only under a trace: the eager (init/debug) path
        # runs the same math without pinning layouts
        mesh = self._mesh if _trace_depth.depth else None
        axes = self._axes if mesh is not None else {}
        logits, load, drops = pipeline_moe_forward(
            vals, data, self.num_microbatches, self.capacity_factor,
            mesh=mesh, dp=axes.get("dp"), pp=axes.get("pp"),
            ep=axes.get("ep"))
        # accumulate router accounting into the carried aux params (the
        # BatchNorm running-stats protocol: _set_data on the traced
        # wrapper rebinds the aux output of the donated step)
        el = self.expert_load.data()
        el._set_data(el.data + load.astype(el.data.dtype))
        rd = self.router_drops.data()
        rd._set_data(rd.data + drops.reshape(1).astype(rd.data.dtype))
        return NDArray(logits)


def publish_moe_telemetry(block):
    """One deferred window read of the on-device router accounting ->
    ``mxt_moe_expert_load{expert}`` gauges + the
    ``mxt_moe_router_drops_total`` counter. Call per telemetry window
    (epoch end, reshard, bench teardown) — NEVER per step: the aux
    arrays live on device and this is the one sanctioned transfer.
    Returns ``{'expert_load': [...], 'drops': float}`` cumulative."""
    from .. import telemetry

    load = np.asarray(block.expert_load.data().data)  # sync-ok: windowed moe accounting read
    drops = float(np.asarray(  # sync-ok: windowed moe accounting read
        block.router_drops.data().data)[0])
    g = telemetry.gauge(
        "mxt_moe_expert_load",
        "Cumulative real tokens each MoE expert kept (on-device router "
        "accounting, read once per window).", ("expert",))
    for i, v in enumerate(load):
        g.labels(str(i)).set(float(v))  # sync-ok: host numpy value
    c = telemetry.counter(
        "mxt_moe_router_drops_total",
        "Cumulative real tokens dropped over expert capacity.")
    prev = getattr(block, "_moe_drops_published", 0.0)
    if drops > prev:
        c.inc(drops - prev)
    block._moe_drops_published = drops
    return {"expert_load": [float(v) for v in load],  # sync-ok: host numpy
            "drops": drops}
