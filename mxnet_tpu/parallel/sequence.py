"""Sequence/context parallelism — ring attention + Ulysses all-to-all
(SURVEY §5: absent from the reference, a first-class new capability here).

Both are shard_map programs over a mesh sequence axis:

- **Ring attention**: Q stays put, K/V blocks rotate around the ring via
  ``ppermute`` (ICI neighbor exchange); each hop folds one KV block into the
  running online-softmax state. Peak memory per chip is O(T/n), enabling
  sequences n× longer than one chip's HBM would allow. Collective order:
  hop i holds the block originally on device (idx - i) mod n.

- **Ulysses**: ``all_to_all`` reshards (T-sharded, all heads) →
  (H-sharded, full T), runs dense local attention, reshards back. One
  collective pair instead of n hops — better when heads ≥ devices and T
  fits per-chip.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..ops.attention import _attention_reference, _NEG_INF

# jax.shard_map is top-level only from jax 0.4.38 on; this build carries
# it under jax.experimental (the public home since 0.4.x) — resolve once
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map

__all__ = ["ring_attention", "ulysses_attention", "sequence_scope",
           "current_sequence_scope", "shard_map"]


def _ring_hop_scores(qf, k_cur, b_cur, idx, src, Tl, causal, sm_scale):
    """Masked score block for one ring hop: (B, H, Tl, Tl) in f32."""
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_cur.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * sm_scale
    if b_cur is not None:
        s = s + b_cur.astype(jnp.float32)
    if causal:
        row = idx * Tl + jnp.arange(Tl)
        col = src * Tl + jnp.arange(Tl)
        mask = col[None, :] <= row[:, None]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    return s


def _ring_fwd_pass(q_loc, k_loc, v_loc, bias_loc, axis_name, causal,
                   sm_scale, n_shards):
    """Per-device online-softmax ring. q_loc/k_loc/v_loc: (B, H, Tl, D);
    bias_loc: (B, 1, 1, Tl) additive key bias or None. Returns (out, lse)."""
    B, H, Tl, D = q_loc.shape
    idx = jax.lax.axis_index(axis_name)
    qf = q_loc.astype(jnp.float32)

    m0 = jnp.full((B, H, Tl), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tl), jnp.float32)
    acc0 = jnp.zeros((B, H, Tl, D), jnp.float32)
    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]

    def body(i, carry):
        k_cur, v_cur, b_cur, m, l, acc = carry
        src = (idx - i) % n_shards  # which global block k_cur is
        s = _ring_hop_scores(qf, k_cur, b_cur, idx, src, Tl, causal,
                             sm_scale)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32))
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        b_nxt = None if b_cur is None else jax.lax.ppermute(
            b_cur, axis_name, perm)
        return k_nxt, v_nxt, b_nxt, m_new, l_new, acc_new

    carry = (k_loc, v_loc, bias_loc, m0, l0, acc0)
    # n_shards hops: python loop keeps b_cur=None branch static; XLA still
    # pipelines the ppermutes against the matmuls
    for i in range(n_shards):
        carry = body(i, carry)
    _, _, _, m, l, acc = carry
    l = jnp.maximum(l, 1e-30)
    out = (acc / l[..., None]).astype(q_loc.dtype)
    return out, m + jnp.log(l)


# --------------------------------------------------------------------------
# custom VJP: the naive autodiff of the unrolled ring saves every hop's
# (B, H, Tl, Tl) probability block, making backward O(T^2/n) memory
# (round-1 ADVICE #1). Instead we save only out + lse — O(T/n) — and the
# backward re-runs the ring, recomputing each hop's scores from lse and
# rotating dk/dv accumulators along with their K/V blocks so every
# gradient lands back on the chip that owns the block.
# --------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _ring_core(q_loc, k_loc, v_loc, bias_loc, axis_name, causal, sm_scale,
               n_shards):
    out, _ = _ring_fwd_pass(q_loc, k_loc, v_loc, bias_loc, axis_name,
                            causal, sm_scale, n_shards)
    return out


def _ring_core_fwd(q_loc, k_loc, v_loc, bias_loc, axis_name, causal,
                   sm_scale, n_shards):
    out, lse = _ring_fwd_pass(q_loc, k_loc, v_loc, bias_loc, axis_name,
                              causal, sm_scale, n_shards)
    return out, (q_loc, k_loc, v_loc, bias_loc, out, lse)


def _ring_core_bwd(axis_name, causal, sm_scale, n_shards, res, do):
    q_loc, k_loc, v_loc, bias_loc, out, lse = res
    B, H, Tl, D = q_loc.shape
    idx = jax.lax.axis_index(axis_name)
    qf = q_loc.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * out.astype(jnp.float32), axis=-1)  # (B, H, Tl)
    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]

    dq = jnp.zeros((B, H, Tl, D), jnp.float32)
    dk_acc = jnp.zeros((B, H, Tl, D), jnp.float32)
    dv_acc = jnp.zeros((B, H, Tl, D), jnp.float32)
    # accumulator matches bias's own shape so broadcast dims (e.g. a
    # (1, 1, 1, T) shared bias with B > 1) get summed, not silently
    # expanded to a wrong-shaped per-example grad
    db_acc = None if bias_loc is None else jnp.zeros(bias_loc.shape,
                                                     jnp.float32)

    k_cur, v_cur, b_cur = k_loc, v_loc, bias_loc
    for i in range(n_shards):
        src = (idx - i) % n_shards
        s = _ring_hop_scores(qf, k_cur, b_cur, idx, src, Tl, causal,
                             sm_scale)
        p = jnp.exp(s - lse[..., None])  # exact probs from saved lse
        dv_acc = dv_acc + jnp.einsum("bhqk,bhqd->bhkd", p, dof)
        dp = jnp.einsum("bhqd,bhkd->bhqk", dof,
                        v_cur.astype(jnp.float32))
        ds = p * (dp - delta[..., None])  # dL/ds_total (pre-scale)
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds,
                             k_cur.astype(jnp.float32)) * sm_scale
        dk_acc = dk_acc + jnp.einsum("bhqk,bhqd->bhkd", ds, qf) * sm_scale
        if b_cur is not None:
            # reduce ds (B, H, Tq, Tk) onto the bias's own shape: sum
            # exactly the axes the bias broadcasts over (H=1 shared
            # biases sum heads; per-head (B, H, 1, Tk) biases — ALiBi —
            # keep their head axis)
            db = ds
            for ax in range(db.ndim):
                if bias_loc.shape[ax] == 1 and db.shape[ax] != 1:
                    db = jnp.sum(db, axis=ax, keepdims=True)
            db_acc = db_acc + db
        # rotate the block with its accumulators; after n hops each dk/dv
        # (and db) lands back on the chip that owns its K/V block
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
        dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)
        if b_cur is not None:
            b_cur = jax.lax.ppermute(b_cur, axis_name, perm)
            db_acc = jax.lax.ppermute(db_acc, axis_name, perm)

    dbias = None if bias_loc is None else db_acc.astype(bias_loc.dtype)
    return (dq.astype(q_loc.dtype), dk_acc.astype(k_loc.dtype),
            dv_acc.astype(v_loc.dtype), dbias)


_ring_core.defvjp(_ring_core_fwd, _ring_core_bwd)


def ring_attention(q, k, v, bias=None, mesh=None, seq_axis="data",
                   causal=False, sm_scale=None):
    """Sequence-parallel attention with ring KV rotation.

    q/k/v: (B, H, T, D) with T sharded over ``mesh[seq_axis]``; bias:
    optional additive (B, 1, 1, T) key bias (sharded on its T too).
    Returns (B, H, T, D) sharded like q.
    """
    if mesh is None:
        raise ValueError("ring_attention requires mesh= (a jax Mesh with "
                         "a %r axis)" % (seq_axis,))
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(q.shape[-1]))
    n_shards = mesh.shape[seq_axis]
    if q.shape[2] % n_shards:
        raise ValueError("sequence length %d not divisible by %d shards"
                         % (q.shape[2], n_shards))

    qkv_spec = P(None, None, seq_axis, None)
    scale = float(sm_scale)
    q, k, v = _commit_to_mesh(mesh, qkv_spec, q, k, v)
    if bias is not None:
        bias, = _commit_to_mesh(mesh, P(None, None, None, seq_axis),
                                bias)
        sm = _ring_callable(mesh, seq_axis, causal, scale, n_shards,
                            True)
        return sm(q, k, v, bias)
    sm = _ring_callable(mesh, seq_axis, causal, scale, n_shards, False)
    return sm(q, k, v)


def _commit_to_mesh(mesh, spec, *arrays):
    """device_put arrays onto the mesh sharding — inputs may live on one
    device while the mesh spans several (eager scope dispatch, or its
    vjp trace); under jit this lowers to a sharding constraint."""
    from jax.sharding import NamedSharding

    sh = NamedSharding(mesh, spec)
    return tuple(jax.device_put(a, sh) for a in arrays)


@functools.lru_cache(maxsize=64)
def _ring_callable(mesh, seq_axis, causal, scale, n_shards, has_bias):
    """Jitted shard_map program, cached by configuration — a fresh
    lambda per call would force a recompile per attention call (63 s/fwd
    for a 4-layer GPT before this cache; one compile per shape after)."""
    qkv_spec = P(None, None, seq_axis, None)
    if has_bias:
        sm = shard_map(
            lambda q_, k_, v_, b_: _ring_core(q_, k_, v_, b_, seq_axis,
                                              causal, scale, n_shards),
            mesh=mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec,
                      P(None, None, None, seq_axis)),
            out_specs=qkv_spec,
        )
    else:
        sm = shard_map(
            lambda q_, k_, v_: _ring_core(q_, k_, v_, None, seq_axis,
                                          causal, scale, n_shards),
            mesh=mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec),
            out_specs=qkv_spec,
        )
    return jax.jit(sm)


def _ulysses_local(q_loc, k_loc, v_loc, *, axis_name, causal, sm_scale):
    """(B, H, Tl, D) T-sharded → all_to_all → (B, H/n, T, D) H-sharded →
    dense local attention → reshard back."""
    a2a = functools.partial(jax.lax.all_to_all, axis_name=axis_name,
                            tiled=True)
    q2 = a2a(q_loc, split_axis=1, concat_axis=2)
    k2 = a2a(k_loc, split_axis=1, concat_axis=2)
    v2 = a2a(v_loc, split_axis=1, concat_axis=2)
    out = _attention_reference(q2, k2, v2, None, causal, sm_scale)
    return a2a(out, split_axis=2, concat_axis=1)


def ulysses_attention(q, k, v, mesh=None, seq_axis="data", causal=False,
                      sm_scale=None):
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism. Heads must
    be divisible by the mesh axis size."""
    if mesh is None:
        raise ValueError("ulysses_attention requires mesh= (a jax Mesh "
                         "with a %r axis)" % (seq_axis,))
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(q.shape[-1]))
    n_shards = mesh.shape[seq_axis]
    if q.shape[1] % n_shards:
        raise ValueError("num_heads %d not divisible by %d shards"
                         % (q.shape[1], n_shards))
    if q.shape[2] % n_shards:
        raise ValueError("sequence length %d not divisible by %d shards"
                         % (q.shape[2], n_shards))
    spec = P(None, None, seq_axis, None)
    q, k, v = _commit_to_mesh(mesh, spec, q, k, v)
    sm = _ulysses_callable(mesh, seq_axis, causal, float(sm_scale))
    return sm(q, k, v)


@functools.lru_cache(maxsize=64)
def _ulysses_callable(mesh, seq_axis, causal, sm_scale):
    """Jitted shard_map program, cached by configuration (same
    recompile-per-call hazard _ring_callable fixes for the ring)."""
    spec = P(None, None, seq_axis, None)
    sm = shard_map(
        functools.partial(_ulysses_local, axis_name=seq_axis,
                          causal=causal, sm_scale=sm_scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    return jax.jit(sm)


# ---------------------------------------------------------------------------
# sequence-parallel scope: any flash_attention op called inside it (eager
# or traced — model zoo, gluon blocks, symbols) dispatches to a
# sequence-parallel schedule (ring, or Ulysses when eligible) with zero
# model changes
# ---------------------------------------------------------------------------
import contextlib as _contextlib
import threading as _threading

_SP_STATE = _threading.local()


@_contextlib.contextmanager
def sequence_scope(mesh, seq_axis="sp", schedule="ring"):
    """Route every flash_attention inside the scope through a
    sequence-parallel schedule over ``mesh[seq_axis]`` (the op reads
    this scope at call time — ops/attention.py flash_attention). The
    model code does not change; the sequence axis of q/k/v must divide
    by the axis size.

    schedule: "ring" (KV rotation; works with biases and any head
    count) or "ulysses" (head all-to-all; needs heads divisible by the
    axis size and no bias — falls back to ring when those don't hold).
    """
    if schedule not in ("ring", "ulysses"):
        raise ValueError("schedule must be 'ring' or 'ulysses', got %r"
                         % (schedule,))
    prev = getattr(_SP_STATE, "scope", None)
    _SP_STATE.scope = (mesh, seq_axis, schedule)
    try:
        yield
    finally:
        _SP_STATE.scope = prev


def current_sequence_scope():
    return getattr(_SP_STATE, "scope", None)
