"""Pipeline parallelism — GPipe-style microbatched stages over a mesh
axis (new capability beyond the reference: its closest analog is manual
`group2ctx` layer placement, SURVEY §2.4 strategy inventory "Pipeline
parallel: none").

Design (the jax-native shape, not a scheduler translation):

- The mesh gets a ``pipe`` axis of S stages; each device holds ONE
  stage's parameters (stacked pytree, leading axis S, sharded over
  ``pipe``).
- One `lax.scan` runs S+M-1 ticks inside a `shard_map`. Each tick every
  stage applies itself to its in-flight activation and hands the result
  to the next stage via `jax.lax.ppermute` — a neighbor hop that rides
  ICI on real hardware.
- The whole schedule is one differentiable XLA program: the backward
  pipeline is jax autodiff of the scan (ppermute's VJP is the reverse
  ppermute), so grads flow stage-by-stage in reverse exactly like the
  1B1F schedule's backward wave — no hand-built backward scheduler.
- Bubbles (S-1 warmup + S-1 drain ticks) compute garbage that is never
  collected; their gradient contribution is exactly zero because the
  output gather only reads real microbatch slots.

Efficiency: pipeline utilization is M/(M+S-1) — pick
``num_microbatches`` >= 4*S to keep the bubble under ~20%.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .sequence import shard_map  # version-compat resolved alias

from ..base import MXNetError

__all__ = ["pipeline_apply", "stack_stage_params"]


def stack_stage_params(per_stage_params):
    """Stacks a list of identically-structured per-stage pytrees along a
    new leading axis (the ``pipe``-sharded layout pipeline_apply
    expects)."""
    if not per_stage_params:
        raise MXNetError("need at least one stage")
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *per_stage_params)


def pipeline_apply(stage_fn, stage_params, x, mesh, axis="pipe",
                   num_microbatches=None):
    """Runs ``stage_fn`` as an S-stage GPipe pipeline over ``mesh``
    axis ``axis``.

    stage_fn(params_i, h) -> h' : one stage (all stages share this
    structure — the homogeneous-blocks case, e.g. transformer layers).
    stage_params: pytree with leading axis S on every leaf (see
    stack_stage_params).
    x: (B, ...) batch; B must divide into ``num_microbatches``.
    Returns the last stage's output, (B, ...).

    Differentiable; call under jit/grad. Activations hop stages via
    ppermute (ICI neighbor traffic on hardware).
    """
    if axis not in mesh.axis_names:
        raise MXNetError("mesh has no %r axis (axes: %s)"
                         % (axis, mesh.axis_names))
    n_stages = mesh.shape[axis]
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != n_stages:
            # a multiple would silently shard >1 stage per device and
            # drop all but the first — refuse loudly instead
            raise MXNetError(
                "stage_params leading dim %d must equal the %r axis "
                "size %d (one stage per device)"
                % (leaf.shape[0], axis, n_stages))
    m = num_microbatches or n_stages
    b = x.shape[0]
    if b % m:
        raise MXNetError("batch %d not divisible into %d microbatches"
                         % (b, m))
    mb = b // m

    def per_device(params, xs):  # params: leaves (1, ...); xs: full batch
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        stage = jax.lax.axis_index(axis)
        x_mb = xs.reshape((m, mb) + xs.shape[1:])

        h0 = jnp.zeros((mb,) + xs.shape[1:], xs.dtype)
        out0 = jnp.zeros((m, mb) + xs.shape[1:], xs.dtype)
        # the loop makes the carry device-varying (ppermute); mark the
        # replicated zeros accordingly so scan's carry types line up
        # (jax builds without lax.pcast track varying-ness implicitly —
        # the no-op fallback keeps the schedule identical)
        _pcast = getattr(jax.lax, "pcast", None)
        if _pcast is not None:
            h0 = _pcast(h0, (axis,), to="varying")
            out0 = _pcast(out0, (axis,), to="varying")

        def tick(carry, t):
            h, outs = carry
            # receive the previous stage's activation (stage 0 receives
            # stage S-1's drain garbage and ignores it)
            recv = jax.lax.ppermute(
                h, axis, [(i, (i + 1) % n_stages)
                          for i in range(n_stages)])
            feed_t = jnp.clip(t, 0, m - 1)
            # one-hot select of microbatch feed_t (not x_mb[feed_t]): the
            # gather's transpose is a scatter/DUS that miscompiles under
            # spmd-partitioning on some backends (s64/s32 index compare);
            # the masked sum's VJP is a broadcast multiply instead
            feed_mask = (jnp.arange(m) == feed_t).reshape(
                (m,) + (1,) * (x_mb.ndim - 1))
            x_t = jnp.sum(jnp.where(feed_mask, x_mb, 0.0), axis=0)
            inp = jnp.where(stage == 0,
                            jnp.where(t < m, x_t, 0.0),
                            recv)
            h2 = stage_fn(params, inp)
            # last stage finishes microbatch t-(S-1) at tick t; masked
            # write (where, not cond — keeps shard_map's varying-axis
            # types uniform)
            slot = t - (n_stages - 1)
            write = (stage == n_stages - 1) & (slot >= 0)
            # one-hot masked write instead of dynamic_update_slice: the
            # DUS transpose under spmd-partitioning miscompiles on some
            # backends (s64/s32 index compare); the where-form is the
            # same masked store and keeps varying-axis types uniform
            onehot = jnp.arange(m) == jnp.clip(slot, 0, m - 1)
            mask = (onehot & write).reshape((m,) + (1,) * (outs.ndim - 1))
            outs = jnp.where(mask, h2[None].astype(outs.dtype), outs)
            return (h2, outs), None

        # python-unrolled ticks (the ring-attention treatment): a
        # lax.scan here stacks its carries with dynamic_update_slice,
        # which miscompiles under spmd-partitioning on some backends —
        # and the tick count m + S - 1 is small, so XLA still pipelines
        # the unrolled ppermutes against the stage matmuls
        carry = (h0, out0)
        for t in range(m + n_stages - 1):
            carry, _ = tick(carry, jnp.asarray(t, jnp.int32))
        outs = carry[1]
        return outs.reshape((b,) + xs.shape[1:])[None]  # (1, B, ...)

    spec_params = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    sm = shard_map(
        per_device, mesh=mesh,
        in_specs=(spec_params, P()), out_specs=P(axis))
    # jit the schedule: eager shard_map dispatches the unrolled tick
    # body primitive-by-primitive through the mesh machinery (~100ms
    # per collective on the CPU mesh — 15s for an 8×8 schedule); one
    # compiled program runs it in milliseconds. Under an outer jit this
    # inlines.
    stacked = jax.jit(sm)(stage_params, x)  # (S, B, ...) — one real row
    # the last stage's output, WITHOUT stacked[-1]: that slice's
    # transpose is a dynamic_update_slice along the pipe-partitioned
    # dim, which miscompiles under spmd-partitioning on some backends
    # (s64 index vs s32 partition offset); the masked sum transposes to
    # a plain select
    last = jnp.arange(stacked.shape[0]) == stacked.shape[0] - 1
    mask = last.reshape((-1,) + (1,) * (stacked.ndim - 1))
    return jnp.sum(jnp.where(mask, stacked, 0.0), axis=0)


def pipeline_utilization(num_stages, num_microbatches):
    """The GPipe schedule's compute utilization M/(M+S-1)."""
    return num_microbatches / (num_microbatches + num_stages - 1)
