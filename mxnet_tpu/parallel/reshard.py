"""Elastic mesh resharding — survivors reshape the mesh in place.

The PR 3 membership layer turns a dead host into a *fenced* host: its
generation is refused, reductions release over survivors. Before this
module, keeping full-efficiency GSPMD training after that still meant a
full-job restart on the smaller world (or renormalized degraded math).
Here the survivors instead:

1. **drain** the in-flight dispatch window (``engine.wait_all`` — the
   same coherence point checkpoints use),
2. **spill** params + optimizer state through
   ``resilience.CheckpointManager`` — the CRC-manifested atomic shard
   format. The checkpoint IS the transfer format: this path exercises
   exactly the bytes a from-checkpoint restart would read, which is why
   the acceptance test can demand bit-exact equality between an in-place
   reshard and a fresh restart on the same survivor mesh,
3. **rebind** the ``ShardedTrainStep`` onto the survivor mesh
   (``rebind_mesh``: dp shrinks, tp is preserved; ZeRO eligibility is
   re-decided for the new dp) and restore the spilled values onto the
   new layout, and
4. **AOT-warm** the resharded-shape step (``tuning.warmup``) so the next
   training step pays no JIT.

No renormalization, no restart, zero lost steps. Reshard events flow
through the telemetry registry (``mxt_reshard_events_total``,
``mxt_reshard_seconds``, the mesh-shape / per-device-bytes gauges the
step re-publishes) and render in ``tools/mxt_top.py``'s mesh section.

Wiring to membership: :class:`ElasticReshardController` listens for the
reaper's death events (``MembershipTable.add_death_listener``) or polls a
worker-side membership view, and performs the reshard at the training
loop's next ``maybe_reshard()`` call — the loop owns the drain point, so
a reap can never rip the mesh out from under a mid-flight dispatch.
"""
from __future__ import annotations

import shutil
import tempfile
import threading
import time
from collections import OrderedDict

import numpy as np

import jax
from jax.sharding import Mesh

from ..base import MXNetError

__all__ = ["HostDeviceMap", "plan_survivor_mesh", "reshard_step",
           "ElasticReshardController"]


class HostDeviceMap:
    """worker_id -> device slice of the mesh's device list.

    On a real multi-host pod (:meth:`from_processes`) host *i* owns its
    process-local devices. On the single-process 8-device CPU harness the
    global device list is split into ``num_hosts`` contiguous slices —
    matching ``make_mesh``'s ICI-order reshape, so a surviving slice
    keeps its tensor-parallel neighbors."""

    def __init__(self, num_hosts, devices=None):
        devices = list(devices if devices is not None else jax.devices())
        num_hosts = int(num_hosts)
        if num_hosts <= 0 or len(devices) % num_hosts:
            raise MXNetError(
                "cannot split %d devices across %d hosts evenly"
                % (len(devices), num_hosts))
        per = len(devices) // num_hosts
        self._slices = OrderedDict(
            (i, devices[i * per:(i + 1) * per]) for i in range(num_hosts))

    @classmethod
    def from_mesh(cls, mesh, num_hosts):
        """Slice the MESH's flattened device order (not jax.devices()):
        survivor meshes built from these slices preserve the original
        axis adjacency."""
        return cls(num_hosts, list(mesh.devices.reshape(-1)))

    @classmethod
    def from_processes(cls):
        """Real multi-host: one slot per JAX process, its local devices."""
        m = cls.__new__(cls)
        m._slices = OrderedDict()
        for d in sorted(jax.devices(),
                        key=lambda d: (d.process_index, d.id)):
            m._slices.setdefault(d.process_index, []).append(d)
        return m

    @property
    def num_hosts(self):
        return len(self._slices)

    def devices_for_survivors(self, lost):
        """Surviving devices in slice order. Unknown worker ids (e.g. a
        worker beyond this map's world) are ignored — membership may
        track more processes than hold mesh devices."""
        lost = {int(w) for w in lost}
        out = [d for i, devs in self._slices.items()
               if i not in lost for d in devs]
        if not out:
            raise MXNetError(
                "every mesh host is lost (%s) — nothing to reshard onto"
                % sorted(lost))
        return out


def plan_survivor_mesh(mesh, lost_workers, host_map, data_axis="data"):
    """The survivor mesh after ``lost_workers`` die: every non-data axis
    keeps its extent (tp groups stay intact for ICI adjacency, the pp
    stage count is preserved, the ep extent — and with it the expert
    partitioning — survives; experts are REMAPPED onto the survivor
    devices by the spill/restore), the data axis absorbs the loss.
    ``data_axis`` accepts the synonym vocabulary (``data``/``dp``) and
    falls back to whichever spelling the mesh actually uses. Raises
    typed when the surviving device count can't keep the non-data axes
    whole. Returns None when nothing changes."""
    if data_axis not in mesh.shape:
        from .unified import resolve_mesh_axis

        resolved = resolve_mesh_axis(mesh, "dp")
        if resolved is None:
            raise MXNetError("mesh %s has no %r axis to shrink"
                             % (dict(mesh.shape), data_axis))
        data_axis = resolved
    devices = host_map.devices_for_survivors(lost_workers)
    if len(devices) == mesh.devices.size:
        return None
    other = 1
    for ax in mesh.axis_names:
        if ax != data_axis:
            other *= mesh.shape[ax]
    if len(devices) % other:
        raise MXNetError(
            "%d surviving devices cannot keep the non-%s axes (extent %d) "
            "whole — survivors don't form a rectangular mesh"
            % (len(devices), data_axis, other))
    new_dp = len(devices) // other
    shape = tuple(new_dp if ax == data_axis else mesh.shape[ax]
                  for ax in mesh.axis_names)
    return Mesh(np.array(devices).reshape(shape), mesh.axis_names)


def reshard_step(step, new_mesh, spill_dir=None, warm=True):
    """Reshard a live ShardedTrainStep onto ``new_mesh`` in place:
    drain -> CheckpointManager spill -> rebind -> restore -> AOT warm.

    ``spill_dir``: where the transfer-format checkpoint lands (kept for
    the caller — e.g. as the restart point the acceptance test compares
    against); default is a temp dir removed after the reshard.
    Returns the reshard event dict (also emitted to telemetry)."""
    from .. import engine, telemetry
    from ..resilience import CheckpointManager

    # survivors drain the in-flight window first: every dispatched step
    # retires and its deferred bookkeeping lands before the mesh moves
    engine.wait_all()
    t0 = time.perf_counter()
    old_shape = {str(k): int(v) for k, v in step.mesh.shape.items()}
    tmp = None
    directory = spill_dir
    if directory is None:
        tmp = tempfile.mkdtemp(prefix="mxt_reshard_")
        directory = tmp
    cursor = step.step_count  # sync-ok: control-plane cursor read
    mgr = CheckpointManager(directory, net=step.block, trainer=step,
                            prefix="reshard", keep_last=1)
    mgr.save(step=cursor)
    try:
        # transfer=False: values ride the spill, not device-to-device
        # copies — the old mesh's hosts may be dead and their buffers
        # unreachable on a real pod
        step.rebind_mesh(new_mesh, transfer=False)
        restored = mgr.resume()
        if restored is None:
            raise MXNetError(
                "reshard spill under %r did not validate — params/state "
                "were NOT moved; the step still targets the new mesh but "
                "holds the old placement" % directory)
    finally:
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)
    warm_summary = None
    if warm:
        from .. import tuning

        # AOT-warm the resharded-shape program so the next training step
        # pays zero JIT (warmup also persists the tuning table)
        warm_summary = tuning.warmup(steps=[step], kernels=False,
                                     include_live=False, reason="reshard")
    dt = time.perf_counter() - t0
    telemetry.counter(
        "mxt_reshard_events_total",
        "In-place elastic mesh reshards (dead host absorbed without a "
        "job restart).").inc()
    telemetry.histogram(
        "mxt_reshard_seconds",
        "Drain + spill + rebind + restore (+ AOT warm) duration of one "
        "elastic reshard.").observe(dt)
    from .. import diagnostics

    # the whole reshard is lost wall-clock in the goodput ledger (the
    # event row lands in the flight recorder via the emit_event tap)
    diagnostics.record_lost("reshard", dt)
    event = {
        "old_shape": old_shape,
        "new_shape": {str(k): int(v) for k, v in new_mesh.shape.items()},
        "devices": int(new_mesh.devices.size),
        "step": cursor,
        "seconds": round(dt, 6),
        "warm_compiles": (warm_summary or {}).get("compiles"),
    }
    telemetry.emit_event("reshard", **event)
    return event


class ElasticReshardController:
    """Bridges membership death events to in-place mesh resharding.

    Attach to the server-side :class:`membership.MembershipTable` (the
    reaper thread invokes our listener when it fences workers), or feed
    worker-side ``members()`` views through :meth:`poll_view`. Deaths
    are only RECORDED on the notifying thread; the reshard itself runs
    in :meth:`maybe_reshard`, called by the training loop between steps
    — the loop owns the drain point.

    Usage::

        ctrl = ElasticReshardController(step, HostDeviceMap.from_mesh(
            step.mesh, num_hosts=4)).attach(table)
        for x, y in batches:          # batch size must divide every dp
            ctrl.maybe_reshard()      # no-op until the reaper fences
            loss = step(x, y)
    """

    def __init__(self, step, host_map, data_axis=None, spill_dir=None,
                 warm=True):
        self.step = step
        self.host_map = host_map
        # default to the step's own resolved data axis so a 4D
        # dp×tp×pp×ep mesh (whatever the dp axis is actually named)
        # shrinks the right dimension without the caller spelling it
        self.data_axis = (data_axis if data_axis is not None
                          else getattr(step, "data_axis", "data"))
        self.spill_dir = spill_dir
        self.warm = warm
        self.events = []
        self._pending = set()
        self._lost = set()
        self._lock = threading.Lock()

    def attach(self, table):
        """Subscribe to a MembershipTable's reaper."""
        table.add_death_listener(self.notice_deaths)
        return self

    def notice_deaths(self, worker_ids):
        """Record newly-fenced workers (any thread; reshard is deferred
        to maybe_reshard on the training loop)."""
        with self._lock:
            self._pending.update(int(w) for w in worker_ids)
            self._pending -= self._lost

    def poll_view(self, view):
        """Worker-side alternative to attach(): feed a membership view
        (``MembershipTable.view()`` / ``WorkerMembership.members()``)."""
        self.notice_deaths(view.get("dead", {}).keys())

    @property
    def pending(self):
        with self._lock:
            return set(self._pending)

    def maybe_reshard(self):
        """Reshard now if deaths are pending. Returns the reshard event
        (with the cumulative ``lost_workers``) or None. Call between
        steps; raises typed when survivors can't form a rectangular
        mesh (caller decides: wait for more deaths, or restart)."""
        with self._lock:
            if not self._pending:
                return None
            batch = set(self._pending)
            lost = self._lost | batch
        new_mesh = plan_survivor_mesh(self.step.mesh, lost, self.host_map,
                                      data_axis=self.data_axis)
        if new_mesh is None:
            with self._lock:
                self._lost |= batch
                self._pending -= batch
            return None
        event = reshard_step(self.step, new_mesh,
                             spill_dir=self.spill_dir, warm=self.warm)
        with self._lock:
            self._lost |= batch
            self._pending -= batch
            event["lost_workers"] = sorted(self._lost)
        self.events.append(event)
        return event
