"""Expert parallelism — Switch-style mixture-of-experts over a mesh
axis (new capability beyond the reference: SURVEY §2.4 strategy
inventory "Expert parallel / MoE: none in core").

Layout (the standard EP arrangement): the ``expert`` mesh axis carries
BOTH the token shards (data-parallel) and the experts — device e holds
1/E of the tokens and expert e. One `shard_map` does:

  gate (local) -> capacity-bounded one-hot dispatch (local einsum)
  -> `jax.lax.all_to_all` (tokens travel to their expert's device, ICI)
  -> expert_fn on the device's expert -> reverse all_to_all -> combine.

Everything is dense/static-shaped (the TPU-correct formulation: no
ragged gathers) and differentiable — gradients ride the reverse
all_to_alls. Tokens beyond an expert's capacity are dropped (their
combine weight is 0), exactly like Switch/GShard."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .sequence import shard_map  # version-compat resolved alias

from ..base import MXNetError

__all__ = ["moe_apply", "stack_expert_params", "switch_load_balance_loss"]


def stack_expert_params(per_expert_params):
    """Stacks identically-structured per-expert pytrees along a new
    leading axis (the ``expert``-sharded layout moe_apply expects)."""
    if not per_expert_params:
        raise MXNetError("need at least one expert")
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *per_expert_params)


def switch_load_balance_loss(gates, dispatch_mask):
    """Switch-Transformer aux loss: E * sum_e f_e * p_e where f_e is
    the fraction of tokens routed to expert e and p_e the mean gate
    probability (Fedus et al. 2101.03961)."""
    e = gates.shape[-1]
    f = dispatch_mask.sum(axis=tuple(range(dispatch_mask.ndim - 1)))
    f = f / jnp.maximum(dispatch_mask.sum(), 1.0)
    p = gates.mean(axis=tuple(range(gates.ndim - 1)))
    return e * jnp.sum(f * p)


def moe_apply(expert_fn, expert_params, gate_w, x, mesh, axis="expert",
              capacity_factor=1.25):
    """Routes tokens to experts over the ``axis`` mesh dimension.

    expert_fn(params_e, tokens) -> tokens' : one expert (a dense MLP in
    the standard Switch block), applied to a (capacity*E, D) slab.
    expert_params: pytree with leading axis E on every leaf.
    gate_w: (D, E) router weights.
    x: (N, D) tokens, N divisible by E (sharded over ``axis``).
    Returns (out (N, D), aux) with aux = (gates, dispatch_mask) for the
    load-balance loss.
    """
    if axis not in mesh.axis_names:
        raise MXNetError("mesh has no %r axis (axes: %s)"
                         % (axis, mesh.axis_names))
    n_exp = mesh.shape[axis]
    for leaf in jax.tree_util.tree_leaves(expert_params):
        if leaf.shape[0] != n_exp:
            raise MXNetError(
                "expert_params leading dim %d must equal the %r axis "
                "size %d (one expert per device)"
                % (leaf.shape[0], axis, n_exp))
    n = x.shape[0]
    if n % n_exp:
        raise MXNetError("token count %d not divisible by %d experts"
                         % (n, n_exp))
    n_local = n // n_exp
    # ceil so the factor always buys headroom (Switch/GShard rounding)
    capacity = max(1, -(-int(n_local * capacity_factor) // n_exp))

    def per_device(params, wg, xs):  # xs: (n_local, D); params (1,...)
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        gates = jax.nn.softmax(xs @ wg, axis=-1)       # (n, E)
        expert_idx = jnp.argmax(gates, axis=-1)        # top-1 routing
        onehot = jax.nn.one_hot(expert_idx, n_exp, dtype=xs.dtype)
        # position of each token within its expert's capacity;
        # one_hot is all-zero for positions >= capacity, which IS the
        # over-capacity drop
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot   # (n, E)
        pos_oh = jax.nn.one_hot(pos.sum(-1).astype(jnp.int32),
                                capacity, dtype=xs.dtype)
        dispatch = onehot[:, :, None] * pos_oh[:, None, :]  # (n, E, C)
        gate_val = (gates * onehot).sum(-1)            # (n,)

        slabs = jnp.einsum("nec,nd->ecd", dispatch, xs)  # (E, C, D)
        # tokens travel to their expert's device (one ICI all-to-all)
        recv = jax.lax.all_to_all(slabs, axis, split_axis=0,
                                  concat_axis=0, tiled=True)
        # this device's expert processes everyone's slab for expert e
        out = expert_fn(params, recv.reshape(-1, recv.shape[-1]))
        out = out.reshape(recv.shape[:-1] + (out.shape[-1],))
        back = jax.lax.all_to_all(out, axis, split_axis=0,
                                  concat_axis=0, tiled=True)
        combined = jnp.einsum("nec,ecd->nd", dispatch, back)
        combined = combined * gate_val[:, None]
        return (combined[None], gates[None],
                dispatch.sum(-1)[None])  # lead axis for out_specs

    sm = shard_map(
        per_device, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(axis),
                                         expert_params), P(), P(axis)),
        out_specs=(P(axis), P(axis), P(axis)))
    out, gates, mask = sm(expert_params, gate_w, x)
    return out.reshape(x.shape[0], -1), (
        gates.reshape(x.shape[0], -1), mask.reshape(x.shape[0], -1))
