"""Parallelism over device meshes — the TPU-native replacement for the
reference's KVStore/ps-lite/NCCL stack (ref: SURVEY §2.4/§5).

Design: pick a Mesh, annotate shardings, let XLA insert collectives over
ICI/DCN (psum/all_gather/reduce_scatter compiled into the step) — instead of
translating worker/server push/pull. The KVStore API survives as a facade
(mxnet_tpu/kvstore.py); this package holds the real machinery:

- mesh.py: mesh construction + distributed init (multi-host)
- sharded.py: sharded training-step builder over Gluon blocks
  (data/tensor parallel via PartitionSpec rules; ZeRO-1/2/3
  weight-update sharding over the data axis)
- reshard.py: elastic in-place mesh resharding when membership fences
  a dead host (CheckpointManager shards as the transfer format)
- unified.py: 4D composition — pipeline stages + MoE experts as
  rule-sharded stacked params on a dp×tp×pp×ep mesh, trained by the
  SAME one-launch ShardedTrainStep (no eager island dispatch)
"""
from .mesh import (
    make_mesh, data_parallel_mesh, init_distributed, local_device_count,
)
from .sharded import (
    ShardedTrainStep, shard_params, sharding_rule, allreduce_across_processes,
)
from .reshard import (
    ElasticReshardController, HostDeviceMap, plan_survivor_mesh,
    reshard_step,
)
from .sequence import (current_sequence_scope, ring_attention,
                       sequence_scope, ulysses_attention)
from .pipeline import pipeline_apply, stack_stage_params
from .moe import moe_apply, stack_expert_params, switch_load_balance_loss
from .unified import (
    PipelineMoEBlock, pipeline_moe_forward, publish_moe_telemetry,
    moe_capacity, resolve_mesh_axis,
)

__all__ = ["make_mesh", "data_parallel_mesh", "init_distributed",
           "local_device_count", "ShardedTrainStep", "shard_params",
           "sharding_rule", "allreduce_across_processes",
           "ElasticReshardController", "HostDeviceMap",
           "plan_survivor_mesh", "reshard_step", "ring_attention",
           "ulysses_attention", "pipeline_apply", "stack_stage_params",
           "moe_apply", "stack_expert_params",
           "switch_load_balance_loss", "sequence_scope",
           "current_sequence_scope", "PipelineMoEBlock",
           "pipeline_moe_forward", "publish_moe_telemetry",
           "moe_capacity", "resolve_mesh_axis"]
