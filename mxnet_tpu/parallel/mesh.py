"""Device mesh construction and multi-host initialization.

Replaces the reference's cluster topology layer (ref: ps-lite Postoffice
membership + tools/launch.py tracker): on TPU the "cluster" is a slice, and
jax.distributed.initialize + a Mesh over all devices is the whole story.
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

from ..base import MXNetError

__all__ = ["make_mesh", "data_parallel_mesh", "init_distributed",
           "local_device_count"]


def local_device_count():
    return jax.local_device_count()


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None):
    """Multi-host init (ref: the DMLC_PS_ROOT_URI/DMLC_ROLE rendezvous in
    ps-lite — here a single coordinator handshake).

    No-arg form reads the MXT_* env set by tools/launch.py, falling back
    to the standard JAX env (or cloud TPU metadata)."""
    import os

    if coordinator_address is None:
        coordinator_address = os.environ.get("MXT_COORDINATOR")
        if coordinator_address is not None:
            num_processes = int(os.environ["MXT_NUM_WORKERS"])
            process_id = int(os.environ["MXT_WORKER_ID"])
    if coordinator_address is None:
        jax.distributed.initialize()
    else:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)


def make_mesh(shape=None, axis_names=("data", "model"), devices=None):
    """Build a Mesh over the (global) device list.

    ``shape`` of -1 entries auto-fills like reshape; default puts every
    device on the data axis. On a pod slice the device order from
    jax.devices() is ICI-contiguous, so adjacent mesh coordinates ride ICI
    rather than DCN — keep the fastest-varying axis the model axis.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if shape is None:
        shape = (n,) + (1,) * (len(axis_names) - 1)
    shape = list(shape)
    if shape.count(-1) > 1:
        raise MXNetError("at most one mesh axis may be -1")
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        if n % known:
            raise MXNetError(
                "cannot infer mesh axis: %d devices not divisible by %d"
                % (n, known))
        shape[shape.index(-1)] = n // known
    if int(np.prod(shape)) != n:
        raise MXNetError(
            "mesh shape %s does not cover %d devices" % (tuple(shape), n))
    arr = np.array(devices).reshape(tuple(shape))
    return Mesh(arr, tuple(axis_names))


def data_parallel_mesh(devices=None):
    return make_mesh(axis_names=("data",), devices=devices)
