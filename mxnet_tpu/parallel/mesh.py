"""Device mesh construction and multi-host initialization.

Replaces the reference's cluster topology layer (ref: ps-lite Postoffice
membership + tools/launch.py tracker): on TPU the "cluster" is a slice, and
jax.distributed.initialize + a Mesh over all devices is the whole story.

Multi-host flow (the GSPMD scale-out contract — one script, any size):

    tools/launch.py -n 16 --launcher ssh -H hosts \\
        --mesh 64,2 --zero-stage 2 python train.py

Each worker process gets ``MXT_COORDINATOR`` / ``MXT_NUM_WORKERS`` /
``MXT_WORKER_ID`` (consumed by :func:`init_distributed`) plus
``MXT_MESH_SHAPE`` / ``MXT_MESH_AXES`` / ``MXT_ZERO_STAGE`` — so
``train.py`` calls ``parallel.make_mesh()`` with NO arguments and gets
the launch-line mesh over the GLOBAL device list, whether that is 8
virtual CPU devices in one process or a pod slice across 16 hosts.
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

from ..base import MXNetError

__all__ = ["make_mesh", "data_parallel_mesh", "init_distributed",
           "local_device_count"]


def local_device_count():
    return jax.local_device_count()


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None):
    """Multi-host init (ref: the DMLC_PS_ROOT_URI/DMLC_ROLE rendezvous in
    ps-lite — here a single coordinator handshake).

    No-arg form reads the MXT_* env set by tools/launch.py, falling back
    to the standard JAX env (or cloud TPU metadata). After this returns,
    ``jax.devices()`` is the GLOBAL device list and :func:`make_mesh`
    builds process-spanning meshes over it."""
    import os

    if coordinator_address is None:
        coordinator_address = os.environ.get("MXT_COORDINATOR")
        if coordinator_address is not None:
            num_processes = int(os.environ["MXT_NUM_WORKERS"])
            process_id = int(os.environ["MXT_WORKER_ID"])
    if coordinator_address is None:
        jax.distributed.initialize()
    else:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)


def make_mesh(shape=None, axis_names=("data", "model", "pipe", "expert"),
              devices=None):
    """Build a Mesh over the (global) device list.

    ``shape`` of -1 entries auto-fills like reshape; default puts every
    device on the data axis. With no ``shape``, ``MXT_MESH_SHAPE`` (and
    optionally ``MXT_MESH_AXES``) is consulted first — tools/launch.py
    exports it per worker from its ``--mesh`` flag, so the same training
    script scales from 1 host to N by changing only the launch line.

    The default axis vocabulary is the full 4D story —
    ``(data, model, pipe, expert)`` — and ``axis_names`` is truncated to
    the rank of ``shape``, so ``--mesh 8`` is pure dp, ``--mesh 4,2`` is
    dp×tp, and ``--mesh 2,1,2,2`` is dp×tp×pp×ep with no ``--mesh-axes``
    needed. Pass MXT_MESH_AXES / ``axis_names`` to rename (the short
    forms ``dp,tp,pp,ep`` are understood everywhere an axis role is
    resolved — see parallel/unified.py).

    On a pod slice the device order from jax.devices() is ICI-contiguous,
    so adjacent mesh coordinates ride ICI rather than DCN — keep the
    fastest-varying axis the model axis.
    """
    if shape is None:
        from .. import config

        spec = config.get("MXT_MESH_SHAPE")
        if spec:
            shape = tuple(int(s) for s in str(spec).split(",") if s)
            axes = config.get("MXT_MESH_AXES")
            if axes:
                axis_names = tuple(a.strip() for a in str(axes).split(",")
                                   if a.strip())
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if shape is None:
        # No shape anywhere: everything data-parallel. Cap the implied
        # rank at 2 so the no-arg mesh stays the classic (n, 1)
        # data×model — extra axes appear only when a shape asks for them.
        shape = (n,) + (1,) * (min(len(axis_names), 2) - 1)
    shape = list(shape)
    if len(shape) != len(axis_names):
        if len(shape) < len(axis_names):
            axis_names = tuple(axis_names)[:len(shape)]
        else:
            raise MXNetError(
                "mesh shape %s has %d axes but axis_names=%s names %d "
                "(set MXT_MESH_AXES alongside MXT_MESH_SHAPE)"
                % (tuple(shape), len(shape), tuple(axis_names),
                   len(axis_names)))
    if shape.count(-1) > 1:
        raise MXNetError("at most one mesh axis may be -1")
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        if n % known:
            raise MXNetError(
                "cannot infer mesh axis: %d devices not divisible by %d"
                % (n, known))
        shape[shape.index(-1)] = n // known
    if int(np.prod(shape)) != n:
        raise MXNetError(
            "mesh shape %s does not cover %d devices" % (tuple(shape), n))
    arr = np.array(devices).reshape(tuple(shape))
    return Mesh(arr, tuple(axis_names))


def data_parallel_mesh(devices=None):
    return make_mesh(axis_names=("data",), devices=devices)
