"""Resilience — skip-step guards, atomic checkpoints, and fault-tolerant
KVStore plumbing for long-running training.

The north-star system trains for days; three failure modes dominate real
pods and each gets a pillar here:

1. **Non-finite step guard.** A single NaN/Inf batch silently corrupts
   weights and every step after it. With ``MXT_SKIP_NONFINITE=1`` the
   optimizer update is skipped whenever any gradient is non-finite: the
   eager ``Trainer.step``/``Module.update`` paths run one fused
   ``multi_all_finite`` check (ref: src/operator/contrib/all_finite.cc —
   the machinery behind AMP's dynamic loss scaling), and the fused
   ``CachedTrainStep`` compiles the check *into* the one-launch program
   via ``jax.lax.cond`` so the guard costs zero extra launches — the
   weight/state/aux update is the identity on overflow, the step counter
   does not advance, and the flag comes back as one extra output (one
   host read). Skipped steps land in the ``skipped_nonfinite_steps``
   profiler counter.

2. **Atomic checkpoint + auto-resume.** :class:`CheckpointManager`
   writes net params + ``Trainer.save_states`` + the epoch/step cursor +
   loss-scale + PRNG state as ONE manifest with per-file CRC32, via
   tmp-file → fsync → ``os.replace`` (crash-safe at any byte: a reader
   only trusts checkpoints whose manifest exists and whose CRCs verify).
   Keep-last-K rotation bounds disk; :meth:`CheckpointManager.resume`
   restores everything — including fused-step re-eligibility, since
   ``Trainer.load_states`` keeps optimizer update counts even and
   ``CachedTrainStep`` rebuilds against the swapped optimizer object.

3. **KVStore retry.** :func:`kv_retry` wraps network-facing kvstore ops
   (dist push reductions, every ``AsyncClient`` request) in exponential
   backoff + jitter with bounded retries and a per-op deadline; a server
   that is truly gone surfaces as a clean :class:`KVStoreError` instead
   of a hang.

Everything above is testable deterministically through the ``MXT_FAULT``
hook: a seeded injector that drops sockets, delays acks, and crashes
checkpoint writes at named points.

``MXT_FAULT`` grammar (semicolon-separated rules)::

    kv_drop:p=0.5,seed=7,n=10    # drop kvstore ops w.p. 0.5 (max 10)
    kv_delay:p=0.2,ms=5,seed=1   # delay acks 5 ms w.p. 0.2
    ckpt_crash:at=manifest,n=1   # SimulatedCrash at a checkpoint phase
                                 # (at= params | states | manifest | rotate)
    hb_drop:p=0.5,seed=3         # lose membership heartbeats on the wire
    worker_freeze:worker=2,after=1  # freeze worker 2's heartbeat thread
                                 # after 1 beat (zombie: process lives,
                                 # server declares it dead and fences it)
    rejoin_race:ms=30            # widen the server-side window between
                                 # fencing the old generation and
                                 # answering a re-registration
    replica_kill:replica=1,after=6  # kill serving replica 1 at its 6th
                                 # router tick (ungraceful: in-flight
                                 # requests fail over to survivors)
    replica_slow:replica=0,ms=500   # stall serving replica 0's decode
                                 # for 500 ms (the router's hedge bait)
    traffic_storm:rps=200,after=5   # flash crowd: the synthetic serving
                                 # TrafficGenerator jumps to 200 req/s
                                 # at its 5th tick (optional tenant=T
                                 # attributes the whole storm to one
                                 # tenant — the QoS isolation stressor)
    replica_spawn_slow:ms=250    # every autoscaler-spawned spare takes
                                 # 250 ms extra to warm before it may
                                 # go routable (the router must keep
                                 # serving off the existing tier)
    grad_spike:layer=0,after=3,scale=1e6,n=1  # multiply layer 0's
                                 # gradient by 1e6 ON DEVICE once the
                                 # fused step's dispatch count passes 3
                                 # (the scale rides the program as a
                                 # traced scalar, 1.0 on non-firing
                                 # steps) — the seeded anomaly the
                                 # training-health detectors
                                 # (health.py) must catch within one
                                 # InflightWindow retirement

``p`` defaults to 1.0, ``n`` (max firings) to unlimited, ``seed`` to 0.
One injector instance lives per distinct spec string so the drawn
sequence is reproducible; :func:`reset_faults` rewinds it.
"""
from __future__ import annotations

import json
import os
import random as _pyrandom
import time
import zlib
from collections import namedtuple

from .base import MXNetError

__all__ = [
    "KVStoreError", "SimulatedCrash", "FaultInjector", "reset_faults",
    "fault_point", "crash_point", "RetryPolicy", "kv_retry",
    "skip_nonfinite_enabled", "all_finite", "record_skipped_step",
    "skipped_step_count", "CheckpointManager", "ResumeState",
]


class KVStoreError(MXNetError):
    """A kvstore network operation failed permanently: retries/backoff
    were exhausted or the per-op deadline passed. Raised instead of
    letting a dead server hang the worker."""


class SimulatedCrash(RuntimeError):
    """Raised by the ``MXT_FAULT`` ``ckpt_crash`` rule to emulate the
    process being killed at a specific byte of a checkpoint write.
    Deliberately NOT an MXNetError: production code must never catch it
    accidentally — only the test harness does."""


# --------------------------------------------------------------------------
# fault injection
# --------------------------------------------------------------------------
class FaultInjector:
    """Deterministic (seeded) fault source parsed from an MXT_FAULT spec."""

    def __init__(self, spec):
        self.spec = spec
        self._rules = {}
        self._rng = {}
        self._fired = {}
        for part in filter(None, (s.strip() for s in spec.split(";"))):
            kind, _, body = part.partition(":")
            kind = kind.strip()
            params = {}
            for kv in filter(None, (s.strip() for s in body.split(","))):
                k, _, v = kv.partition("=")
                params[k.strip()] = v.strip()
            self._rules[kind] = params
            self._rng[kind] = _pyrandom.Random(int(params.get("seed", 0)))
            self._fired[kind] = 0

    def rule(self, kind):
        return self._rules.get(kind)

    def should(self, kind):
        """Draw the (seeded) dice for ``kind``; respects the ``n`` cap."""
        params = self._rules.get(kind)
        if params is None:
            return False
        cap = params.get("n")
        if cap is not None and self._fired[kind] >= int(cap):
            return False
        p = float(params.get("p", 1.0))
        if p < 1.0 and self._rng[kind].random() >= p:
            return False
        self._fired[kind] += 1
        return True

    def maybe_delay(self):
        """Sleep if a kv_delay rule fires (delayed-ack emulation)."""
        if self.should("kv_delay"):
            ms = float(self._rules["kv_delay"].get("ms", 1.0))
            time.sleep(ms / 1e3)

    def maybe_drop(self):
        """Raise ConnectionError if a kv_drop rule fires — the injected
        socket drop rides the SAME retry path real drops do."""
        if self.should("kv_drop"):
            raise ConnectionError(
                "injected socket drop (MXT_FAULT %r)" % self.spec)

    def crash_point(self, point):
        """Raise SimulatedCrash if a ckpt_crash rule targets ``point``."""
        params = self._rules.get("ckpt_crash")
        if params is not None and params.get("at") == point \
                and self.should("ckpt_crash"):
            raise SimulatedCrash(
                "injected crash at checkpoint phase %r (MXT_FAULT %r)"
                % (point, self.spec))


class _NullInjector:
    spec = ""

    @staticmethod
    def rule(kind):
        return None

    @staticmethod
    def should(kind):
        return False

    @staticmethod
    def maybe_delay():
        pass

    @staticmethod
    def maybe_drop():
        pass

    @staticmethod
    def crash_point(point):
        pass


_NULL = _NullInjector()
_injectors = {}  # spec string -> FaultInjector (RNG state persists)


def _fault():
    from . import config

    spec = config.get("MXT_FAULT")
    if not spec:
        return _NULL
    if spec not in _injectors:
        _injectors[spec] = FaultInjector(spec)
    return _injectors[spec]


def fault_point():
    """The active injector (a no-op singleton when MXT_FAULT is unset)."""
    return _fault()


def crash_point(point):
    """Module-level shorthand: raise SimulatedCrash when the active
    MXT_FAULT targets checkpoint phase ``point``."""
    _fault().crash_point(point)


def reset_faults():
    """Forget cached injectors so a re-used spec re-seeds from scratch
    (test isolation helper)."""
    _injectors.clear()


# --------------------------------------------------------------------------
# retry policy
# --------------------------------------------------------------------------
class RetryPolicy:
    """Exponential backoff + jitter with bounded retries and a deadline."""

    def __init__(self, retries=4, base=0.05, max_delay=2.0, deadline=30.0,
                 jitter=0.1):
        self.retries = int(retries)
        self.base = float(base)
        self.max_delay = float(max_delay)
        self.deadline = float(deadline)
        self.jitter = float(jitter)

    @classmethod
    def from_config(cls):
        from . import config

        return cls(retries=config.get("MXT_KV_RETRIES"),
                   base=config.get("MXT_KV_RETRY_BASE"),
                   max_delay=config.get("MXT_KV_RETRY_MAX"),
                   deadline=config.get("MXT_KV_DEADLINE"))

    def delay(self, attempt):
        """Backoff before retry ``attempt`` (1-based): base·2^(a-1),
        capped, plus up to ``jitter`` fraction of random spread so a
        fleet of workers doesn't reconnect in lockstep."""
        d = min(self.base * (2.0 ** (attempt - 1)), self.max_delay)
        return d * (1.0 + self.jitter * _pyrandom.random())


def _record_kv_death(op, key, why, exc):
    """Flight-recorder event for a permanently failed kvstore op — the
    post-mortem names the RPC that killed the run."""
    try:
        from . import diagnostics

        diagnostics.record_event("kv_retry_exhausted", op=str(op),
                                 key=str(key), why=why,
                                 error=str(exc)[:200])
    except Exception:  # noqa: BLE001 — diagnostics never masks the error
        pass


def kv_retry(op, key, fn, reconnect=None, policy=None):
    """Run kvstore op ``fn`` under the retry policy with fault injection.

    Connection-shaped failures (ConnectionError/OSError — including the
    injected drops from ``MXT_FAULT``) are retried with exponential
    backoff; ``reconnect`` (if given) is invoked between attempts to
    re-establish the transport. Bounded by both the retry count and the
    per-op deadline; exhaustion raises :class:`KVStoreError` — the
    worker never hangs on a dead server. ``fn`` must be idempotent up to
    the failure point (callers inject/mutate state only after the
    network step succeeds)."""
    policy = policy or RetryPolicy.from_config()
    inj = _fault()
    deadline_ts = time.monotonic() + policy.deadline
    attempt = 0
    while True:
        try:
            inj.maybe_drop()
            inj.maybe_delay()
            return fn()
        except (ConnectionError, OSError) as e:
            attempt += 1
            from . import telemetry

            telemetry.counter(
                "mxt_kvstore_retry_total",
                "KVStore network-op retry attempts (connection-shaped "
                "failures riding the backoff policy).",
                ("op",)).labels(str(op)).inc()
            if attempt > policy.retries:
                _record_kv_death(op, key, "retries_exhausted", e)
                raise KVStoreError(
                    "kvstore %s(%r) failed after %d retries: %s"
                    % (op, key, policy.retries, e)) from e
            d = policy.delay(attempt)
            if time.monotonic() + d > deadline_ts:
                _record_kv_death(op, key, "deadline", e)
                raise KVStoreError(
                    "kvstore %s(%r) exceeded its %.1fs deadline "
                    "(attempt %d): %s"
                    % (op, key, policy.deadline, attempt, e)) from e
            time.sleep(d)
            if reconnect is not None:
                try:
                    reconnect()
                except (OSError, MXNetError) as re:
                    # the transport cannot come back — the server is
                    # truly gone; fail cleanly rather than spinning out
                    # the remaining budget
                    _record_kv_death(op, key, "reconnect_failed", re)
                    raise KVStoreError(
                        "kvstore %s(%r): reconnect failed, server "
                        "unreachable: %s" % (op, key, re)) from re


# --------------------------------------------------------------------------
# non-finite step guard helpers
# --------------------------------------------------------------------------
def skip_nonfinite_enabled():
    from . import config

    return bool(config.get("MXT_SKIP_NONFINITE"))


def all_finite(arrays):
    """True iff every element of every array is finite. ONE fused device
    check + one host read for the whole set (ref: all_finite.cc —
    MultiAllFinite), same machinery amp.LossScaler.has_overflow uses."""
    from .ndarray.ndarray import NDArray

    flat = []
    for a in arrays:
        if hasattr(a, "_values"):  # row_sparse: check the stored values
            v = a._values
            flat.append(v if isinstance(v, NDArray) else NDArray(v))
        else:
            flat.append(a if isinstance(a, NDArray) else NDArray(a))
    if not flat:
        return True
    from . import nd

    flag = nd.multi_all_finite(*flat, num_arrays=len(flat))
    return float(flag.asnumpy()[0]) == 1.0


_SKIP_COUNTER = "skipped_nonfinite_steps"
_skip_counter = None


def record_skipped_step(n=1):
    """Bump the skipped-step profiler counter (shows in profiler.dumps())."""
    global _skip_counter
    from . import profiler

    if _skip_counter is None or _SKIP_COUNTER not in profiler._counters:
        _skip_counter = profiler.Counter(None, _SKIP_COUNTER)
    _skip_counter.increment(n)


def skipped_step_count():
    """Skipped steps so far. Reading the counter is a sync point: the
    async engine's deferred guard flags are drained first, so the value
    reflects every step DISPATCHED (not just observed) when called."""
    from . import engine

    engine.wait_all()
    from . import profiler

    return profiler.counter_value(_SKIP_COUNTER)


# --------------------------------------------------------------------------
# atomic checkpoint + auto-resume
# --------------------------------------------------------------------------
ResumeState = namedtuple("ResumeState",
                         ["epoch", "step", "extra", "tag", "manifest"])

_MANIFEST_SUFFIX = ".manifest.json"
_FORMAT_VERSION = 1


def _crc_file(path):
    crc = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def _fsync_path(path):
    with open(path, "rb") as f:
        os.fsync(f.fileno())


def _dir_fsync(path):
    """Durably record renames in the directory entry (best-effort on
    platforms whose directory fds reject fsync)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _publish(tmp, final):
    """tmp → fsync → atomic rename: ``final`` either doesn't exist or is
    the complete new content, at every possible crash byte."""
    _fsync_path(tmp)
    os.replace(tmp, final)


class CheckpointManager:
    """Atomic full-training-state checkpoints with keep-last-K rotation.

    Unlike the symbolic ``save_checkpoint`` (model.py — params + symbol
    only) and bare ``Trainer.save_states`` (optimizer state only), one
    ``save()`` captures the WHOLE run: net parameters, trainer/optimizer
    state, the epoch/step cursor, AMP loss-scale, and the global PRNG
    state — published as payload files plus one CRC-carrying manifest.
    Write order is payloads → manifest, every file via tmp + fsync +
    ``os.replace``; a crash at any byte leaves either the previous
    checkpoint set or the complete new one, never a torn state visible
    to :meth:`resume` (which also re-verifies sizes + CRC32 so torn or
    bit-rotted payloads demote to the previous checkpoint).

    Usage::

        mgr = resilience.CheckpointManager("ckpts", net=net,
                                           trainer=trainer, keep_last=3)
        start = 0
        state = mgr.resume()
        if state is not None:
            start = state.step          # params/opt/PRNG already restored
        for t in range(start, steps):
            step(x_t, y_t)
            mgr.save(step=t + 1)
    """

    def __init__(self, directory, net=None, trainer=None, prefix="ckpt",
                 keep_last=3):
        self.directory = str(directory)
        self.net = net
        self.trainer = trainer
        self.prefix = prefix
        self.keep_last = max(1, int(keep_last))
        os.makedirs(self.directory, exist_ok=True)

    # -- write -------------------------------------------------------------
    def _tag(self, step):
        return "%s-%010d" % (self.prefix, step)

    def save(self, epoch=0, step=0, extra=None, net=None, trainer=None):
        """Publish one atomic checkpoint for cursor ``(epoch, step)``.
        ``extra`` is any JSON-serializable payload riding the manifest
        (e.g. dataloader cursor). Returns the manifest path."""
        net = net if net is not None else self.net
        trainer = trainer if trainer is not None else self.trainer
        # drain the async dispatch window: in-flight steps finish and
        # their deferred bookkeeping (update counts, loss-scale, skip
        # counter) lands, so the snapshot is internally consistent —
        # weights, optimizer state, and counts all describe the same step
        from . import engine

        engine.wait_all()
        _save_t0 = time.perf_counter()
        inj = _fault()
        tag = self._tag(step)
        files = {}

        def _payload(name, writer, phase):
            final = os.path.join(self.directory, name)
            tmp = final + ".tmp"
            writer(tmp)
            inj.crash_point(phase)  # kill BEFORE publish: final untouched
            _publish(tmp, final)
            files[name] = {"crc32": _crc_file(final),
                           "size": os.path.getsize(final)}

        if net is not None:
            _payload(tag + ".params", net.save_parameters, "params")
        if trainer is not None:
            _payload(tag + ".states", trainer.save_states, "states")

        from . import random as _random

        scaler = getattr(trainer, "_amp_scaler", None) \
            if trainer is not None else None
        meta = {
            "format": _FORMAT_VERSION,
            "tag": tag,
            "epoch": int(epoch),
            "step": int(step),
            "time": time.time(),
            "loss_scale": scaler.state_dict() if scaler is not None
            else None,
            "prng": _random.get_state(),
            "extra": extra,
            "files": files,
        }
        manifest = os.path.join(self.directory, tag + _MANIFEST_SUFFIX)
        tmp = manifest + ".tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps(meta, indent=1, sort_keys=True))
            f.flush()
            os.fsync(f.fileno())
        inj.crash_point("manifest")
        os.replace(tmp, manifest)
        _dir_fsync(self.directory)
        inj.crash_point("rotate")
        self._rotate()
        from . import telemetry

        dt = time.perf_counter() - _save_t0
        telemetry.histogram(
            "mxt_checkpoint_save_seconds",
            "Atomic checkpoint publish duration (payloads + manifest + "
            "rotation; excludes the window drain).").observe(dt)
        telemetry.emit_event("checkpoint_save", tag=tag, step=int(step),
                             epoch=int(epoch), seconds=round(dt, 6))
        from . import diagnostics

        diagnostics.record_lost("checkpoint", dt)
        return manifest

    def _rotate(self):
        entries = self.checkpoints()
        for meta, manifest in entries[:-self.keep_last]:
            # manifest first: the checkpoint becomes invisible atomically,
            # then its payloads are garbage and safe to delete
            for path in [manifest] + [
                    os.path.join(self.directory, n)
                    for n in meta.get("files", {})]:
                try:
                    os.remove(path)
                except OSError:
                    pass

    # -- read --------------------------------------------------------------
    def _validate(self, manifest):
        """Parsed meta if the manifest and every payload verify, else
        None (truncated/corrupt checkpoints demote silently)."""
        try:
            with open(manifest) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            return None
        if meta.get("format") != _FORMAT_VERSION:
            return None
        for name, want in meta.get("files", {}).items():
            path = os.path.join(self.directory, name)
            try:
                if os.path.getsize(path) != want["size"] or \
                        _crc_file(path) != want["crc32"]:
                    return None
            except OSError:
                return None
        return meta

    def checkpoints(self):
        """[(meta, manifest_path)] for every VALID checkpoint, oldest
        first (step order)."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        pre = self.prefix + "-"
        for name in names:
            if not (name.startswith(pre)
                    and name.endswith(_MANIFEST_SUFFIX)):
                continue
            manifest = os.path.join(self.directory, name)
            meta = self._validate(manifest)
            if meta is not None:
                out.append((meta, manifest))
        out.sort(key=lambda e: e[0]["step"])
        return out

    def latest(self):
        """Meta of the newest valid checkpoint, or None."""
        entries = self.checkpoints()
        return entries[-1][0] if entries else None

    def resume(self, net=None, trainer=None):
        """Restore the newest valid checkpoint. Loads params into the
        net, optimizer state into the trainer (``load_states`` keeps the
        fused step re-eligible: update counts stay even and the fused
        program rebuilds against the swapped optimizer), the AMP
        loss-scale, and the PRNG state. Returns a :class:`ResumeState`
        cursor, or None when no valid checkpoint exists."""
        net = net if net is not None else self.net
        trainer = trainer if trainer is not None else self.trainer
        # a live run resuming over itself must not race its own window:
        # drain in-flight steps before overwriting params/opt state
        from . import engine

        engine.wait_all()
        _restore_t0 = time.perf_counter()
        entries = self.checkpoints()
        if not entries:
            return None
        meta, manifest = entries[-1]
        tag = meta["tag"]
        if net is not None and (tag + ".params") in meta["files"]:
            net.load_parameters(os.path.join(self.directory,
                                             tag + ".params"))
        if trainer is not None and (tag + ".states") in meta["files"]:
            trainer.load_states(os.path.join(self.directory,
                                             tag + ".states"))
        if trainer is not None and meta.get("loss_scale") is not None:
            scaler = getattr(trainer, "_amp_scaler", None)
            if scaler is None:
                from .amp import LossScaler

                scaler = LossScaler()
                trainer._amp_scaler = scaler
            scaler.load_state_dict(meta["loss_scale"])
        if meta.get("prng") is not None:
            from . import random as _random

            _random.set_state(meta["prng"])
        from . import telemetry

        dt = time.perf_counter() - _restore_t0
        telemetry.histogram(
            "mxt_checkpoint_restore_seconds",
            "Checkpoint validate + restore duration (params, optimizer "
            "state, loss-scale, PRNG).").observe(dt)
        telemetry.emit_event("checkpoint_restore", tag=tag,
                             step=meta["step"], epoch=meta["epoch"],
                             seconds=round(dt, 6))
        from . import diagnostics

        diagnostics.record_lost("checkpoint", dt)
        return ResumeState(epoch=meta["epoch"], step=meta["step"],
                           extra=meta.get("extra"), tag=tag,
                           manifest=manifest)
