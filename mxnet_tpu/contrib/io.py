"""contrib.io (ref: python/mxnet/contrib/io.py): DataLoaderIter wraps a
gluon DataLoader as a classic DataIter so Module.fit can drive
gluon-style datasets."""
from __future__ import annotations

from ..io.io import DataBatch, DataDesc, DataIter

__all__ = ["DataLoaderIter"]


class DataLoaderIter(DataIter):
    """ref: contrib/io.py — DataLoaderIter. Infers provide_data /
    provide_label from the first batch; the loader must yield
    (data, label) pairs of NDArrays (or lists of them)."""

    def __init__(self, loader, data_name="data", label_name="softmax_label",
                 dtype="float32"):
        super().__init__()
        self._loader = loader
        self._iter = iter(loader)
        self._dtype = dtype
        self._data_name = data_name
        self._label_name = label_name

        first = next(self._iter)
        data, label = self._as_pair(first)
        self.batch_size = data[0].shape[0]

        def _descs(arrays, name):
            # multi-array loaders need distinct names (Module binds by
            # name); single-array keeps the plain name like NDArrayIter
            if len(arrays) == 1:
                return [DataDesc(name, arrays[0].shape, dtype)]
            return [DataDesc("_%d_%s" % (i, name), a.shape, dtype)
                    for i, a in enumerate(arrays)]

        self.provide_data = _descs(data, data_name)
        self.provide_label = _descs(label, label_name)
        self._pending = first

    @staticmethod
    def _as_pair(batch):
        data, label = batch
        if not isinstance(data, (list, tuple)):
            data = [data]
        if not isinstance(label, (list, tuple)):
            label = [label]
        return data, label

    def reset(self):
        self._iter = iter(self._loader)
        self._pending = None

    def next(self):
        if self._pending is not None:
            batch, self._pending = self._pending, None
        else:
            batch = next(self._iter)
        data, label = self._as_pair(batch)
        return DataBatch(list(data), list(label), pad=0)
