"""Post-training int8 quantization (ref: python/mxnet/contrib/
quantization.py — quantize_model / quantize_graph;
src/operator/quantization/quantize_graph_pass.cc).

Flow, mirroring the reference:

1. **Calibrate** (``calib_mode='naive'``): bind a Group symbol over every
   tensor that will cross a float↔int8 boundary and stream
   ``calib_data`` through it, tracking per-tensor min/max
   (``calib_mode='entropy'`` refines the range by KL-divergence threshold
   search over a histogram, ref: _LayerHistogramCollector/
   _get_optimal_threshold).
2. **Quantize weights offline**: each target layer's weight becomes an
   int8 param ``<name>_quantize`` plus ``<name>_quantize_min/max`` range
   params (ref: quantize_params).
3. **Rewrite the graph**: Convolution/FullyConnected become
   quantized_conv / quantized_fully_connected (s8×s8→s32 on the MXU)
   bracketed by quantize_v2 / requantize / dequantize; Pooling and
   Flatten between quantized layers ride the int8 triple directly
   (quantize_graph_pass's passthrough list), and adjacent
   dequantize→quantize_v2 pairs never materialize because each rewritten
   tensor keeps its int8 triple alongside its f32 value.

Accuracy contract (ref test: test_quantization.py): a calibrated int8
LeNet/ResNet stays within ~1pt of its fp32 accuracy.
"""
from __future__ import annotations

import os

import numpy as np

from ..base import MXNetError
from ..symbol.symbol import Symbol, _Node, Group

__all__ = ["quantize_model", "quantize_graph", "quantize_net"]

_QUANTIZABLE = ("Convolution", "FullyConnected")
_PASSTHROUGH = ("Pooling", "Flatten", "flatten")


def _collect_stats(sym, arg_params, aux_params, tensors, calib_data,
                   num_calib_examples, ctx, calib_mode):
    """Run calibration batches; return {(node_id, out_idx): (min, max)}.

    ``tensors`` is a list of (node, out_idx) pairs from the ORIGINAL
    graph — a Group symbol over them shares those nodes, so stats key
    cleanly by node identity.
    """
    from .. import context as _ctx

    group = Group([Symbol([(n, i)]) for (n, i) in tensors])
    data_names = [d[0] for d in calib_data.provide_data]
    shapes = dict(calib_data.provide_data)
    args = {}
    for name in group.list_arguments():
        if name in arg_params:
            args[name] = arg_params[name]
        elif name in shapes:
            from .. import nd
            args[name] = nd.zeros(tuple(shapes[name]))
        else:
            raise MXNetError(
                "calibration: argument %r has no value (not in arg_params "
                "or calib_data.provide_data)" % name)
    aux = {k: v for k, v in aux_params.items()
           if k in group.list_auxiliary_states()}
    exe = group.bind(ctx or _ctx.cpu(), args=args, aux_states=aux,
                     grad_req="null")

    if calib_mode == "entropy":
        collectors = [_HistogramCollector() for _ in tensors]
    else:
        collectors = [_MinMaxCollector() for _ in tensors]
    seen = 0
    calib_data.reset()
    for batch in calib_data:
        feed = dict(zip(data_names, batch.data))
        outs = exe.forward(is_train=False, **feed)
        for c, o in zip(collectors, outs):
            c.update(o.asnumpy())
        seen += batch.data[0].shape[0]
        if num_calib_examples is not None and seen >= num_calib_examples:
            break
    if seen == 0:
        raise MXNetError("calibration data iterator yielded no batches")
    return {(id(n), i): c.range()
            for (n, i), c in zip(tensors, collectors)}, seen


class _MinMaxCollector:
    def __init__(self):
        self.mn, self.mx = np.inf, -np.inf

    def update(self, arr):
        self.mn = min(self.mn, float(arr.min()))
        self.mx = max(self.mx, float(arr.max()))

    def range(self):
        return self.mn, self.mx


class _HistogramCollector:
    """KL calibration (ref: _LayerHistogramCollector +
    _get_optimal_threshold): accumulate |x| into a fixed histogram, then
    pick the threshold whose clipped/quantized distribution has minimal
    KL divergence from the original."""

    BINS = 2048

    def __init__(self):
        self.hist = None
        self.amax = 0.0

    def update(self, arr):
        a = np.abs(arr.astype(np.float64)).ravel()
        amax = float(a.max()) if a.size else 0.0
        if self.hist is None:
            # range fixed from the first batch (headroom ×1.5); later
            # overflow lands in the edge bin — exactly the outlier mass
            # KL clipping discounts anyway
            self.amax = max(amax * 1.5, 1e-12)
            self.hist = np.zeros(self.BINS)
        h, _ = np.histogram(np.minimum(a, self.amax), bins=self.BINS,
                            range=(0.0, self.amax))
        self.hist += h

    def range(self):
        t = _kl_threshold(self.hist, self.amax, nbits=8)
        return -t, t


def _kl_threshold(hist, amax, nbits=8):
    """Smallest-KL clipping threshold (ref: _get_optimal_threshold,
    after TensorRT's entropy calibration)."""
    nbins = len(hist)
    nquant = 2 ** (nbits - 1) - 1  # 127 levels for symmetric int8
    start = max(nquant, nbins // 8)
    best_kl, best_i = np.inf, nbins
    total = hist.sum()
    if total == 0:
        return amax
    for i in range(start, nbins + 1, max(1, (nbins - start) // 64)):
        ref = hist[:i].copy()
        ref[i - 1] += hist[i:].sum()  # clip outliers into the edge bin
        p = ref / ref.sum()
        # quantize the first i bins down to nquant levels
        chunks = np.array_split(hist[:i], nquant)
        q = np.concatenate([
            np.full(len(c), (c.sum() / max((c > 0).sum(), 1)) if c.sum()
                    else 0.0) * (c > 0) for c in chunks])
        if q.sum() == 0:
            continue
        q = q / q.sum()
        mask = p > 0
        kl = float(np.sum(p[mask] * np.log(p[mask] /
                                           np.maximum(q[mask], 1e-12))))
        if kl < best_kl:
            best_kl, best_i = kl, i
    return amax * best_i / nbins


def _weight_amax(w):
    return float(np.max(np.abs(w.asnumpy()))) or 1e-6


def quantize_graph(sym, arg_params, aux_params, excluded_sym_names=(),
                   excluded_op_names=(), stats=None,
                   quantized_dtype="int8"):
    """Graph-surgery core (ref: quantize_graph_pass.cc). ``stats`` maps
    ``(id(node), out_idx) -> (min, max)`` for calibrated boundaries; when
    absent, quantize_v2/requantize fall back to dynamic ranges."""
    if quantized_dtype != "int8":
        raise MXNetError("TPU build quantizes to signed int8 only")
    stats = stats or {}
    excluded_sym_names = set(excluded_sym_names)
    excluded_op_names = set(excluded_op_names)

    qarg_params = dict(arg_params)
    new_of = {}      # id(old node) -> new node
    triple_of = {}   # id(old node), only idx 0 -> (node, i_q, i_min, i_max)
    hinted_vars = {}  # name -> shape-hinted replacement var node

    def hinted_var(old_var):
        """Copy of a param var with its concrete shape baked in — the
        quantized ops around it have no PARAM_SHAPE_RULES, so inference
        needs the hint (shapes are static at rewrite time anyway)."""
        name = old_var.name
        if name not in hinted_vars:
            attrs = dict(old_var.attrs)
            if name in arg_params:
                attrs.setdefault("__shape__",
                                 tuple(arg_params[name].shape))
            hinted_vars[name] = _Node(None, name, attrs, [],
                                      annotations=dict(
                                          old_var.annotations))
        return hinted_vars[name]

    def rewritten(entry):
        old, idx = entry
        return (new_of.get(id(old), old), idx)

    def f32_input(entry):
        """f32 view of a rewritten tensor (dequantize if int8-only)."""
        old, idx = entry
        if id(old) in triple_of and idx == 0:
            node, qi, mi, xi = triple_of[id(old)]
            deq = _Node("dequantize", old.name + "_dequantize", {},
                        [(node, qi), (node, mi), (node, xi)])
            return (deq, 0)
        return rewritten(entry)

    def int8_input(entry):
        """(q, min, max) triple for a rewritten tensor, quantizing its
        f32 value with calibrated ranges if it isn't int8 already."""
        old, idx = entry
        if id(old) in triple_of and idx == 0:
            node, qi, mi, xi = triple_of[id(old)]
            return (node, qi), (node, mi), (node, xi)
        src = rewritten(entry)
        attrs = {}
        rng = stats.get((id(old), idx))
        if rng is not None:
            attrs = {"min_calib_range": rng[0], "max_calib_range": rng[1]}
        qn = _Node("quantize_v2", old.name + "_quantize", attrs, [src],
                   num_outputs=3)
        return (qn, 0), (qn, 1), (qn, 2)

    for node in Symbol(list(sym._outputs))._topo_nodes():
        if node.is_var():
            continue
        quantizable = (
            node.op in _QUANTIZABLE
            and node.name not in excluded_sym_names
            and node.op not in excluded_op_names
            and len(node.inputs) >= 2
            and node.inputs[1][0].is_var()  # weight must be a plain param
            and node.inputs[1][0].name in arg_params
        )
        if quantizable:
            wname = node.inputs[1][0].name
            w = arg_params[wname]
            amax_w = _weight_amax(w)
            scale_w = 127.0 / amax_w
            q_w = np.clip(np.round(w.asnumpy() * scale_w), -127, 127) \
                .astype(np.int8)
            from .. import nd
            qarg_params.pop(wname, None)
            qarg_params[wname + "_quantize"] = nd.array(q_w,
                                                        dtype="int8")
            qarg_params[wname + "_quantize_min"] = nd.array(
                np.float32(-amax_w).reshape(()))
            qarg_params[wname + "_quantize_max"] = nd.array(
                np.float32(amax_w).reshape(()))
            # bake shapes/dtypes into the vars: quantized ops have no
            # shape-inference rules, and the shapes are static here anyway
            wq_var = _Node(None, wname + "_quantize",
                           {"__shape__": tuple(q_w.shape),
                            "__dtype__": "int8"}, [])
            wmin_var = _Node(None, wname + "_quantize_min",
                             {"__shape__": ()}, [])
            wmax_var = _Node(None, wname + "_quantize_max",
                             {"__shape__": ()}, [])

            dq, dmin, dmax = int8_input(node.inputs[0])
            no_bias = bool(node.attrs.get("no_bias", False))
            bias_in = None
            if not no_bias and len(node.inputs) > 2:
                bnode, bidx = node.inputs[2]
                bias_in = ((hinted_var(bnode), bidx) if bnode.is_var()
                           else rewritten(node.inputs[2]))
            attrs = dict(node.attrs)
            qop = ("quantized_conv" if node.op == "Convolution"
                   else "quantized_fully_connected")
            ins = [dq, (wq_var, 0)]
            ins.append(bias_in if bias_in is not None else (wmin_var, 0))
            if bias_in is None:
                attrs["no_bias"] = True
                ins[2] = (wmin_var, 0)  # placeholder, unused under no_bias
            ins += [dmin, dmax, (wmin_var, 0), (wmax_var, 0)]
            qnode = _Node(qop, node.name + "_quantize", attrs, ins,
                          num_outputs=3)
            # requantize int32 accum → int8 with the layer's calibrated
            # OUTPUT range
            rattrs = {}
            rng = stats.get((id(node), 0))
            if rng is not None:
                rattrs = {"min_calib_range": rng[0],
                          "max_calib_range": rng[1]}
            rq = _Node("requantize", node.name + "_requantize", rattrs,
                       [(qnode, 0), (qnode, 1), (qnode, 2)],
                       num_outputs=3)
            new_of[id(node)] = rq
            triple_of[id(node)] = (rq, 0, 1, 2)
            continue

        passthrough = node.op in _PASSTHROUGH or (
            node.op == "Activation"
            and node.attrs.get("act_type") == "relu")
        if passthrough and node.inputs and \
                id(node.inputs[0][0]) in triple_of and \
                node.inputs[0][1] == 0 and \
                node.name not in excluded_sym_names:
            q, mn, mx = int8_input(node.inputs[0])
            qop = {"Pooling": "quantized_pooling",
                   "Activation": "quantized_act"}.get(
                       node.op, "quantized_flatten")
            pn = _Node(qop, node.name + "_quantize",
                       dict(node.attrs), [q, mn, mx], num_outputs=3)
            new_of[id(node)] = pn
            triple_of[id(node)] = (pn, 0, 1, 2)
            continue

        # ordinary op: consume f32 views of rewritten inputs
        new_inputs = [f32_input(e) for e in node.inputs]
        if new_inputs != node.inputs:
            nn = _Node(node.op, node.name, dict(node.attrs), new_inputs,
                       num_outputs=node.num_outputs,
                       annotations=dict(node.annotations))
            new_of[id(node)] = nn

    outs = []
    for (node, idx) in sym._outputs:
        if id(node) in triple_of and idx == 0:
            outs.append(f32_input((node, idx)))
        else:
            outs.append(rewritten((node, idx)))
    return Symbol(outs), qarg_params


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   label_names=("softmax_label",), ctx=None,
                   excluded_sym_names=(), excluded_op_names=(),
                   calib_mode="naive", calib_data=None,
                   num_calib_examples=None, quantized_dtype="int8",
                   quantize_mode="smart", logger=None):
    """Quantize a trained fp32 model to int8
    (ref: contrib/quantization.py — quantize_model).

    Returns ``(qsym, qarg_params, aux_params)``; bind qsym like any other
    symbol (inference only — quantized ops carry no gradients).
    """
    del data_names, label_names, quantize_mode
    if calib_mode not in ("none", "naive", "entropy"):
        raise MXNetError("calib_mode must be none|naive|entropy, got %r"
                         % (calib_mode,))
    stats = None
    if calib_mode != "none":
        if calib_data is None:
            raise MXNetError("calib_mode=%r needs calib_data" % calib_mode)
        # tensors crossing a float<->int8 boundary: each quantizable
        # node's data input and output
        excluded = set(excluded_sym_names)
        tensors, seen_t = [], set()
        for node in Symbol(list(sym._outputs))._topo_nodes():
            if node.is_var() or node.op not in _QUANTIZABLE or \
                    node.name in excluded or node.op in excluded_op_names:
                continue
            for t in (node.inputs[0], (node, 0)):
                key = (id(t[0]), t[1])
                if key not in seen_t:
                    seen_t.add(key)
                    tensors.append(t)
        stats, seen = _collect_stats(
            sym, arg_params, aux_params, tensors, calib_data,
            num_calib_examples, ctx, calib_mode)
        if logger:
            logger.info("calibrated %d tensors over %d examples (%s)",
                        len(tensors), seen, calib_mode)
    qsym, qarg = quantize_graph(
        sym, arg_params, aux_params,
        excluded_sym_names=excluded_sym_names,
        excluded_op_names=excluded_op_names,
        stats=stats, quantized_dtype=quantized_dtype)
    return qsym, qarg, dict(aux_params)


def quantize_net(network, quantized_dtype="int8", quantize_mode="smart",
                 exclude_layers=(), exclude_operators=(),
                 calib_data=None, calib_mode="naive", data_shapes=None,
                 num_calib_examples=None, ctx=None, logger=None,
                 tmpdir=None):
    """Quantize a trained Gluon (Hybrid)Block to an int8 SymbolBlock
    (ref: contrib/quantization.py — quantize_net_v2): export the block
    to symbol+params, run quantize_model, and import the quantized pair
    back as a SymbolBlock for inference.

    ``data_shapes`` is accepted for reference signature parity but
    unused: the reference needed it to bind before rewriting, while this
    rewrite is shape-free and calib_mode='none' needs no binding at all.
    """
    del data_shapes
    import shutil
    import tempfile

    from ..gluon import SymbolBlock
    from ..model import load_checkpoint

    d = tmpdir or tempfile.mkdtemp(prefix="mxt_qnet_")
    own_tmp = tmpdir is None
    try:
        prefix = os.path.join(d, "net")
        network.export(prefix, 0)
        symbol, arg, aux = load_checkpoint(prefix, 0)
        qsym, qarg, qaux = quantize_model(
            symbol, arg, aux, ctx=ctx,
            excluded_sym_names=exclude_layers,
            excluded_op_names=exclude_operators,
            calib_mode=calib_mode, calib_data=calib_data,
            num_calib_examples=num_calib_examples,
            quantized_dtype=quantized_dtype, quantize_mode=quantize_mode,
            logger=logger)
        qprefix = os.path.join(d, "qnet")
        from ..model import save_checkpoint
        save_checkpoint(qprefix, 0, qsym, qarg, qaux)
        data_names = ["data"]  # exported gluon blocks use the data convention
        return SymbolBlock.imports(qprefix + "-symbol.json", data_names,
                                   qprefix + "-0000.params")
    finally:
        if own_tmp:
            shutil.rmtree(d, ignore_errors=True)

