"""ONNX -> Symbol importer (ref: python/mxnet/contrib/onnx/onnx2mx —
import_model / GraphProto.from_onnx).

Covers the opset the exporter emits plus the common inference graphs:
Conv/ConvTranspose, Gemm (alpha/beta/transB; transA rejected), MatMul,
BatchNormalization, pooling (incl. global), activations, Clip,
Flatten/Reshape/Transpose/Concat/Pad, Reduce{Sum,Mean,Max,Min},
LpNormalization, elementwise arithmetic, Gather, Dropout, Cast,
Identity, Sum. Returns (sym, arg_params, aux_params) exactly like the
reference API.
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from . import onnx_minimal_pb2 as P

_ONNX_TO_NP = {
    P.TensorProto.FLOAT: np.float32,
    P.TensorProto.DOUBLE: np.float64,
    P.TensorProto.FLOAT16: np.float16,
    P.TensorProto.INT32: np.int32,
    P.TensorProto.INT64: np.int64,
    P.TensorProto.INT8: np.int8,
    P.TensorProto.UINT8: np.uint8,
    P.TensorProto.BOOL: np.bool_,
    P.TensorProto.BFLOAT16: np.float32,  # promoted on import
}


def _tensor_to_np(t):
    dtype = _ONNX_TO_NP.get(t.data_type)
    if dtype is None:
        raise MXNetError("unsupported tensor data_type %d" % t.data_type)
    shape = tuple(t.dims)
    if t.raw_data:
        if t.data_type == P.TensorProto.BFLOAT16:
            raw = np.frombuffer(t.raw_data, np.uint16).astype(np.uint32)
            arr = (raw << 16).view(np.float32).astype(np.float32)
        else:
            arr = np.frombuffer(
                t.raw_data,
                np.dtype(dtype if t.data_type != P.TensorProto.BFLOAT16
                         else np.uint16))
        return arr.reshape(shape).copy()
    for field in ("float_data", "int64_data", "int32_data", "double_data"):
        data = getattr(t, field)
        if len(data):
            return np.asarray(list(data), dtype).reshape(shape)
    return np.zeros(shape, dtype)


def _attrs(node):
    out = {}
    for a in node.attribute:
        if a.type == P.AttributeProto.FLOAT:
            out[a.name] = float(a.f)
        elif a.type == P.AttributeProto.INT:
            out[a.name] = int(a.i)
        elif a.type == P.AttributeProto.STRING:
            out[a.name] = a.s.decode()
        elif a.type == P.AttributeProto.FLOATS:
            out[a.name] = tuple(float(x) for x in a.floats)
        elif a.type == P.AttributeProto.INTS:
            out[a.name] = tuple(int(x) for x in a.ints)
        elif a.type == P.AttributeProto.TENSOR:
            out[a.name] = _tensor_to_np(a.t)
        else:
            raise MXNetError("unsupported attribute type %d for %s"
                             % (a.type, a.name))
    return out


def _pair(v, default=(1, 1)):
    if v is None:
        return default
    v = tuple(v)
    return v


def _split_pads(pads):
    if pads is None:
        return (0, 0)
    pads = tuple(pads)
    n = len(pads) // 2
    begin, end = pads[:n], pads[n:]
    if begin != end:
        raise MXNetError("asymmetric pads %s not supported" % (pads,))
    return begin


class _Importer:
    def __init__(self):
        from ... import symbol as S

        self.S = S
        self.tensors = {}   # onnx tensor name -> Symbol
        self.params = {}    # param name -> np array
        self.consumed = set()

    def get(self, name):
        t = self.tensors.get(name)
        if t is not None:
            return t
        if name in self.params:
            # materialize a parameter variable on first symbolic use
            var = self.S.Variable(name)
            self.tensors[name] = var
            self.consumed.add(name)
            return var
        raise MXNetError("tensor %r is not defined yet" % (name,))

    def const(self, name):
        """A parameter consumed as a STATIC value (Reshape shapes)."""
        if name in self.params:
            return self.params[name]
        raise MXNetError("expected initializer for %r" % (name,))

    # -- per-op handlers ---------------------------------------------------
    def op_Conv(self, node, at):
        ins = [self.get(i) for i in node.input]
        kernel = _pair(at.get("kernel_shape"))
        return self.S.Convolution(
            *ins, kernel=kernel,
            stride=_pair(at.get("strides"), (1,) * len(kernel)),
            dilate=_pair(at.get("dilations"), (1,) * len(kernel)),
            pad=_split_pads(at.get("pads")),
            num_filter=int(self.const(node.input[1]).shape[0]),
            num_group=int(at.get("group", 1)),
            no_bias=len(node.input) < 3, name=node.name or None)

    def op_Gemm(self, node, at):
        # full Gemm semantics: Y = alpha * A @ B' + beta * C
        # (ONNX defaults: alpha=1, beta=1, transA=0, transB=0)
        alpha = float(at.get("alpha", 1.0))
        beta = float(at.get("beta", 1.0))
        if int(at.get("transA", 0)):
            raise MXNetError("Gemm(transA=1) is not supported")
        trans_b = int(at.get("transB", 0))
        a = self.get(node.input[0])
        b_name = node.input[1]
        if b_name in self.params and b_name not in self.tensors:
            if not trans_b:
                # FullyConnected wants (num_hidden, in): fold the
                # transpose into the stored weight once
                self.params[b_name] = np.ascontiguousarray(
                    self.params[b_name].T)
            num_hidden = int(self.params[b_name].shape[0])
            bias_as_fc = (len(node.input) > 2 and alpha == 1.0
                          and beta == 1.0
                          and node.input[2] in self.params
                          and self.params[node.input[2]].ndim == 1)
            if bias_as_fc:
                return self.S.FullyConnected(
                    a, self.get(b_name), self.get(node.input[2]),
                    num_hidden=num_hidden, flatten=False,
                    name=node.name or None)
            out = self.S.FullyConnected(
                a, self.get(b_name), num_hidden=num_hidden, no_bias=True,
                flatten=False, name=node.name or None)
        else:
            w = self.get(b_name)
            if trans_b:
                w = self.S.transpose(w)
            out = self.S.dot(a, w)
        if alpha != 1.0:
            out = out * alpha
        if len(node.input) > 2:  # bias_as_fc returned above
            c = self.get(node.input[2])
            out = self.S.broadcast_add(out, c * beta if beta != 1.0 else c)
        return out

    def op_MatMul(self, node, at):
        a, bsym = (self.get(i) for i in node.input)
        return self.S.dot(a, bsym, name=node.name or None)

    def op_BatchNormalization(self, node, at):
        ins = [self.get(i) for i in node.input]
        return self.S.BatchNorm(
            *ins, eps=float(at.get("epsilon", 1e-5)),
            momentum=float(at.get("momentum", 0.9)),
            fix_gamma=False, name=node.name or None)

    def op_MaxPool(self, node, at, pool_type="max"):
        kernel = _pair(at.get("kernel_shape"))
        kw = dict(kernel=kernel, pool_type=pool_type,
                  stride=_pair(at.get("strides"), (1,) * len(kernel)),
                  pad=_split_pads(at.get("pads")))
        if at.get("ceil_mode"):
            kw["pooling_convention"] = "full"
        if pool_type == "avg":
            kw["count_include_pad"] = bool(at.get("count_include_pad", 0))
        return self.S.Pooling(self.get(node.input[0]),
                              name=node.name or None, **kw)

    def op_AveragePool(self, node, at):
        return self.op_MaxPool(node, at, pool_type="avg")

    def op_GlobalMaxPool(self, node, at):
        return self.S.Pooling(self.get(node.input[0]), global_pool=True,
                              pool_type="max", name=node.name or None)

    def op_GlobalAveragePool(self, node, at):
        return self.S.Pooling(self.get(node.input[0]), global_pool=True,
                              pool_type="avg", name=node.name or None)

    def op_Flatten(self, node, at):
        if int(at.get("axis", 1)) != 1:
            raise MXNetError("Flatten(axis!=1) not supported")
        return self.S.Flatten(self.get(node.input[0]),
                              name=node.name or None)

    def op_Reshape(self, node, at):
        shape = tuple(int(x) for x in self.const(node.input[1]))
        return self.S.Reshape(self.get(node.input[0]), shape=shape,
                              name=node.name or None)

    def op_Transpose(self, node, at):
        perm = at.get("perm")
        return self.S.transpose(self.get(node.input[0]),
                                axes=perm, name=node.name or None)

    def op_Concat(self, node, at):
        ins = [self.get(i) for i in node.input]
        return self.S.Concat(*ins, dim=int(at.get("axis", 1)),
                             name=node.name or None)

    def op_Softmax(self, node, at):
        return self.S.softmax(self.get(node.input[0]),
                              axis=int(at.get("axis", -1)),
                              name=node.name or None)

    def op_Dropout(self, node, at):
        return self.S.Dropout(self.get(node.input[0]),
                              name=node.name or None)

    def op_Cast(self, node, at):
        to = _ONNX_TO_NP.get(int(at.get("to", P.TensorProto.FLOAT)),
                             np.float32)
        return self.S.cast(self.get(node.input[0]),
                           dtype=np.dtype(to).name,
                           name=node.name or None)

    def op_Gather(self, node, at):
        if int(at.get("axis", 0)) != 0:
            raise MXNetError("Gather(axis!=0) not supported")
        data, idx = node.input
        if data in self.params:
            vocab, dim = self.params[data].shape[:2]
            return self.S.Embedding(self.get(idx), self.get(data),
                                    input_dim=int(vocab),
                                    output_dim=int(dim),
                                    name=node.name or None)
        return self.S.take(self.get(data), self.get(idx),
                           name=node.name or None)

    def op_ConvTranspose(self, node, at):
        if at.get("auto_pad", "NOTSET") not in ("NOTSET", "") or \
                at.get("output_shape"):
            raise MXNetError(
                "ConvTranspose with auto_pad/output_shape is not "
                "supported — re-export with explicit pads")
        ins = [self.get(i) for i in node.input]
        kernel = _pair(at.get("kernel_shape"))
        w = self.const(node.input[1])  # (in, out/group, kH, kW)
        return self.S.Deconvolution(
            *ins, kernel=kernel,
            stride=_pair(at.get("strides"), (1,) * len(kernel)),
            dilate=_pair(at.get("dilations"), (1,) * len(kernel)),
            pad=_split_pads(at.get("pads")),
            adj=_pair(at.get("output_padding"), (0,) * len(kernel)),
            num_filter=int(w.shape[1]) * int(at.get("group", 1)),
            num_group=int(at.get("group", 1)),
            no_bias=len(node.input) < 3, name=node.name or None)

    def op_Clip(self, node, at):
        lo = hi = None
        if len(node.input) > 1 and node.input[1]:
            lo = float(np.asarray(self.const(node.input[1])).reshape(())[()])
        if len(node.input) > 2 and node.input[2]:
            hi = float(np.asarray(self.const(node.input[2])).reshape(())[()])
        lo = at.get("min", lo)  # opset<11 attribute form
        hi = at.get("max", hi)
        return self.S.clip(self.get(node.input[0]), a_min=lo, a_max=hi,
                           name=node.name or None)

    def _reduce(self, node, at, mx_name):
        axes = at.get("axes")
        if mx_name == "sum" and len(node.input) > 1:  # opset-13 input
            axes = tuple(int(a) for a in self.const(node.input[1]))
        return getattr(self.S, mx_name)(
            self.get(node.input[0]),
            axis=tuple(axes) if axes is not None else None,
            keepdims=bool(at.get("keepdims", 1)), name=node.name or None)

    def op_ReduceSum(self, node, at):
        return self._reduce(node, at, "sum")

    def op_ReduceMean(self, node, at):
        return self._reduce(node, at, "mean")

    def op_ReduceMax(self, node, at):
        return self._reduce(node, at, "max")

    def op_ReduceMin(self, node, at):
        return self._reduce(node, at, "min")

    def op_Pad(self, node, at):
        if len(node.input) > 1:
            flat = [int(x) for x in self.const(node.input[1])]
        else:  # opset<11 attribute form (same begins+ends layout)
            flat = [int(x) for x in at.get("pads", ())]
        n = len(flat) // 2
        pw = []
        for i in range(n):
            pw += [flat[i], flat[n + i]]
        val = float(at.get("value", 0.0))
        if len(node.input) > 2 and node.input[2]:
            val = float(np.asarray(self.const(node.input[2])
                                   ).reshape(())[()])
        return self.S.pad(self.get(node.input[0]),
                          mode=at.get("mode", "constant"),
                          pad_width=tuple(pw), constant_value=val,
                          name=node.name or None)

    def op_LpNormalization(self, node, at):
        if int(at.get("p", 2)) != 2 or int(at.get("axis", -1)) != 1:
            raise MXNetError("only LpNormalization(p=2, axis=1) imports")
        return self.S.L2Normalization(self.get(node.input[0]),
                                      mode="channel",
                                      name=node.name or None)

    def op_Identity(self, node, at):
        return self.S.identity(self.get(node.input[0]),
                               name=node.name or None)

    def op_Sum(self, node, at):
        ins = [self.get(i) for i in node.input]
        total = ins[0]
        for extra in ins[1:]:
            total = self.S.broadcast_add(total, extra)
        return total

    def op_Softplus(self, node, at):
        return self.S.Activation(self.get(node.input[0]),
                                 act_type="softrelu",
                                 name=node.name or None)

    def op_LeakyRelu(self, node, at):
        return self.S.LeakyReLU(self.get(node.input[0]),
                                slope=float(at.get("alpha", 0.01)),
                                name=node.name or None)

    def op_Elu(self, node, at):
        return self.S.LeakyReLU(self.get(node.input[0]), act_type="elu",
                                slope=float(at.get("alpha", 1.0)),
                                name=node.name or None)

    def _simple(mx_name):  # noqa: N805 — converter factory
        def handler(self, node, at):
            ins = [self.get(i) for i in node.input]
            return getattr(self.S, mx_name)(*ins, name=node.name or None)
        return handler

    op_Relu = _simple("relu")
    op_Sigmoid = _simple("sigmoid")
    op_Tanh = _simple("tanh")
    op_Softsign = _simple("softsign")
    op_Exp = _simple("exp")
    op_Log = _simple("log")
    op_Sqrt = _simple("sqrt")
    op_Neg = _simple("negative")
    op_Abs = _simple("abs")
    op_Add = _simple("broadcast_add")
    op_Sub = _simple("broadcast_sub")
    op_Mul = _simple("broadcast_mul")
    op_Div = _simple("broadcast_div")
    del _simple


def _load_model(model_file):
    model = P.ModelProto()
    with open(model_file, "rb") as f:
        model.ParseFromString(f.read())
    return model


def get_model_metadata(model_file):
    """Input/output descriptions (ref: onnx2mx.get_model_metadata)."""
    model = _load_model(model_file)
    graph = model.graph
    inits = {t.name for t in graph.initializer}

    def info(vi):
        shape = tuple(
            d.dim_value if d.dim_value else d.dim_param
            for d in vi.type.tensor_type.shape.dim)
        return (vi.name, shape)

    return {
        "input_tensor_data": [info(v) for v in graph.input
                              if v.name not in inits],
        "output_tensor_data": [info(v) for v in graph.output],
    }


def import_model(model_file):
    """Load an ONNX file into (sym, arg_params, aux_params)
    (ref: onnx2mx.import_model — same return contract)."""
    from ...ndarray.ndarray import NDArray
    import jax.numpy as jnp

    model = _load_model(model_file)
    graph = model.graph
    imp = _Importer()
    for t in graph.initializer:
        imp.params[t.name] = _tensor_to_np(t)
    inits = set(imp.params)
    for vi in graph.input:
        if vi.name not in inits:
            imp.tensors[vi.name] = imp.S.Variable(vi.name)

    for node in graph.node:
        handler = getattr(imp, "op_" + node.op_type, None)
        if handler is None:
            raise MXNetError(
                "ONNX op %r has no importer (file %s)"
                % (node.op_type, model_file))
        result = handler(node, _attrs(node))
        outs = list(node.output)
        if len(outs) == 1:
            imp.tensors[outs[0]] = result
        else:
            for i, oname in enumerate(outs):
                imp.tensors[oname] = result[i]

    out_syms = [imp.tensors[v.name] for v in graph.output]
    sym = out_syms[0] if len(out_syms) == 1 else imp.S.Group(out_syms)

    aux_names = set(sym.list_auxiliary_states())
    arg_params, aux_params = {}, {}
    for name in imp.consumed:
        arr = NDArray(jnp.asarray(imp.params[name]))
        (aux_params if name in aux_names else arg_params)[name] = arr
    return sym, arg_params, aux_params


def import_to_gluon(model_file, ctx=None):
    """Load an ONNX file as a Gluon SymbolBlock
    (ref: onnx2mx.import_to_gluon)."""
    del ctx
    from ...gluon.symbol_block import SymbolBlock
    from ... import symbol as S

    sym, arg_params, aux_params = import_model(model_file)
    meta = get_model_metadata(model_file)
    inputs = [S.Variable(n) for n, _ in meta["input_tensor_data"]]
    net = SymbolBlock(sym, inputs)
    net_params = net.collect_params()
    for name, arr in list(arg_params.items()) + list(aux_params.items()):
        if name in net_params:
            net_params[name].set_data(arr)
    return net
