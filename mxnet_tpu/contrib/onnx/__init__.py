"""``mx.contrib.onnx`` — ONNX export/import
(ref: python/mxnet/contrib/onnx — mx2onnx.export_model,
onnx2mx.import_model/import_to_gluon/get_model_metadata).

Self-contained: serialization uses a wire-compatible subset of the public
onnx.proto compiled into ``onnx_minimal_pb2`` (same field numbers/enums),
so no external ``onnx`` package is required and the files interoperate
with standard ONNX tooling.
"""
from .export_onnx import export_model  # noqa: F401
from .import_onnx import (  # noqa: F401
    get_model_metadata, import_model, import_to_gluon,
)

# reference-compatible aliases (mx.contrib.onnx.mx2onnx.export_model, …)
from . import export_onnx as mx2onnx  # noqa: F401
from . import import_onnx as onnx2mx  # noqa: F401
