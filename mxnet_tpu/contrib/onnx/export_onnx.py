"""Symbol -> ONNX exporter (ref: python/mxnet/contrib/onnx/mx2onnx —
export_model / MXNetGraph.create_onnx_graph_proto).

Walks the Symbol node DAG in topological order, mapping each registry op
to its ONNX opset-13 equivalent. Parameters present in the params dict
become graph initializers; remaining variables become graph inputs.
Serialization uses the wire-compatible minimal schema in
``onnx_minimal.proto`` (identical field numbers to the public onnx.proto),
so the output loads in standard ONNX tooling.
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from . import onnx_minimal_pb2 as P

_DTYPE_TO_ONNX = {
    np.dtype("float32"): P.TensorProto.FLOAT,
    np.dtype("float64"): P.TensorProto.DOUBLE,
    np.dtype("float16"): P.TensorProto.FLOAT16,
    np.dtype("int32"): P.TensorProto.INT32,
    np.dtype("int64"): P.TensorProto.INT64,
    np.dtype("int8"): P.TensorProto.INT8,
    np.dtype("uint8"): P.TensorProto.UINT8,
    np.dtype("bool"): P.TensorProto.BOOL,
}


def _tuple(v, n=2):
    if v is None or v == ():
        return (1,) * n if n else ()
    if isinstance(v, (int, float)):
        return (int(v),) * n
    return tuple(int(x) for x in v)


class _GraphBuilder:
    def __init__(self, graph):
        self.graph = graph
        self._const_id = 0
        self._param_shapes = {}

    def param_shape(self, name):
        return self._param_shapes.get(name)

    def node(self, op_type, inputs, outputs, name, **attrs):
        n = self.graph.node.add()
        n.op_type = op_type
        n.name = name
        n.input.extend(inputs)
        n.output.extend(outputs)
        for k, v in attrs.items():
            if v is None:
                continue
            a = n.attribute.add()
            a.name = k
            if isinstance(v, float):
                a.f = v
                a.type = P.AttributeProto.FLOAT
            elif isinstance(v, bool):
                a.i = int(v)
                a.type = P.AttributeProto.INT
            elif isinstance(v, int):
                a.i = v
                a.type = P.AttributeProto.INT
            elif isinstance(v, str):
                a.s = v.encode()
                a.type = P.AttributeProto.STRING
            elif isinstance(v, (tuple, list)):
                if v and isinstance(v[0], float):
                    a.floats.extend(float(x) for x in v)
                    a.type = P.AttributeProto.FLOATS
                else:
                    a.ints.extend(int(x) for x in v)
                    a.type = P.AttributeProto.INTS
            else:
                raise MXNetError("unsupported attribute %s=%r" % (k, v))
        return n

    def initializer(self, name, array):
        array = np.ascontiguousarray(array)
        self._param_shapes[name] = tuple(array.shape)
        t = self.graph.initializer.add()
        t.name = name
        t.dims.extend(array.shape)
        dt = _DTYPE_TO_ONNX.get(array.dtype)
        if dt is None:  # bf16 params export as f32 (ONNX f32 graphs)
            array = array.astype(np.float32)
            dt = P.TensorProto.FLOAT
        t.data_type = dt
        t.raw_data = array.tobytes()
        return name

    def constant(self, array, hint):
        self._const_id += 1
        name = "%s_const%d" % (hint, self._const_id)
        return self.initializer(name, np.asarray(array))

    def value_info(self, vi, name, shape, dtype=np.float32):
        vi.name = name
        tt = vi.type.tensor_type
        tt.elem_type = _DTYPE_TO_ONNX.get(np.dtype(dtype),
                                          P.TensorProto.FLOAT)
        for d in shape:
            dim = tt.shape.dim.add()
            dim.dim_value = int(d)


def _pads(pad, rank=2):
    p = _tuple(pad, 0) or (0,) * rank
    return list(p) + list(p)  # symmetric begin+end


# --------------------------------------------------------------------------
# per-op converters: fn(builder, node, in_names, out_names) -> None
# --------------------------------------------------------------------------
def _conv(b, node, ins, outs):
    at = node.attrs
    kernel = _tuple(at.get("kernel"))
    b.node("Conv", ins, outs, node.name,
           kernel_shape=kernel,
           strides=_tuple(at.get("stride"), len(kernel)),
           dilations=_tuple(at.get("dilate"), len(kernel)),
           pads=_pads(at.get("pad"), len(kernel)),
           group=int(at.get("num_group", 1)))


def _fc(b, node, ins, outs):
    at = node.attrs
    data = ins[0]
    if at.get("flatten", True):
        flat = node.name + "_flat"
        b.node("Flatten", [data], [flat], flat, axis=1)
        data = flat
    b.node("Gemm", [data] + ins[1:], outs, node.name,
           alpha=1.0, beta=1.0, transA=0, transB=1)


def _batchnorm(b, node, ins, outs):
    at = node.attrs
    ins = list(ins)
    if at.get("fix_gamma", True):
        # reference semantics: fix_gamma forces scale == 1 at runtime
        # regardless of the stored gamma values — ONNX has no such flag,
        # so export a ones tensor as the scale input (the reference
        # exporter does the same, mx2onnx convert_batchnorm)
        shape = b.param_shape(ins[1])
        if shape is None:
            raise MXNetError(
                "BatchNorm %s has fix_gamma=True but its gamma %r is a "
                "graph input, not a parameter — cannot export"
                % (node.name, ins[1]))
        ins[1] = b.constant(np.ones(shape, np.float32),
                            node.name + "_fixed_gamma")
    b.node("BatchNormalization", ins, outs[:1], node.name,
           epsilon=float(at.get("eps", 1e-5)),
           momentum=float(at.get("momentum", 0.9)))


def _activation(b, node, ins, outs):
    table = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
             "softrelu": "Softplus", "softsign": "Softsign"}
    act = node.attrs.get("act_type", "relu")
    if act not in table:
        raise MXNetError("cannot export Activation(act_type=%r)" % act)
    b.node(table[act], ins, outs, node.name)


def _leaky(b, node, ins, outs):
    at = node.attrs
    act = at.get("act_type", "leaky")
    if act == "leaky":
        b.node("LeakyRelu", ins, outs, node.name,
               alpha=float(at.get("slope", 0.25)))
    elif act == "elu":
        b.node("Elu", ins, outs, node.name,
               alpha=float(at.get("slope", 0.25)))
    else:
        raise MXNetError("cannot export LeakyReLU(act_type=%r)" % act)


def _pooling(b, node, ins, outs):
    at = node.attrs
    ptype = at.get("pool_type", "max")
    if at.get("global_pool", False):
        op = {"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}.get(ptype)
        if op is None:
            raise MXNetError("cannot export global %s pooling" % ptype)
        b.node(op, ins, outs, node.name)
        return
    kernel = _tuple(at.get("kernel"))
    kw = dict(kernel_shape=kernel,
              strides=_tuple(at.get("stride"), len(kernel)),
              pads=_pads(at.get("pad"), len(kernel)))
    if at.get("pooling_convention", "valid") == "full":
        kw["ceil_mode"] = 1
    if ptype == "max":
        b.node("MaxPool", ins, outs, node.name, **kw)
    elif ptype == "avg":
        kw["count_include_pad"] = 1 if at.get("count_include_pad",
                                              True) else 0
        b.node("AveragePool", ins, outs, node.name, **kw)
    else:
        raise MXNetError("cannot export %s pooling" % ptype)


def _softmax(b, node, ins, outs):
    b.node("Softmax", ins[:1], outs, node.name,
           axis=int(node.attrs.get("axis", -1)))


def _softmax_output(b, node, ins, outs):
    # inference semantics: the loss head exports as plain Softmax over
    # the data input (ref: mx2onnx softmax_output converter)
    b.node("Softmax", ins[:1], outs, node.name, axis=-1)


def _flatten(b, node, ins, outs):
    b.node("Flatten", ins, outs, node.name, axis=1)


def _reshape(b, node, ins, outs):
    shape = node.attrs.get("shape")
    if shape is None:
        raise MXNetError("Reshape without a static shape can't export")
    shape = tuple(int(s) for s in shape)
    if any(s < -1 for s in shape):
        # MXNet's -2/-3/-4 shape codes have no ONNX equivalent (ONNX
        # Reshape defines only 0 = copy and -1 = infer, which match)
        raise MXNetError(
            "Reshape shape %s uses MXNet special codes (<-1) that ONNX "
            "cannot express" % (shape,))
    shp = b.constant(np.asarray(shape, np.int64), node.name)
    b.node("Reshape", [ins[0], shp], outs, node.name)


def _transpose(b, node, ins, outs):
    axes = node.attrs.get("axes")
    b.node("Transpose", ins, outs, node.name,
           perm=_tuple(axes, 0) if axes else None)


def _concat(b, node, ins, outs):
    b.node("Concat", ins, outs, node.name,
           axis=int(node.attrs.get("dim", 1)))


def _dropout(b, node, ins, outs):
    b.node("Dropout", ins, outs[:1], node.name)


def _embedding(b, node, ins, outs):
    idx = node.name + "_idx"
    b.node("Cast", [ins[0]], [idx], idx, to=int(P.TensorProto.INT64))
    b.node("Gather", [ins[1], idx], outs, node.name)


def _deconv(b, node, ins, outs):
    at = node.attrs
    kernel = _tuple(at.get("kernel"))
    adj = at.get("adj")
    b.node("ConvTranspose", ins, outs, node.name,
           kernel_shape=kernel,
           strides=_tuple(at.get("stride"), len(kernel)),
           dilations=_tuple(at.get("dilate"), len(kernel)),
           pads=_pads(at.get("pad"), len(kernel)),
           output_padding=_tuple(adj, len(kernel)) if adj else None,
           group=int(at.get("num_group", 1)))


def _clip(b, node, ins, outs):
    # opset-13 Clip takes min/max as INPUTS, not attributes
    at = node.attrs
    lo = b.constant(np.float32(at.get("a_min", np.finfo("f4").min)),
                    node.name + "_min")
    hi = b.constant(np.float32(at.get("a_max", np.finfo("f4").max)),
                    node.name + "_max")
    b.node("Clip", [ins[0], lo, hi], outs, node.name)


def _reduce(op_type):
    def conv(b, node, ins, outs):
        at = node.attrs
        if at.get("exclude"):
            raise MXNetError("%s with exclude=True can't export"
                             % node.op)
        axis = at.get("axis")
        if axis is not None and not isinstance(axis, (tuple, list)):
            axis = (axis,)
        # opset-13 ReduceSum takes axes as an input; the other reduces
        # still use the attribute form
        kw = dict(keepdims=int(bool(at.get("keepdims", False))))
        if op_type == "ReduceSum":
            inputs = list(ins)
            if axis is not None:
                inputs.append(b.constant(
                    np.asarray(axis, np.int64), node.name))
            b.node(op_type, inputs, outs, node.name, **kw)
        else:
            if axis is not None:
                kw["axes"] = tuple(int(a) for a in axis)
            b.node(op_type, ins, outs, node.name, **kw)
    return conv


def _cast(b, node, ins, outs):
    dtype = node.attrs.get("dtype", "float32")
    try:
        dt = _DTYPE_TO_ONNX[np.dtype(dtype)]
    except (KeyError, TypeError):
        raise MXNetError("Cast to %r has no ONNX mapping" % (dtype,))
    b.node("Cast", ins, outs, node.name, to=int(dt))


def _pad_op(b, node, ins, outs):
    at = node.attrs
    mode = at.get("mode", "constant")
    onnx_mode = {"constant": "constant", "edge": "edge",
                 "reflect": "reflect"}.get(mode)
    if onnx_mode is None:
        raise MXNetError("pad mode %r can't export" % mode)
    pw = at.get("pad_width", ())
    n = len(pw) // 2
    begins = [int(pw[2 * i]) for i in range(n)]
    ends = [int(pw[2 * i + 1]) for i in range(n)]
    pads = b.constant(np.asarray(begins + ends, np.int64), node.name)
    val = b.constant(np.float32(at.get("constant_value", 0.0)),
                     node.name + "_val")
    b.node("Pad", [ins[0], pads, val], outs, node.name, mode=onnx_mode)


def _l2norm(b, node, ins, outs):
    if node.attrs.get("mode", "instance") != "channel":
        raise MXNetError(
            "L2Normalization exports only mode='channel' "
            "(ONNX LpNormalization is per-axis)")
    b.node("LpNormalization", ins, outs, node.name, axis=1, p=2)


def _binop(op_type):
    def conv(b, node, ins, outs):
        b.node(op_type, ins, outs, node.name)
    return conv


def _scalar_op(op_type, swap=False):
    def conv(b, node, ins, outs):
        scalar = float(node.attrs.get("scalar", 0.0))
        c = b.constant(np.asarray(scalar, np.float32), node.name)
        ins2 = [c, ins[0]] if swap else [ins[0], c]
        b.node(op_type, ins2, outs, node.name)
    return conv


def _unary(op_type):
    def conv(b, node, ins, outs):
        b.node(op_type, ins, outs, node.name)
    return conv


CONVERTERS = {
    "Deconvolution": _deconv,
    "clip": _clip,
    "sum": _reduce("ReduceSum"),
    "mean": _reduce("ReduceMean"),
    "max": _reduce("ReduceMax"),
    "min": _reduce("ReduceMin"),
    "norm_like_cast": _cast,
    "pad": _pad_op,
    "L2Normalization": _l2norm,
    "Convolution": _conv,
    "FullyConnected": _fc,
    "BatchNorm": _batchnorm,
    "Activation": _activation,
    "LeakyReLU": _leaky,
    "Pooling": _pooling,
    "softmax": _softmax,
    "SoftmaxActivation": _softmax,
    "SoftmaxOutput": _softmax_output,
    "Flatten": _flatten,
    "flatten": _flatten,
    "Reshape": _reshape,
    "reshape": _reshape,
    "transpose": _transpose,
    "Concat": _concat,
    "concat": _concat,
    "Dropout": _dropout,
    "Embedding": _embedding,
    "elemwise_add": _binop("Add"),
    "broadcast_add": _binop("Add"),
    "elemwise_sub": _binop("Sub"),
    "broadcast_sub": _binop("Sub"),
    "elemwise_mul": _binop("Mul"),
    "broadcast_mul": _binop("Mul"),
    "elemwise_div": _binop("Div"),
    "broadcast_div": _binop("Div"),
    "_plus_scalar": _scalar_op("Add"),
    "_minus_scalar": _scalar_op("Sub"),
    "_rminus_scalar": _scalar_op("Sub", swap=True),
    "_mul_scalar": _scalar_op("Mul"),
    "_div_scalar": _scalar_op("Div"),
    "relu": _unary("Relu"),
    "sigmoid": _unary("Sigmoid"),
    "tanh": _unary("Tanh"),
    "exp": _unary("Exp"),
    "log": _unary("Log"),
    "sqrt": _unary("Sqrt"),
    "negative": _unary("Neg"),
    "abs": _unary("Abs"),
    "identity": _unary("Identity"),
    "BlockGrad": _unary("Identity"),
}


def _out_names(node):
    if node.num_outputs == 1:
        return [node.name]
    return ["%s_out%d" % (node.name, i) for i in range(node.num_outputs)]


def export_model(sym, params, input_shape, input_type=np.float32,
                 onnx_file_path="model.onnx", verbose=False):
    """Export a Symbol + params to an ONNX file
    (ref: mx2onnx.export_model — same signature contract).

    params: dict of NDArray/ndarray keyed by parameter name (the
    ``arg:``/``aux:`` prefixes of .params files are stripped).
    input_shape: one shape tuple, or a list with one shape per graph
    input (in ``list_inputs`` order of the non-parameter variables).
    """
    from ...ndarray.ndarray import NDArray

    params = {
        (k.split(":", 1)[1] if k.startswith(("arg:", "aux:")) else k):
        (v.asnumpy() if isinstance(v, NDArray) else np.asarray(v))
        for k, v in (params or {}).items()
    }
    if input_shape and isinstance(input_shape[0], (int, np.integer)):
        input_shapes = [tuple(input_shape)]
    else:
        input_shapes = [tuple(s) for s in input_shape]

    model = P.ModelProto()
    model.ir_version = 8
    model.producer_name = "mxnet_tpu"
    model.producer_version = "0.4"
    opset = model.opset_import.add()
    opset.version = 13
    graph = model.graph
    graph.name = sym.name or "mxnet_tpu_graph"
    b = _GraphBuilder(graph)

    nodes = sym._topo_nodes()
    data_inputs = []
    for node in nodes:
        if not node.is_var():
            continue
        if node.name in params:
            b.initializer(node.name, params[node.name])
        else:
            data_inputs.append(node.name)
    if len(data_inputs) != len(input_shapes):
        raise MXNetError(
            "model has %d data inputs %s but %d input shapes given"
            % (len(data_inputs), data_inputs, len(input_shapes)))
    for name, shape in zip(data_inputs, input_shapes):
        b.value_info(graph.input.add(), name, shape, input_type)

    for node in nodes:
        if node.is_var():
            continue
        conv = CONVERTERS.get(node.op)
        if conv is None:
            raise MXNetError(
                "op %r has no ONNX converter (supported: %s)"
                % (node.op, sorted(CONVERTERS)))
        ins = [_out_names(n)[i] for n, i in node.inputs]
        conv(b, node, ins, _out_names(node))
        if verbose:
            print("exported %s -> %s" % (node.op, node.name))

    for node, idx in sym._outputs:
        name = _out_names(node)[idx]
        b.value_info(graph.output.add(), name, ())

    with open(onnx_file_path, "wb") as f:
        f.write(model.SerializeToString())
    return onnx_file_path
