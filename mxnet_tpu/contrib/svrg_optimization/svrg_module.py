"""SVRGModule — Stochastic Variance Reduced Gradient training
(ref: python/mxnet/contrib/svrg_optimization/svrg_module.py; Johnson &
Zhang 2013).

Design: the reference keeps a second executor group at the snapshot
weights and special kvstore keys for the full gradients. Here the
auxiliary Module shares the same single-program executor machinery, and
the variance-reduced gradient ``g_i(w) - g_i(w_snap) + mu`` is one fused
XLA elementwise expression per parameter — no kvstore round-trips."""
from __future__ import annotations

import time

from ... import initializer as init_mod
from ... import metric as metric_mod
from ... import ndarray as nd
from ... import optimizer as opt_mod
from ...model import BatchEndParam
from ...module.base_module import _as_list
from ...module.module import Module
from .svrg_optimizer import _SVRGOptimizer

__all__ = ["SVRGModule"]


def _as_metric(metric):
    return metric if isinstance(metric, metric_mod.EvalMetric) \
        else metric_mod.create(metric)


class SVRGModule(Module):
    """Module with SVRG updates: every ``update_freq`` epochs a full
    gradient is evaluated at a weight snapshot, and each batch update
    uses ``g_i(w) - g_i(w_snapshot) + full_grad``
    (ref: svrg_module.py — SVRGModule)."""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=None, context=None,
                 work_load_list=None, fixed_param_names=None,
                 state_names=None, update_freq=2, **kwargs):
        import logging

        super().__init__(symbol, data_names=data_names,
                         label_names=label_names,
                         logger=logger or logging, context=context,
                         work_load_list=work_load_list,
                         fixed_param_names=fixed_param_names,
                         state_names=state_names, **kwargs)
        if not isinstance(update_freq, int) or update_freq < 1:
            raise ValueError("update_freq must be a positive int, got %r"
                             % (update_freq,))
        self.update_freq = update_freq
        self._mod_aux = Module(symbol, data_names=data_names,
                               label_names=label_names,
                               context=context,
                               fixed_param_names=fixed_param_names)
        self._param_dict = None  # name -> full gradient at the snapshot

    # -- lifecycle -----------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        super().bind(data_shapes, label_shapes, for_training,
                     inputs_need_grad, force_rebind, shared_module,
                     grad_req)
        if for_training:
            self._mod_aux.bind(data_shapes, label_shapes, for_training,
                               inputs_need_grad, force_rebind,
                               shared_module, grad_req)

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        if initializer is None:
            initializer = init_mod.Uniform(0.01)
        super().init_params(initializer=initializer, arg_params=arg_params,
                            aux_params=aux_params,
                            allow_missing=allow_missing,
                            force_init=force_init, allow_extra=allow_extra)
        if self._mod_aux.binded:
            arg, aux = self.get_params()
            self._mod_aux.init_params(
                initializer=initializer, arg_params=arg, aux_params=aux,
                allow_missing=allow_missing, force_init=force_init,
                allow_extra=allow_extra)

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        # the reference swaps in _SVRGOptimizer(default_optimizer=...)
        # with offset keys for the full-grad slots; same seam here
        if self.optimizer_initialized and not force_init:
            return
        super().init_optimizer(kvstore=kvstore, optimizer=optimizer,
                               optimizer_params=optimizer_params,
                               force_init=force_init)
        idx2name = {i: n for i, n in enumerate(self._param_names)}
        idx2name.update({i + len(self._param_names): n + "_full"
                         for i, n in enumerate(self._param_names)})
        self._optimizer = _SVRGOptimizer(
            default_optimizer=self._optimizer, param_idx2name=idx2name)
        self._updater = opt_mod.get_updater(self._optimizer)

    # -- SVRG machinery ------------------------------------------------
    def update_full_grads(self, train_data):
        """Takes a weight snapshot and accumulates the mean gradient of
        the whole ``train_data`` at it (ref: svrg_module.py —
        update_full_grads)."""
        assert self.binded and self.params_initialized
        arg, aux = self.get_params()
        self._mod_aux.set_params(arg_params=arg, aux_params=aux)
        train_data.reset()
        nbatch = 0
        accum = {name: None for name in self._param_names}
        for batch in train_data:
            self._mod_aux.forward_backward(batch)
            for name in self._param_names:
                g = self._mod_aux._exec.grad_dict.get(name)
                if g is None:
                    continue
                accum[name] = g.copy() if accum[name] is None \
                    else accum[name] + g
            nbatch += 1
        assert nbatch > 0, "train_data yielded no batches"
        # the mean full grads land in their slots through the offset
        # keys + _AssignmentOptimizer, the reference's kvstore seam
        self._param_dict = self._param_dict or {}
        for i, name in enumerate(self._param_names):
            if accum[name] is None:
                continue
            mean = accum[name] / nbatch
            slot = self._param_dict.get(name)
            if slot is None:
                slot = nd.zeros(mean.shape, dtype=mean.dtype)
                self._param_dict[name] = slot
            if self.optimizer_initialized:
                self._updater(i + len(self._param_names), mean, slot)
            else:
                slot[:] = mean
        train_data.reset()

    def forward_backward(self, data_batch):
        """Forward+backward on BOTH the live weights and the snapshot
        weights (ref: svrg_module.py — forward_backward)."""
        super().forward_backward(data_batch)
        if self._param_dict is not None:
            self._mod_aux.forward(data_batch, is_train=True)
            self._mod_aux.backward()

    def update(self):
        """Applies the variance-reduced gradient through the updater
        (ref: svrg_module.py — update + _update_svrg_gradients)."""
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        for i, name in enumerate(self._param_names):
            grad = self._exec.grad_dict.get(name)
            if grad is None:
                continue
            if self._param_dict is not None and name in self._param_dict:
                g_snap = self._mod_aux._exec.grad_dict[name]
                grad = grad - g_snap + self._param_dict[name]
            self._updater(i, grad, self._exec.arg_dict[name])

    # -- training loop -------------------------------------------------
    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None):
        """BaseModule.fit plus the full-gradient snapshot every
        ``update_freq`` epochs (ref: svrg_module.py — fit)."""
        del sparse_row_id_fn
        assert num_epoch is not None, "please specify number of epochs"
        if initializer is None:
            initializer = init_mod.Uniform(0.01)

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)

        if validation_metric is None:
            validation_metric = eval_metric
        eval_metric = _as_metric(eval_metric)

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            if epoch % self.update_freq == 0:
                self.update_full_grads(train_data)
            eval_metric.reset()
            nbatch = 0
            for data_batch in train_data:
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(data_batch)
                self.update()
                self.update_metric(eval_metric, data_batch.label)
                if monitor is not None:
                    monitor.toc_print()
                if batch_end_callback is not None:
                    params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                           eval_metric=eval_metric,
                                           locals=locals())
                    for cb in _as_list(batch_end_callback):
                        cb(params)
                nbatch += 1

            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)

            arg_p, aux_p = self.get_params()
            self.set_params(arg_p, aux_p)
            if epoch_end_callback is not None:
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_p, aux_p)

            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)
            train_data.reset()
