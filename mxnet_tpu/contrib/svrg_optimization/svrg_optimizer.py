"""SVRG optimizer internals (ref: python/mxnet/contrib/
svrg_optimization/svrg_optimizer.py).

The reference routes three key families through one kvstore optimizer:
parameter keys (default optimizer), full-gradient keys (assignment), and
the special-key arithmetic lives server-side. Our single-program design
does the variance-reduction arithmetic in SVRGModule.update (one fused
XLA expression per param); these classes keep the reference's optimizer
seam so the update path is still routed through an Optimizer object."""
from __future__ import annotations

from ... import optimizer as opt_mod

__all__ = ["_AssignmentOptimizer", "_SVRGOptimizer"]


@opt_mod.register
class _AssignmentOptimizer(opt_mod.Optimizer):
    """update = plain assignment; used for the full-gradient slots
    (ref: svrg_optimizer.py — _AssignmentOptimizer)."""

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        weight[:] = grad


@opt_mod.register
class _SVRGOptimizer(opt_mod.Optimizer):
    """Dispatches full-gradient keys to assignment and parameter keys to
    the wrapped default optimizer (ref: svrg_optimizer.py —
    _SVRGOptimizer). Full-gradient keys are index-offset by the param
    count and name-suffixed "_full", matching the reference's key
    mangling."""

    def __init__(self, default_optimizer="sgd", param_idx2name=None,
                 **kwargs):
        super().__init__(param_idx2name=param_idx2name or {}, **kwargs)
        if isinstance(default_optimizer, str):
            self.default_opt = opt_mod.create(
                default_optimizer, param_idx2name=param_idx2name, **kwargs)
        else:
            self.default_opt = default_optimizer
        self.aux_opt = _AssignmentOptimizer()

    def _is_full_grad_key(self, index):
        name = self.idx2name.get(index, "")
        return name.endswith("_full")

    def create_state(self, index, weight):
        if self._is_full_grad_key(index):
            return self.aux_opt.create_state(index, weight)
        return self.default_opt.create_state(index, weight)

    def update(self, index, weight, grad, state):
        if self._is_full_grad_key(index):
            self.aux_opt.update(index, weight, grad, state)
        else:
            self.default_opt.update(index, weight, grad, state)
