"""SVRG optimization (ref: python/mxnet/contrib/svrg_optimization)."""
from .svrg_module import SVRGModule
from .svrg_optimizer import _SVRGOptimizer

__all__ = ["SVRGModule", "_SVRGOptimizer"]
