"""contrib namespace (ref: python/mxnet/contrib/__init__.py — the 1.x home
of amp + onnx; exposed here as both mx.amp and mx.contrib.amp)."""
import importlib

from .. import amp  # noqa: F401


def __getattr__(name):  # PEP 562: lazy — onnx pulls in protobuf
    if name in ("onnx", "text", "svrg_optimization", "io",
                "quantization"):
        return importlib.import_module("." + name, __name__)
    raise AttributeError("module %r has no attribute %r"
                         % (__name__, name))
