"""contrib namespace (ref: python/mxnet/contrib/__init__.py — the 1.x home
of amp; exposed here as both mx.amp and mx.contrib.amp)."""
from .. import amp  # noqa: F401
