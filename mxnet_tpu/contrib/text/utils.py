"""Text utilities (ref: python/mxnet/contrib/text/utils.py)."""
from __future__ import annotations

import collections
import re

__all__ = ["count_tokens_from_str"]


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """Counts tokens in ``source_str`` split on ``token_delim`` and
    ``seq_delim`` (ref: utils.py — count_tokens_from_str). Returns (or
    updates in place) a ``collections.Counter``."""
    source_str = re.split(token_delim + "|" + seq_delim, source_str)
    tokens = [t for t in source_str if t]
    if to_lower:
        tokens = [t.lower() for t in tokens]
    if counter_to_update is None:
        return collections.Counter(tokens)
    counter_to_update.update(tokens)
    return counter_to_update
