"""Pretrained token embeddings (ref: python/mxnet/contrib/text/
embedding.py). The file-format layer (token<sep>vec lines) and the
lookup/update API are fully functional; the GloVe/FastText classes keep
the reference's registry + pretrained-file inventory but their fetch
goes through gluon.utils.download, which raises loudly in this no-egress
environment unless the file is already cached on disk."""
from __future__ import annotations

import io
import logging
import os

import numpy as np

from ... import ndarray as nd
from ...gluon.utils import download
from .vocab import Vocabulary

__all__ = ["register", "create", "get_pretrained_file_names",
           "TokenEmbedding", "GloVe", "FastText", "CustomEmbedding",
           "CompositeEmbedding"]

_REGISTRY = {}


def register(cls):
    """Registers a TokenEmbedding subclass under its lowercase name
    (ref: embedding.py — register)."""
    _REGISTRY[cls.__name__.lower()] = cls
    return cls


def create(embedding_name, **kwargs):
    """Creates a registered embedding, e.g. ``create('glove',
    pretrained_file_name=...)`` (ref: embedding.py — create)."""
    name = embedding_name.lower()
    if name not in _REGISTRY:
        raise KeyError("embedding %r not registered; have %s"
                       % (embedding_name, sorted(_REGISTRY)))
    return _REGISTRY[name](**kwargs)


def get_pretrained_file_names(embedding_name=None):
    """Inventory of known pretrained files per embedding
    (ref: embedding.py — get_pretrained_file_names)."""
    if embedding_name is not None:
        return list(_REGISTRY[embedding_name.lower()]
                    .pretrained_file_name_sha1)
    return {name: list(cls.pretrained_file_name_sha1)
            for name, cls in _REGISTRY.items()
            if cls.pretrained_file_name_sha1}


class TokenEmbedding:
    """Base: token -> vector table with unknown handling
    (ref: embedding.py — _TokenEmbedding)."""

    pretrained_file_name_sha1 = {}  # non-pretrained subclasses stay empty

    def __init__(self, unknown_token="<unk>",
                 init_unknown_vec=nd.zeros):
        self._unknown_token = unknown_token
        self._init_unknown_vec = init_unknown_vec
        self._idx_to_token = [unknown_token]
        self._token_to_idx = {unknown_token: 0}
        self._idx_to_vec = None
        self._vec_len = 0

    # -- loading ------------------------------------------------------
    def _load_embedding(self, path, elem_delim=" ", encoding="utf8"):
        """Parses token<elem_delim>v1...vN lines; malformed lines are
        skipped with a warning, first seen token wins (ref:
        embedding.py — _load_embedding)."""
        vecs = []
        loaded_unknown_vec = None
        with io.open(path, "r", encoding=encoding) as f:
            for line_num, line in enumerate(f):
                elems = line.rstrip().split(elem_delim)
                if len(elems) <= 2:
                    logging.warning("line %d in %s: unexpected format, "
                                    "skipped", line_num, path)
                    continue
                token, vec = elems[0], elems[1:]
                if self._vec_len == 0:
                    self._vec_len = len(vec)
                elif len(vec) != self._vec_len:
                    logging.warning("line %d in %s: inconsistent vector "
                                    "length, skipped", line_num, path)
                    continue
                if token == self._unknown_token:
                    # file supplies the unknown vector — use it (ref:
                    # embedding.py loaded_unknown_vec)
                    loaded_unknown_vec = np.asarray(vec, dtype=np.float32)
                    continue
                if token in self._token_to_idx:
                    continue
                self._token_to_idx[token] = len(self._idx_to_token)
                self._idx_to_token.append(token)
                vecs.append(np.asarray(vec, dtype=np.float32))
        table = np.zeros((len(self._idx_to_token), self._vec_len),
                         dtype=np.float32)
        if vecs:
            table[1:] = np.stack(vecs)
        if loaded_unknown_vec is not None:
            table[0] = loaded_unknown_vec
        else:
            unk = self._init_unknown_vec(shape=(self._vec_len,))
            table[0] = (unk.asnumpy() if isinstance(unk, nd.NDArray)
                        else np.asarray(unk))
        self._idx_to_vec = nd.array(table)

    # -- API ----------------------------------------------------------
    def __len__(self):
        return len(self._idx_to_token)

    @property
    def vec_len(self):
        return self._vec_len

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        """Vectors for token(s); unknown tokens get the unknown vector
        (ref: embedding.py — get_vecs_by_tokens)."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens

        def idx(t):
            if t in self._token_to_idx:
                return self._token_to_idx[t]
            if lower_case_backup and t.lower() in self._token_to_idx:
                return self._token_to_idx[t.lower()]
            if self._unknown_token is None:
                raise KeyError("token %r unknown and no unknown_token "
                               "is set" % (t,))
            return self._token_to_idx[self._unknown_token]
        rows = self._idx_to_vec[nd.array([idx(t) for t in toks],
                                         dtype="int32")]
        return rows[0] if single else rows

    def update_token_vectors(self, tokens, new_vectors):
        """Overwrites vectors for existing tokens
        (ref: embedding.py — update_token_vectors)."""
        if self._idx_to_vec is None:
            raise RuntimeError("no vectors loaded")
        single = isinstance(tokens, str)
        toks = [tokens] if single else list(tokens)
        vals = (new_vectors.asnumpy()
                if isinstance(new_vectors, nd.NDArray)
                else np.asarray(new_vectors, dtype=np.float32))
        if vals.ndim == 1:
            vals = vals.reshape(1, -1)
        if len(vals) != len(toks):
            raise ValueError("got %d tokens but %d vectors"
                             % (len(toks), len(vals)))
        table = np.array(self._idx_to_vec.asnumpy())  # asnumpy is read-only
        for t, v in zip(toks, vals):
            if t not in self._token_to_idx:
                raise ValueError("token %r not in the embedding" % (t,))
            table[self._token_to_idx[t]] = v
        self._idx_to_vec = nd.array(table)


class _PretrainedEmbedding(TokenEmbedding):
    """Shared ctor for registry embeddings whose file ships from a URL
    inventory (loud download failure without egress)."""

    url_prefix = ""
    pretrained_file_name_sha1 = {}
    pretrained_archive_name = {}  # file -> containing zip (GloVe)

    def __init__(self, pretrained_file_name=None, embedding_root=None,
                 **kwargs):
        super().__init__(**kwargs)
        if pretrained_file_name is None:
            pretrained_file_name = next(iter(
                self.pretrained_file_name_sha1))
        if pretrained_file_name not in self.pretrained_file_name_sha1:
            raise KeyError(
                "unknown pretrained file %r for %s; known: %s"
                % (pretrained_file_name, type(self).__name__,
                   sorted(self.pretrained_file_name_sha1)))
        root = embedding_root or os.path.join(
            os.path.expanduser("~"), ".mxnet_tpu", "embeddings")
        sha1 = self.pretrained_file_name_sha1[pretrained_file_name]
        if sha1 is None:
            # the reference pins SHA1s so torn caches re-fetch; those
            # values aren't available offline, so be loud about it
            logging.warning(
                "%s: no SHA1 pinned for %s — a cached file is used "
                "without integrity verification; delete %s to re-fetch",
                type(self).__name__, pretrained_file_name, root)
        path = os.path.join(root, pretrained_file_name)
        archive = self.pretrained_archive_name.get(pretrained_file_name)
        if os.path.isfile(path) or archive is None:
            # direct file (cached, or served as-is like fastText .vec)
            path = download(self.url_prefix + pretrained_file_name,
                            path=path, sha1_hash=sha1)
        else:
            # served inside a zip archive (GloVe): fetch + extract the
            # member, like the reference's _get_pretrained_file
            import zipfile

            zpath = download(self.url_prefix + archive,
                             path=os.path.join(root, archive))
            with zipfile.ZipFile(zpath) as zf:
                zf.extract(pretrained_file_name, root)
        self._load_embedding(path)


@register
class GloVe(_PretrainedEmbedding):
    """GloVe vectors (ref: embedding.py — GloVe; files from
    nlp.stanford.edu). File inventory mirrors the reference's list."""

    url_prefix = "https://nlp.stanford.edu/data/"
    pretrained_file_name_sha1 = {
        "glove.6B.50d.txt": None, "glove.6B.100d.txt": None,
        "glove.6B.200d.txt": None, "glove.6B.300d.txt": None,
        "glove.42B.300d.txt": None, "glove.840B.300d.txt": None,
        "glove.twitter.27B.25d.txt": None,
        "glove.twitter.27B.50d.txt": None,
        "glove.twitter.27B.100d.txt": None,
        "glove.twitter.27B.200d.txt": None,
    }
    pretrained_archive_name = {
        "glove.6B.50d.txt": "glove.6B.zip",
        "glove.6B.100d.txt": "glove.6B.zip",
        "glove.6B.200d.txt": "glove.6B.zip",
        "glove.6B.300d.txt": "glove.6B.zip",
        "glove.42B.300d.txt": "glove.42B.300d.zip",
        "glove.840B.300d.txt": "glove.840B.300d.zip",
        "glove.twitter.27B.25d.txt": "glove.twitter.27B.zip",
        "glove.twitter.27B.50d.txt": "glove.twitter.27B.zip",
        "glove.twitter.27B.100d.txt": "glove.twitter.27B.zip",
        "glove.twitter.27B.200d.txt": "glove.twitter.27B.zip",
    }


@register
class FastText(_PretrainedEmbedding):
    """fastText vectors (ref: embedding.py — FastText)."""

    url_prefix = "https://dl.fbaipublicfiles.com/fasttext/vectors-wiki/"
    pretrained_file_name_sha1 = {
        "wiki.simple.vec": None, "wiki.en.vec": None,
    }


@register
class CustomEmbedding(TokenEmbedding):
    """Embedding loaded from a user file of token<elem_delim>vector
    lines (ref: embedding.py — CustomEmbedding)."""

    def __init__(self, pretrained_file_path, elem_delim=" ",
                 encoding="utf8", **kwargs):
        super().__init__(**kwargs)
        self._load_embedding(pretrained_file_path, elem_delim=elem_delim,
                             encoding=encoding)


class CompositeEmbedding(TokenEmbedding):
    """Concatenates several embeddings' vectors over one vocabulary
    (ref: embedding.py — CompositeEmbedding)."""

    def __init__(self, vocabulary, token_embeddings):
        if not isinstance(vocabulary, Vocabulary):
            raise TypeError("vocabulary must be a Vocabulary")
        if not isinstance(token_embeddings, (list, tuple)):
            token_embeddings = [token_embeddings]
        unk = vocabulary.unknown_token
        super().__init__(unknown_token=unk)
        self._vocabulary = vocabulary
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        parts = [emb.get_vecs_by_tokens(self._idx_to_token)
                 for emb in token_embeddings]
        self._idx_to_vec = nd.concat(*parts, dim=1)
        self._vec_len = self._idx_to_vec.shape[1]

    @property
    def vocabulary(self):
        return self._vocabulary
