"""Text-token indexing (ref: python/mxnet/contrib/text/vocab.py)."""
from __future__ import annotations

__all__ = ["Vocabulary"]


class Vocabulary:
    """Maps tokens <-> integer indices (ref: vocab.py — Vocabulary).

    Index 0 is the unknown token when ``unknown_token`` is set, followed
    by ``reserved_tokens``, then counter tokens sorted by descending
    frequency (ties broken alphabetically, like the reference).
    """

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise ValueError("min_freq must be >= 1")
        if reserved_tokens is not None:
            reserved_set = set(reserved_tokens)
            if len(reserved_set) != len(reserved_tokens):
                raise ValueError("reserved_tokens must not be duplicated")
            if unknown_token in reserved_set:
                raise ValueError(
                    "unknown_token must not appear in reserved_tokens")
        self._unknown_token = unknown_token
        self._reserved_tokens = (list(reserved_tokens)
                                 if reserved_tokens else None)
        self._idx_to_token = ([unknown_token]
                              if unknown_token is not None else [])
        if reserved_tokens:
            self._idx_to_token += list(reserved_tokens)
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}
        if counter is not None:
            self._index_counter_keys(counter, most_freq_count, min_freq)

    def _index_counter_keys(self, counter, most_freq_count, min_freq):
        pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
        kept = 0
        for token, freq in pairs:
            if freq < min_freq:
                break
            if most_freq_count is not None and kept >= most_freq_count:
                break
            if token in self._token_to_idx:
                continue
            self._token_to_idx[token] = len(self._idx_to_token)
            self._idx_to_token.append(token)
            kept += 1

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        """Token (or list of tokens) -> index (or list). Unknown tokens
        map to the unknown index (0) — raises if no unknown_token."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        out = []
        for t in toks:
            if t in self._token_to_idx:
                out.append(self._token_to_idx[t])
            elif self._unknown_token is not None:
                out.append(self._token_to_idx[self._unknown_token])
            else:
                raise KeyError(
                    "token %r unknown and no unknown_token is set" % (t,))
        return out[0] if single else out

    def to_tokens(self, indices):
        single = isinstance(indices, int)
        idxs = [indices] if single else indices
        out = []
        for i in idxs:
            if not 0 <= i < len(self._idx_to_token):
                raise ValueError("index %d out of range [0, %d)" %
                                 (i, len(self._idx_to_token)))
            out.append(self._idx_to_token[i])
        return out[0] if single else out
