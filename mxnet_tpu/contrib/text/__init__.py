"""contrib.text (ref: python/mxnet/contrib/text/__init__.py)."""
from . import embedding, utils, vocab
from .vocab import Vocabulary

__all__ = ["embedding", "utils", "vocab", "Vocabulary"]
