"""Automatic symbol naming (ref: python/mxnet/name.py — NameManager /
Prefix). The default manager numbers by op hint ("convolution0", ...);
Prefix prepends a string to every name it resolves."""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix"]


class NameManager:
    """with-scope resolving (name, hint) -> node name
    (ref: name.py — NameManager)."""

    _state = threading.local()

    def __init__(self):
        self._counter = {}
        self._old_manager = None

    def get(self, name, hint):
        if name:
            return name
        n = self._counter.get(hint, 0)
        self._counter[hint] = n + 1
        return "%s%d" % (hint, n)

    def __enter__(self):
        if not hasattr(NameManager._state, "current"):
            NameManager._state.current = NameManager()
        self._old_manager = NameManager._state.current
        NameManager._state.current = self
        return self

    def __exit__(self, ptype, value, trace):
        assert self._old_manager is not None
        NameManager._state.current = self._old_manager


class Prefix(NameManager):
    """Prepends ``prefix`` to every resolved name
    (ref: name.py — Prefix)."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return self._prefix + super().get(name, hint)


def current():
    if not hasattr(NameManager._state, "current"):
        NameManager._state.current = NameManager()
    return NameManager._state.current
