"""KVStore — the parameter synchronization facade
(ref: include/mxnet/kvstore.h, src/kvstore/kvstore_local.h,
src/kvstore/kvstore_dist.h, python/mxnet/kvstore.py).

TPU-native re-design: the reference's worker/server topology (ps-lite ZMQ)
and NCCL collectives collapse into XLA collectives compiled into the step.
What remains as *state* is exactly what KVStoreLocal held — the merged
buffers and the optional server-side updater. Types:

- ``local`` / ``device`` / ``nccl``: single-process aggregation. Multiple
  pushed values per key are summed (the reference reduces across GPUs; here
  a sharded batch already arrives pre-reduced by psum, and list pushes are
  summed with one fused XLA add-n).
- ``dist_sync`` / ``dist_device_sync``: multi-process via
  ``jax.distributed`` (see parallel/). Push triggers a cross-process psum of
  the gradient; semantics of sync mode (all workers see identical weights)
  hold because the reduction is collective.
- ``dist_async``: under the launcher this is the reference's REAL async
  mode — a parameter-server thread on worker 0 (async_server.py) applies
  each worker's push on arrival with no cross-worker barrier (ref:
  kvstore_dist_server.h — DataHandleEx async branch). Async cannot ride
  XLA collectives (collectives ARE barriers), hence the server. Without
  the launcher it falls back to synchronous semantics with a warning.

``set_optimizer`` installs an Updater so ``push`` applies updates
server-side (update_on_kvstore=True path), exactly like
KVStoreDistServer::ApplyUpdates.
"""
from __future__ import annotations

import os
import pickle

from .base import MXNetError
from .ndarray.ndarray import NDArray
from .ndarray import ndarray as _nd
from . import optimizer as opt
from . import resilience
from .resilience import KVStoreError

__all__ = ["KVStore", "GradientCompression", "KVStoreError", "create"]


def _key_str(key):
    return str(key)


class GradientCompression:
    """2-bit quantization with error feedback (ref:
    src/kvstore/gradient_compression.{cc,h} — GradientCompression).

    Each element of (gradient + residual) quantizes to one of
    {-threshold, 0, +threshold}; the quantization error stays in the
    per-key residual and is added to the next push, so small gradients
    accumulate until they cross the threshold instead of vanishing."""

    def __init__(self, threshold=0.5):
        if threshold <= 0:
            raise MXNetError("compression threshold must be positive")
        self.threshold = float(threshold)
        self.residual = {}

    def compress(self, key, grad):
        import jax.numpy as jnp
        from .sparse import BaseSparseNDArray

        if isinstance(grad, BaseSparseNDArray):
            # the reference's 2-bit kernel is dense-only (row_sparse push
            # already sends only touched rows); error-feedback residuals
            # also cannot align across varying per-step index sets
            raise MXNetError(
                "gradient compression does not support %s gradients "
                "(matches reference: 2bit is dense-only)" % grad.stype)
        data = grad.data
        r = self.residual.get(key)
        if r is not None:
            data = data + r
        t = self.threshold
        q = jnp.where(data >= t, jnp.full_like(data, t),
                      jnp.where(data <= -t, jnp.full_like(data, -t),
                                jnp.zeros_like(data)))
        self.residual[key] = data - q
        return NDArray(q)


class KVStore:
    """Single-process key-value store (ref: kvstore_local.h — KVStoreLocal)."""

    def __init__(self, kv_type="local"):
        self._type = kv_type
        self._store = {}           # key -> NDArray (weight if updater else merged)
        self._updater = None
        self._optimizer = None
        self._compression = None
        self._str_key_dict = {}
        self._async = None         # AsyncClient when true async is active
        self._async_server = None  # rank 0 owns the server thread
        if kv_type == "dist_async":
            self._maybe_start_async()

    def _maybe_start_async(self):
        """Engage the real hogwild parameter server (async_server.py) when
        running multi-process under the launcher; single-process
        dist_async keeps synchronous local semantics (create() warns)."""
        from . import async_server

        addr = async_server.server_address()
        if addr is None or self.num_workers <= 1:
            return
        host, port = addr
        if self.rank == 0:
            # singleton per process; a fresh KVStore generation resets
            # the server state
            self._async_server = async_server.get_server(host, port)
            reset = async_server.AsyncClient(host, port)
            reset.request("reset")
            reset.close()
        # rendezvous (ps-lite init is one too): nobody talks to the
        # server until rank 0's reset is acked, so a fast worker can't
        # have its init wiped (and then have a first PUSH take the
        # first-push-initializes branch with a gradient as the weight)
        self._barrier()
        self._async = async_server.AsyncClient(host, port)

    # -- identity ----------------------------------------------------------
    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        if self._type.startswith("dist"):
            try:
                import jax

                return jax.process_index()
            except Exception:
                return 0
        return 0

    @property
    def num_workers(self):
        if self._type.startswith("dist"):
            try:
                import jax

                return jax.process_count()
            except Exception:
                return 1
        return 1

    # -- core API ----------------------------------------------------------
    def init(self, key, value):
        keys, values = self._flatten(key, value)
        if self._async is not None:
            import numpy as np

            for k, v in zip(keys, values):
                arr = v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)
                self._async.request("init", k, arr)  # first writer wins
            return
        for k, v in zip(keys, values):
            if k in self._store:
                continue
            self._store[k] = v.copy() if isinstance(v, NDArray) \
                else _nd.array(v)

    def _flatten(self, key, value):
        if isinstance(key, (list, tuple)):
            if len(key) != len(value):
                raise MXNetError("key/value length mismatch")
            return [_key_str(k) for k in key], list(value)
        return [_key_str(key)], [value]

    def _merge(self, vals):
        """Sum a list of pushed values (ref: CommCPU/CommDevice::Reduce;
        row_sparse lists reduce over the index union like the reference's
        rsp reduce in comm.h)."""
        if isinstance(vals, NDArray):
            return vals
        if len(vals) == 1:
            return vals[0]
        from .sparse import RowSparseNDArray, add as rsp_add

        if all(isinstance(v, RowSparseNDArray) for v in vals):
            total = vals[0]
            for v in vals[1:]:
                total = rsp_add(total, v)
            return total
        total = vals[0].asnumpy() if isinstance(vals[0], RowSparseNDArray) \
            else vals[0].data
        for v in vals[1:]:
            total = total + (v.asnumpy() if isinstance(v, RowSparseNDArray)
                             else v.data)
        return NDArray(total)

    def _dist_reduce(self, merged):
        """Cross-process gradient sum for dist types. With one process this
        is the identity; under jax.distributed the arrays are process-local
        and reduced via a tiny pjit psum (parallel.allreduce)."""
        if self.num_workers <= 1:
            return merged
        from .parallel import allreduce_across_processes

        return allreduce_across_processes(merged)

    def push(self, key, value, priority=0):
        del priority  # XLA async dispatch owns scheduling
        keys, values = self._flatten(key, value)
        if self._async is not None:
            # hogwild: this worker's contribution goes straight to the
            # server (which applies it immediately) — no collective, no
            # barrier with other workers (ref: DataHandleEx async branch)
            for k, v in zip(keys, values):
                merged = self._merge(v)
                merged = self._maybe_compress(k, merged)
                self._async.request("push", k, merged.asnumpy())
            return
        for k, v in zip(keys, values):
            merged = self._merge(v)
            if self._type.startswith("dist"):
                # compress this worker's contribution before it crosses
                # the network (ref: push-side compression in kvstore_dist)
                merged = self._maybe_compress(k, merged)
                # the cross-process reduction is the network step: retry
                # transient drops with backoff, raise KVStoreError (not a
                # hang) when the budget is exhausted (resilience.kv_retry;
                # MXT_FAULT kv_drop/kv_delay inject here). The reduction
                # is pure — a retried attempt is idempotent; the store
                # mutation below happens only after it succeeds.
                merged = resilience.kv_retry(
                    "push", k, lambda m=merged: self._dist_reduce(m))
            if k not in self._store:
                self._store[k] = merged.copy()
                continue
            if self._updater is not None:
                # server-side update: stored value is the weight (a
                # row_sparse merged grad routes to the sparse optimizer
                # path via Optimizer.update's stype dispatch)
                self._updater(int(k) if k.isdigit() else k, merged,
                              self._store[k])
            else:
                # replace semantics (ref: CopyFromTo(merged, &local)) — a
                # row_sparse merged value zero-fills the dense store's
                # untouched rows via RowSparseNDArray.copyto's densify;
                # a dense push into a sparse-stored key casts storage
                from .sparse import BaseSparseNDArray, cast_storage
                tgt = self._store[k]
                if isinstance(tgt, BaseSparseNDArray) and \
                        not isinstance(merged, BaseSparseNDArray):
                    self._store[k] = cast_storage(merged, tgt.stype)
                else:
                    merged.copyto(tgt)

    def _fetch(self, k):
        """Current value of a key: from the async server in hogwild mode,
        else the local store."""
        if self._async is not None:
            return NDArray(self._async.request("pull", k))
        if k in self._store:
            return self._store[k]
        raise MXNetError("key %s has not been initialized" % (k,))

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """ref: KVStore::Pull — with ignore_sparse (the default), sparse
        outs are skipped and must use row_sparse_pull instead."""
        del priority
        from .sparse import BaseSparseNDArray, cast_storage

        keys, outs = self._flatten(key, out)
        for k, o in zip(keys, outs):
            targets = o if isinstance(o, (list, tuple)) else [o]
            if ignore_sparse:
                live = [oo for oo in targets
                        if not isinstance(oo, BaseSparseNDArray)]
            else:
                live = list(targets)
            if not live:
                continue  # nothing to write — skip the (network) fetch
            src = self._fetch(k)
            for oo in live:
                if isinstance(oo, BaseSparseNDArray):
                    cast_storage(src, oo.stype).copyto(oo)
                else:
                    src.copyto(oo)

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in row_ids (ref: KVStore::PullRowSparse).
        Returns row_sparse NDArrays holding the selected rows."""
        if row_ids is None:
            raise MXNetError("row_ids is required for row_sparse_pull")
        keys, outs = self._flatten(key, out)
        rids = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids]
        if len(rids) == 1 and len(outs) > 1:
            rids = rids * len(outs)
        from .sparse import retain_rows

        for k, o, r in zip(keys, outs, rids):
            retain_rows(self._fetch(k), r, out=o)

    # -- optimizer plumbing ------------------------------------------------
    def set_optimizer(self, optimizer):
        """Install a server-side optimizer (ref: kvstore.py —
        set_optimizer; the reference pickles it to the servers)."""
        # round-trip through pickle like the reference, so state must be
        # serializable (catches the same bugs the reference would)
        self._optimizer = pickle.loads(pickle.dumps(optimizer))
        self._updater = opt.get_updater(self._optimizer)
        if self._async is not None:
            # only rank 0 ships it (ref: kvstore_dist.cc — SendCommandTo
            # servers from worker 0); a later arrival from another rank
            # would replace the live updater and wipe its state. The
            # barrier makes this collective (like the reference, every
            # worker calls set_optimizer): no rank can push a gradient
            # before the server has its optimizer — an optimizer-less
            # push would REPLACE the weight instead of updating it.
            if self.rank == 0:
                self._async.request("set_optimizer", None,
                                    pickle.dumps(optimizer))
            self._barrier()

    def set_gradient_compression(self, compression_params):
        """2-bit gradient compression with error-feedback residual
        (ref: src/kvstore/gradient_compression.cc — applied on push in
        dist mode; the residual keeps what quantization dropped so it is
        re-sent on later pushes)."""
        params = dict(compression_params)
        ctype = params.pop("type", None)
        if ctype != "2bit":
            raise MXNetError(
                "gradient compression type %r is not supported (the "
                "reference implements '2bit' only)" % (ctype,))
        if not self._type.startswith("dist"):
            raise MXNetError(
                "gradient compression requires a dist kvstore (ref: "
                "kvstore_dist only; local comm is in-process)")
        threshold = float(params.pop("threshold", 0.5))
        if params:
            raise MXNetError("unknown compression params %s"
                             % sorted(params))
        self._compression = GradientCompression(threshold)

    def _maybe_compress(self, key, merged):
        if self._compression is None:
            return merged
        return self._compression.compress(key, merged)

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("optimizer is not set on this kvstore")
        if self._async is not None:
            # the LIVE states are on the server thread, not the local
            # (never-invoked) updater
            blob = self._async.request("get_states", None, dump_optimizer)
            if blob is None:
                raise MXNetError("async server has no optimizer states")
        else:
            blob = self._updater.get_states(dump_optimizer)
        with open(fname, "wb") as f:
            f.write(blob)

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("optimizer is not set on this kvstore")
        with open(fname, "rb") as f:
            blob = f.read()
        if self._async is not None:
            if self.rank == 0:
                self._async.request("set_states", None, blob)
        else:
            self._updater.set_states(blob)

    def _barrier(self):
        if self.num_workers > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("kvstore_barrier")


_KV_TYPES = ("local", "device", "nccl", "dist", "dist_sync", "dist_async",
             "dist_device_sync", "dist_sync_device", "horovod")


_warned_async = False


def create(name="local"):
    """Factory (ref: kvstore.py — create / KVStore::Create)."""
    if not isinstance(name, str) or name not in _KV_TYPES:
        raise MXNetError("unknown KVStore type %r" % (name,))
    if name == "horovod":
        # horovod's allreduce role is played by the same XLA collectives
        name = "device"
    kv = KVStore(name)
    if name == "dist_async" and kv._async is None:
        # multi-process dist_async gets the REAL hogwild parameter server
        # (async_server.py, ref: kvstore_dist_server.h — DataHandleEx
        # async branch). Without the launcher's coordinator (single
        # process) pushes reduce synchronously instead — loud once, so a
        # ported async script knows its staleness model changed.
        global _warned_async
        if not _warned_async:
            import warnings

            warnings.warn(
                "kvstore 'dist_async' without a multi-process launcher "
                "runs with SYNCHRONOUS semantics: pushes reduce "
                "collectively, not via hogwild server-side updates. Run "
                "under tools/launch.py for the reference's async mode.",
                UserWarning, stacklevel=2)
            _warned_async = True
    return kv
