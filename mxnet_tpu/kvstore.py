"""KVStore — the parameter synchronization facade
(ref: include/mxnet/kvstore.h, src/kvstore/kvstore_local.h,
src/kvstore/kvstore_dist.h, python/mxnet/kvstore.py).

TPU-native re-design: the reference's worker/server topology (ps-lite ZMQ)
and NCCL collectives collapse into XLA collectives compiled into the step.
What remains as *state* is exactly what KVStoreLocal held — the merged
buffers and the optional server-side updater. Types:

- ``local`` / ``device`` / ``nccl``: single-process aggregation. Multiple
  pushed values per key are summed (the reference reduces across GPUs; here
  a sharded batch already arrives pre-reduced by psum, and list pushes are
  summed with one fused XLA add-n).
- ``dist_sync`` / ``dist_device_sync``: multi-process via
  ``jax.distributed`` (see parallel/). Push triggers a cross-process psum of
  the gradient; semantics of sync mode (all workers see identical weights)
  hold because the reduction is collective.
- ``dist_async``: under the launcher this is the reference's REAL async
  mode — a parameter-server thread on worker 0 (async_server.py) applies
  each worker's push on arrival with no cross-worker barrier (ref:
  kvstore_dist_server.h — DataHandleEx async branch). Async cannot ride
  XLA collectives (collectives ARE barriers), hence the server. Without
  the launcher it falls back to synchronous semantics with a warning.

- ``dist_embedding``: the sharded sparse embedding fleet (embedding/) —
  every registered key is a ``row_sparse`` table consistent-hash-sharded
  across embedding servers; push sends only gradient rows (applied with
  the SERVER-side sparse optimizer), ``row_sparse_pull`` returns only
  requested rows through the hot-row device cache. Dense parameters
  never route here — gluon.Trainer keeps them on the local (fused)
  update path.

``set_optimizer`` installs an Updater so ``push`` applies updates
server-side (update_on_kvstore=True path), exactly like
KVStoreDistServer::ApplyUpdates.

Elastic membership (membership.py; ``MXT_MEMBERSHIP``, default on):
multi-process ``dist_async`` workers register with the coordinator-side
server, heartbeat on a background thread, and stamp every frame with a
(worker_id, generation) fencing token — a worker that misses its
``MXT_LIVENESS_TIMEOUT`` window is declared dead, its generation is
fenced (zombie pushes raise :class:`StaleWorkerError`), barriers release
over survivors, and a restarted worker rejoins with a fresh generation
plus a CRC-verified parameter snapshot. ``MXT_ELASTIC=1`` additionally
routes the sync dist types' reductions through the same membership
server so a mid-reduction death degrades the round to the survivors
(renormalized by num_workers/len(survivors)) instead of hanging a
collective.
"""
from __future__ import annotations

import itertools
import os
import pickle
import threading

from .base import MXNetError
from .ndarray.ndarray import NDArray
from .ndarray import ndarray as _nd
from . import optimizer as opt
from . import resilience
from . import telemetry
from .resilience import KVStoreError
from .membership import StaleWorkerError

__all__ = ["KVStore", "GradientCompression", "KVStoreError",
           "StaleWorkerError", "create"]


def _key_str(key):
    return str(key)


# per-process count of engaged multi-worker dist_async stores: creation
# is collective, so every process's Nth store rendezvouses on the
# server's Nth reset (see KVStore._await_world)
_async_world_counter = itertools.count(1)


class GradientCompression:
    """2-bit quantization with error feedback (ref:
    src/kvstore/gradient_compression.{cc,h} — GradientCompression).

    Each element of (gradient + residual) quantizes to one of
    {-threshold, 0, +threshold}; the quantization error stays in the
    per-key residual and is added to the next push, so small gradients
    accumulate until they cross the threshold instead of vanishing."""

    def __init__(self, threshold=0.5):
        if threshold <= 0:
            raise MXNetError("compression threshold must be positive")
        self.threshold = float(threshold)  # sync-ok: host config scalar
        self.residual = {}

    def compress(self, key, grad):
        import jax.numpy as jnp
        from .sparse import BaseSparseNDArray

        if isinstance(grad, BaseSparseNDArray):
            # the reference's 2-bit kernel is dense-only (row_sparse push
            # already sends only touched rows); error-feedback residuals
            # also cannot align across varying per-step index sets
            raise MXNetError(
                "gradient compression does not support %s gradients "
                "(matches reference: 2bit is dense-only)" % grad.stype)
        data = grad.data
        r = self.residual.get(key)
        if r is not None:
            data = data + r
        t = self.threshold
        q = jnp.where(data >= t, jnp.full_like(data, t),
                      jnp.where(data <= -t, jnp.full_like(data, -t),
                                jnp.zeros_like(data)))
        self.residual[key] = data - q
        return NDArray(q)


class KVStore:
    """Single-process key-value store (ref: kvstore_local.h — KVStoreLocal)."""

    def __init__(self, kv_type="local"):
        self._type = kv_type
        self._store = {}           # key -> NDArray (weight if updater else merged)
        self._updater = None
        self._optimizer = None
        self._compression = None
        self._str_key_dict = {}
        self._async = None         # AsyncClient when true async is active
        self._async_server = None  # rank 0 owns the server thread
        self._member = None        # WorkerMembership when elastic
        self._barrier_seq = 0      # unique tags for membership barriers
        self._reduce_seq = {}      # key -> elastic reduce round counter
        # async mode keeps a client-side shadow of the last weights this
        # worker observed (init values + every pull; pushes too when the
        # no-updater path makes the push BE the weight). A server that
        # restarts mid-run boots with an empty store — the resync hook
        # re-seeds it from this shadow so a survivor's retried push
        # cannot take the first-push-initializes branch and install a
        # raw gradient as the weight. One numpy copy per key.
        self._shadow = {}
        # sharded embedding fleet state (kv_type == "dist_embedding"):
        # row_sparse tables live sharded across embedding servers
        # (embedding/), dense keys keep local semantics so the fused
        # dense step stays intact
        self._emb_fleet = None
        self._emb_tables = {}    # key -> embedding.ShardedEmbedding
        self._emb_mirror = {}    # key -> dense NDArray (recover source)
        self._emb_handles = []   # in-process fleet servers we own
        if kv_type == "dist_async":
            self._maybe_start_async()
        elif kv_type == "dist_embedding":
            self._maybe_start_embedding()
        elif kv_type.startswith("dist"):
            self._maybe_start_elastic()

    def _worker_id(self):
        """Stable identity for membership: the launcher's MXT_WORKER_ID
        survives a respawn even before jax.distributed re-initializes;
        fall back to the jax process index."""
        wid = os.environ.get("MXT_WORKER_ID")
        return int(wid) if wid is not None else self.rank

    def _engage_membership(self, host, port):
        """Register with the coordinator-side membership table, start
        heartbeats, and stamp the data client's frames with the
        (worker_id, generation) fencing token."""
        from . import membership

        self._member = membership.WorkerMembership(
            host, port, self._worker_id())
        self._member.register()
        self._adopt_rendezvous_seqs()
        self._member.start_heartbeats()
        if self._async is not None:
            self._async.set_credentials(self._member.worker_id,
                                        self._member.generation)
            self._async.on_server_restart = self._on_server_restart

    def attach_membership(self, member):
        """Adopt an externally managed WorkerMembership (tests, custom
        launchers): frames are credentialed and barriers/reductions go
        elastic through it."""
        self._member = member
        self._adopt_rendezvous_seqs()
        if self._async is not None:
            self._async.set_credentials(member.worker_id,
                                        member.generation)
            self._async.on_server_restart = self._on_server_restart
        return self

    def _adopt_rendezvous_seqs(self):
        """Resume at the SURVIVORS' rendezvous rounds after a rejoin:
        the registration snapshot carries the server's last released
        barrier/reduce sequence numbers, and this store's counters
        fast-forward to them. Counters restarting at 0 would tag rounds
        the survivors already finished — their barriers and elastic
        reduce rounds would never match ours again and both sides would
        end in BarrierTimeout."""
        snap = self._member.snapshot if self._member is not None else None
        seqs = (snap or {}).get("seqs")
        if not seqs:
            return
        if seqs.get("barrier"):
            self._barrier_seq = max(self._barrier_seq,
                                    max(seqs["barrier"].values()))
        for k, s in seqs.get("reduce", {}).items():
            self._reduce_seq[k] = max(self._reduce_seq.get(k, 0), s)

    def _on_server_restart(self, client):
        """The data client reconnected to a RESTARTED server (boot id
        changed): its membership table, store, AND optimizer are all
        empty. Re-register for a fresh generation, then restore server
        state BEFORE the retried frame is re-sent — against an
        un-reseeded store the retried push would take the
        first-push-initializes branch and install a raw GRADIENT as the
        weight, and every later push would REPLACE instead of update
        (set_optimizer is only shipped once at store creation)."""
        if self._member is None:
            return
        from . import diagnostics

        # server restarts are prime post-mortem material: the flight
        # recorder shows the resync in the run-up to any later incident
        diagnostics.record_event(
            "kv_server_restart_resync", worker=self._member.worker_id,
            shadow_keys=len(self._shadow))
        self._member.re_register()
        client.set_credentials(self._member.worker_id,
                               self._member.generation)
        self._adopt_rendezvous_seqs()
        if self._optimizer is not None:
            # every reconnecting worker re-ships it (no rank gate: rank
            # 0 may be the one that died); the updater's slot state
            # restarts fresh, like resuming a checkpoint without states
            client.request("set_optimizer", None,
                           pickle.dumps(self._optimizer))
        for k, arr in self._shadow.items():
            # re-seed from the last weights this worker observed —
            # init is first-writer-wins across the reconnecting fleet
            client.request("init", k, arr)

    def lost_workers(self):
        """Workers declared dead by the liveness reaper so far (0 without
        membership). Cached from heartbeat replies — no extra traffic."""
        return self._member.lost_total if self._member is not None else 0

    def _maybe_start_async(self):
        """Engage the real hogwild parameter server (async_server.py) when
        running multi-process under the launcher; single-process
        dist_async keeps synchronous local semantics (create() warns)."""
        from . import async_server, config

        addr = async_server.server_address()
        if addr is None or self.num_workers <= 1:
            return
        host, port = addr
        world = next(_async_world_counter)
        if self.rank == 0:
            try:
                # singleton per process; a fresh KVStore generation
                # resets the server state
                self._async_server = async_server.get_server(host, port)
            except OSError:
                # the coordinator port is already served by ANOTHER
                # process (standalone `python -m mxnet_tpu.kvstore_server`,
                # or a worker 0 whose server thread outlived us): be a
                # plain client of it instead of dying with EADDRINUSE
                self._async_server = None
            ctl = async_server.AsyncClient(host, port)
            try:
                if world == 1 and ctl.request("members")["members"]:
                    # this process's FIRST store, yet the membership
                    # table already has live workers: we are a respawned
                    # rank 0 joining a RUNNING world (tools/launch.py
                    # --respawn preserves MXT_WORKER_ID=0). A reset here
                    # would wipe the live store and fence every survivor
                    # with an unrecoverable StaleWorkerError — rejoin
                    # below instead (register hands back the snapshot
                    # plus the survivors' rendezvous seqs). Later store
                    # generations (world > 1) are collective re-creates
                    # and reset as before.
                    pass
                else:
                    ctl.request("reset")
            finally:
                ctl.close()
        else:
            # rendezvous (ps-lite init is one too): nobody talks to the
            # server until rank 0's reset for THIS store generation is
            # acked, so a fast worker can't have its init wiped (and
            # then have a first PUSH take the first-push-initializes
            # branch with a gradient as the weight). Store creation is
            # collective, so every process's Nth dist_async store waits
            # on the server's Nth reset — a plain poll over the server
            # transport, no XLA collective needed.
            self._await_world(host, port, world)
        self._async = async_server.AsyncClient(host, port)
        if config.get("MXT_MEMBERSHIP"):
            self._engage_membership(host, port)
            # and the world itself must FORM before elastic semantics
            # (live-member barriers) can exclude anyone
            self._member.wait_for_world(self.num_workers)

    @staticmethod
    def _await_world(host, port, world):
        import time

        from . import async_server, config

        deadline = time.monotonic() + float(config.get("MXT_KV_DEADLINE"))  # sync-ok: host config scalar
        probe = async_server.AsyncClient(host, port)
        try:
            while probe.request("world") < world:
                if time.monotonic() > deadline:
                    raise KVStoreError(
                        "dist_async store generation %d never opened: "
                        "rank 0's reset did not arrive within the "
                        "MXT_KV_DEADLINE window" % world)
                time.sleep(0.01)
        finally:
            probe.close()

    def _maybe_start_embedding(self):
        """Connect to (or spin) the sharded embedding server fleet.
        ``MXT_EMBEDDING_SERVERS`` names a running fleet; without it an
        in-process fleet of ``MXT_EMBEDDING_LOCAL_SERVERS`` servers
        starts here (single-host rigs, tests, benches). The worker
        registers with every server for PR 3 fencing credentials —
        sparse row pushes ride the same (worker_id, generation)
        tokens as dense frames."""
        from . import config, embedding

        spec = config.get("MXT_EMBEDDING_SERVERS")
        if spec:
            self._emb_fleet = embedding.EmbeddingFleet.from_spec(spec)
            self._emb_fleet.refresh()
            self._emb_fleet.register_worker(self._worker_id())
        else:
            self._emb_fleet, self._emb_handles = embedding.local_fleet(
                int(config.get("MXT_EMBEDDING_LOCAL_SERVERS")),
                snapshot_dir=config.get("MXT_EMBEDDING_SNAPSHOT_DIR"),
                worker_id=self._worker_id())

    def is_embedding_key(self, key):
        return _key_str(key) in self._emb_tables

    def _emb_recover(self, key):
        """Worker-side row source for reshard re-seeding: the dense
        mirror (the gluon parameter buffer for trainer-managed tables —
        row-current because every push is followed by a row pull into
        it)."""
        def recover(ids):
            mirror = self._emb_mirror.get(key)
            if mirror is None:
                return None
            import numpy as np

            return np.asarray(mirror.data[ids])  # sync-ok: reshard re-seed (cold path)
        return recover

    def close(self):
        """Tear down owned embedding-fleet resources (no-op for other
        kvstore types)."""
        for t in list(self._emb_tables.values()):
            t.close()
        self._emb_tables.clear()
        if self._emb_fleet is not None:
            self._emb_fleet.close()
            self._emb_fleet = None
        # reverse: server 0 is the fleet coordinator — closing it first
        # would strand every other server's graceful deregister
        for h in reversed(self._emb_handles):
            h.close()
        self._emb_handles = []

    def _maybe_start_elastic(self):
        """Opt-in elastic membership for the sync dist types
        (MXT_ELASTIC=1): rank 0 hosts the membership server on the async
        port; reductions rendezvous there so a dead peer degrades the
        sum over survivors instead of hanging an XLA collective."""
        from . import async_server, config

        if not (config.get("MXT_ELASTIC") and config.get("MXT_MEMBERSHIP")):
            return
        addr = async_server.server_address()
        if addr is None or self.num_workers <= 1:
            return
        host, port = addr
        if self.rank == 0:
            try:
                self._async_server = async_server.get_server(host, port)
            except OSError:
                # port already served (standalone coordinator): client
                self._async_server = None
        # non-zero ranks rely on the client's bounded connect retry to
        # ride out the server coming up
        self._engage_membership(host, port)
        # registration rendezvous: survivors-only degradation starts
        # from a FORMED world — without this an early worker's first
        # reduce would release solo before its peers register
        self._member.wait_for_world(self.num_workers)

    # -- identity ----------------------------------------------------------
    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        if self._type.startswith("dist"):
            try:
                import jax

                return jax.process_index()
            except Exception:
                return 0
        return 0

    @property
    def num_workers(self):
        if self._type.startswith("dist"):
            try:
                import jax

                return jax.process_count()
            except Exception:
                return 1
        return 1

    # -- core API ----------------------------------------------------------
    def init(self, key, value):
        keys, values = self._flatten(key, value)
        if self._emb_fleet is not None:
            # dist_embedding: every registered key is a sharded table —
            # initial rows scatter to their owning servers (one RPC per
            # server); the init value doubles as the dense mirror that
            # reshard re-seeding recovers rows from
            from . import embedding
            from .sparse import BaseSparseNDArray

            with telemetry.trace_scope():
                for k, v in zip(keys, values):
                    if k in self._emb_tables:
                        continue
                    tbl = embedding.ShardedEmbedding(
                        self._emb_fleet, k, v.shape, dtype=v.dtype,
                        recover=self._emb_recover(k))
                    tbl.init(v)
                    self._emb_tables[k] = tbl
                    if isinstance(v, NDArray) and \
                            not isinstance(v, BaseSparseNDArray):
                        self._emb_mirror[k] = v
            return
        if self._async is not None:
            import numpy as np

            # one trace for the whole (possibly multi-key) init — each
            # key's RPC is a span of it (telemetry.record_rpc both ends)
            with telemetry.trace_scope():
                for k, v in zip(keys, values):
                    arr = (v.asnumpy()  # sync-ok: network serialization (async push frame)
                           if hasattr(v, "asnumpy")
                           else np.asarray(v))  # sync-ok: network serialization (async push frame)
                    self._async.request("init", k, arr)  # first writer wins
                    self._shadow[k] = arr
            return
        for k, v in zip(keys, values):
            if k in self._store:
                continue
            self._store[k] = v.copy() if isinstance(v, NDArray) \
                else _nd.array(v)

    def _flatten(self, key, value):
        if isinstance(key, (list, tuple)):
            if len(key) != len(value):
                raise MXNetError("key/value length mismatch")
            return [_key_str(k) for k in key], list(value)
        return [_key_str(key)], [value]

    def _merge(self, vals):
        """Sum a list of pushed values (ref: CommCPU/CommDevice::Reduce;
        row_sparse lists reduce over the index union like the reference's
        rsp reduce in comm.h)."""
        if isinstance(vals, NDArray):
            return vals
        if len(vals) == 1:
            return vals[0]
        from .sparse import RowSparseNDArray, add as rsp_add

        if all(isinstance(v, RowSparseNDArray) for v in vals):
            total = vals[0]
            for v in vals[1:]:
                total = rsp_add(total, v)
            return total
        # mixed dense/row_sparse: reduce ON DEVICE — dense values sum
        # directly; each row_sparse contribution scatter-adds its rows
        # over the index union (ref: comm.h rsp reduce). The old path
        # densified via per-value asnumpy(), a host round-trip per
        # pushed value on the hot push path.
        dense = None
        sparse_vals = []
        for v in vals:
            if isinstance(v, RowSparseNDArray):
                sparse_vals.append(v)
            else:
                dense = v.data if dense is None else dense + v.data
        for v in sparse_vals:
            dense = dense.at[v._indices].add(
                v._values.astype(dense.dtype))
        return NDArray(dense)

    def _dist_reduce(self, merged, key=None):
        """Cross-process gradient sum for dist types. With one process this
        is the identity; under jax.distributed the arrays are process-local
        and reduced via a tiny pjit psum (parallel.allreduce). With
        elastic membership attached the sum instead rendezvouses at the
        membership server, which releases over LIVE members only — a
        peer that dies mid-reduction degrades the round to the
        survivors instead of hanging a collective."""
        if self.num_workers <= 1:
            return merged
        if self._member is not None and self._type != "dist_async":
            return self._elastic_reduce(key, merged)
        from .parallel import allreduce_across_processes

        return allreduce_across_processes(merged)

    def _elastic_reduce(self, key, merged):
        """Membership-mediated sum with survivor renormalization: when
        contributors < num_workers the sum is scaled by
        num_workers/len(survivors) so the reduced gradient stays an
        unbiased estimate of the full-cohort gradient (the
        cross-replica line of work in PAPERS.md assumes exactly this
        calibration under elasticity)."""
        import numpy as np

        from .sparse import BaseSparseNDArray

        self._reduce_seq[key] = seq = self._reduce_seq.get(key, 0) + 1
        if isinstance(merged, BaseSparseNDArray):
            # elastic rounds sum densely (per-worker index sets cannot
            # align when the member set changes mid-round)
            arr = merged.asnumpy()  # sync-ok: elastic rounds reduce densely host-side (documented above)
        else:
            arr = np.asarray(merged.data)  # sync-ok: network serialization (elastic reduce frame)
        total, contributors = self._member.reduce(key, seq, arr)
        if len(contributors) < self.num_workers:
            total = total * (float(self.num_workers) / len(contributors))  # sync-ok: host scalar renormalization
        return NDArray(total)

    def push(self, key, value, priority=0):
        del priority  # XLA async dispatch owns scheduling
        keys, values = self._flatten(key, value)
        if self._emb_fleet is not None:
            # sparse row push: only gradient rows + ids travel, batched
            # per destination server; the server applies the sparse
            # optimizer and replies with the updated rows (hot-cache
            # write-back) — ref: KVStoreDistServer sparse DataHandleEx
            from .sparse import RowSparseNDArray

            with telemetry.trace_scope():
                for k, v in zip(keys, values):
                    tbl = self._emb_tables.get(k)
                    if tbl is None:
                        raise MXNetError(
                            "embedding key %s has not been initialized"
                            % (k,))
                    merged = self._merge(v)
                    if isinstance(merged, RowSparseNDArray):
                        tbl.push(merged._indices, merged._values)
                    else:
                        # dense push into a sharded table: every row
                        import numpy as np

                        tbl.push(np.arange(tbl.shape[0]), merged.data)
            return
        if self._async is not None:
            # hogwild: this worker's contribution goes straight to the
            # server (which applies it immediately) — no collective, no
            # barrier with other workers (ref: DataHandleEx async branch)
            with telemetry.trace_scope():
                for k, v in zip(keys, values):
                    merged = self._merge(v)
                    merged = self._maybe_compress(k, merged)
                    arr = merged.asnumpy()  # sync-ok: network serialization (async push frame)
                    self._async.request("push", k, arr)
                    if self._updater is None:
                        # no server-side optimizer: the push IS the new
                        # weight (replace semantics) — keep the shadow live
                        self._shadow[k] = arr
            return
        for k, v in zip(keys, values):
            merged = self._merge(v)
            if self._type.startswith("dist"):
                # compress this worker's contribution before it crosses
                # the network (ref: push-side compression in kvstore_dist)
                merged = self._maybe_compress(k, merged)
                # the cross-process reduction is the network step: retry
                # transient drops with backoff, raise KVStoreError (not a
                # hang) when the budget is exhausted (resilience.kv_retry;
                # MXT_FAULT kv_drop/kv_delay inject here). The reduction
                # is pure — a retried attempt is idempotent; the store
                # mutation below happens only after it succeeds.
                merged = resilience.kv_retry(
                    "push", k, lambda m=merged, kk=k: self._dist_reduce(
                        m, kk))
            if k not in self._store:
                self._store[k] = merged.copy()
                continue
            if self._updater is not None:
                # server-side update: stored value is the weight (a
                # row_sparse merged grad routes to the sparse optimizer
                # path via Optimizer.update's stype dispatch)
                self._updater(int(k) if k.isdigit() else k, merged,
                              self._store[k])
            else:
                # replace semantics (ref: CopyFromTo(merged, &local)) — a
                # row_sparse merged value zero-fills the dense store's
                # untouched rows via RowSparseNDArray.copyto's densify;
                # a dense push into a sparse-stored key casts storage
                from .sparse import BaseSparseNDArray, cast_storage
                tgt = self._store[k]
                if isinstance(tgt, BaseSparseNDArray) and \
                        not isinstance(merged, BaseSparseNDArray):
                    self._store[k] = cast_storage(merged, tgt.stype)
                else:
                    merged.copyto(tgt)

    def _fetch(self, k):
        """Current value of a key: from the async server in hogwild mode,
        else the local store."""
        if self._async is not None:
            with telemetry.trace_scope():
                arr = self._async.request("pull", k)
            self._shadow[k] = arr  # last observed weight (restart re-seed)
            return NDArray(arr)
        if k in self._store:
            return self._store[k]
        raise MXNetError("key %s has not been initialized" % (k,))

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """ref: KVStore::Pull — with ignore_sparse (the default), sparse
        outs are skipped and must use row_sparse_pull instead."""
        del priority
        from .sparse import BaseSparseNDArray, cast_storage

        keys, outs = self._flatten(key, out)
        for k, o in zip(keys, outs):
            if k in self._emb_tables:
                raise MXNetError(
                    "key %s is a sharded embedding table — a full-table "
                    "pull would materialize every row on this worker; "
                    "use row_sparse_pull with the batch's row ids" % (k,))
            targets = o if isinstance(o, (list, tuple)) else [o]
            if ignore_sparse:
                live = [oo for oo in targets
                        if not isinstance(oo, BaseSparseNDArray)]
            else:
                live = list(targets)
            if not live:
                continue  # nothing to write — skip the (network) fetch
            src = self._fetch(k)
            for oo in live:
                if isinstance(oo, BaseSparseNDArray):
                    cast_storage(src, oo.stype).copyto(oo)
                else:
                    src.copyto(oo)

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in row_ids (ref: KVStore::PullRowSparse).
        Returns row_sparse NDArrays holding the selected rows."""
        if row_ids is None:
            raise MXNetError("row_ids is required for row_sparse_pull")
        keys, outs = self._flatten(key, out)
        rids = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids]
        if len(rids) == 1 and len(outs) > 1:
            rids = rids * len(outs)
        from .sparse import retain_rows

        for k, o, r in zip(keys, outs, rids):
            tbl = self._emb_tables.get(k)
            if tbl is not None:
                self._emb_row_pull(k, tbl, o, r)
                continue
            retain_rows(self._fetch(k), r, out=o)

    def _emb_row_pull(self, key, tbl, out, row_ids):
        """PullRowSparse against the sharded fleet, through the hot-row
        cache. A dense ``out`` receives ONLY the requested rows (a
        device scatter — untouched rows keep their values, the lazy-
        update contract) and becomes the table's dense mirror; a
        row_sparse ``out`` receives the retained rows."""
        import numpy as np
        import jax.numpy as jnp

        from .sparse import RowSparseNDArray

        ids = np.unique(np.asarray(  # sync-ok: row ids are host metadata
            row_ids.asnumpy() if hasattr(row_ids, "asnumpy") else row_ids  # sync-ok: row ids are host metadata (control plane)
        ).astype(np.int64))
        rows = tbl.pull(ids)  # (n, *row_shape) on device
        if isinstance(out, RowSparseNDArray):
            RowSparseNDArray(rows, jnp.asarray(ids),
                             tbl.shape).copyto(out)
            return
        data = out.data
        out._set_data(data.at[jnp.asarray(ids)].set(
            rows.astype(data.dtype)))
        self._emb_mirror[key] = out

    # -- optimizer plumbing ------------------------------------------------
    def set_optimizer(self, optimizer):
        """Install a server-side optimizer (ref: kvstore.py —
        set_optimizer; the reference pickles it to the servers)."""
        if self._emb_fleet is not None:
            # ship to every embedding server: sparse row pushes apply
            # THERE (sparse_sgd/adagrad/adam/ftrl_update over the shard)
            self._optimizer = optimizer
            self._emb_fleet.set_optimizer(optimizer)
            return
        # round-trip through pickle like the reference, so state must be
        # serializable (catches the same bugs the reference would)
        self._optimizer = pickle.loads(pickle.dumps(optimizer))
        self._updater = opt.get_updater(self._optimizer)
        if self._async is not None:
            # only rank 0 ships it (ref: kvstore_dist.cc — SendCommandTo
            # servers from worker 0); a later arrival from another rank
            # would replace the live updater and wipe its state. The
            # barrier makes this collective (like the reference, every
            # worker calls set_optimizer): no rank can push a gradient
            # before the server has its optimizer — an optimizer-less
            # push would REPLACE the weight instead of updating it.
            if self.rank == 0:
                self._async.request("set_optimizer", None,
                                    pickle.dumps(optimizer))
            self._barrier()

    def set_gradient_compression(self, compression_params):
        """2-bit gradient compression with error-feedback residual
        (ref: src/kvstore/gradient_compression.cc — applied on push in
        dist mode; the residual keeps what quantization dropped so it is
        re-sent on later pushes)."""
        params = dict(compression_params)
        ctype = params.pop("type", None)
        if ctype != "2bit":
            raise MXNetError(
                "gradient compression type %r is not supported (the "
                "reference implements '2bit' only)" % (ctype,))
        if not self._type.startswith("dist"):
            raise MXNetError(
                "gradient compression requires a dist kvstore (ref: "
                "kvstore_dist only; local comm is in-process)")
        threshold = float(params.pop("threshold", 0.5))  # sync-ok: host config scalar
        if params:
            raise MXNetError("unknown compression params %s"
                             % sorted(params))
        self._compression = GradientCompression(threshold)

    def _maybe_compress(self, key, merged):
        if self._compression is None:
            return merged
        return self._compression.compress(key, merged)

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("optimizer is not set on this kvstore")
        if self._async is not None:
            # the LIVE states are on the server thread, not the local
            # (never-invoked) updater
            blob = self._async.request("get_states", None, dump_optimizer)
            if blob is None:
                raise MXNetError("async server has no optimizer states")
        else:
            blob = self._updater.get_states(dump_optimizer)
        with open(fname, "wb") as f:
            f.write(blob)

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("optimizer is not set on this kvstore")
        with open(fname, "rb") as f:
            blob = f.read()
        if self._async is not None:
            if self.rank == 0:
                self._async.request("set_states", None, blob)
        else:
            self._updater.set_states(blob)

    def _barrier(self, tag="kvstore_barrier"):
        """Cross-worker rendezvous. With membership attached the barrier
        releases over LIVE members only (a dead peer is dropped within
        one liveness window); either way it is deadline-bounded
        (MXT_BARRIER_TIMEOUT, falling back to MXT_KV_DEADLINE) and
        raises KVStoreError instead of waiting forever on a peer that
        will never arrive."""
        if self.num_workers <= 1:
            return
        if self._member is not None:
            # unique per-call tag: barrier calls are collective, so every
            # worker's Nth barrier lands on the same tag
            self._barrier_seq += 1
            self._member.barrier("%s:%d" % (tag, self._barrier_seq))
            return
        from . import config

        deadline = config.get("MXT_BARRIER_TIMEOUT")
        if deadline is None:
            deadline = config.get("MXT_KV_DEADLINE")
        box = {}

        def run():
            try:
                from jax.experimental import multihost_utils

                multihost_utils.sync_global_devices(tag)
                box["ok"] = True
            except BaseException as e:  # surfaced to the caller below
                box["err"] = e

        # the jax collective has no timeout of its own: run it on a
        # daemon thread and bound the join, so a peer that never arrives
        # becomes a typed error instead of a worker wedged forever
        t = threading.Thread(target=run, daemon=True, name="kv-barrier")
        t.start()
        t.join(float(deadline))  # sync-ok: host config scalar
        if t.is_alive():
            raise KVStoreError(
                "kvstore barrier %r exceeded its %.1fs deadline "
                "(MXT_BARRIER_TIMEOUT/MXT_KV_DEADLINE) — a peer is "
                "unreachable and will never arrive" % (tag,
                                                       float(deadline)))  # sync-ok: host config scalar
        if "err" in box:
            raise box["err"]


_KV_TYPES = ("local", "device", "nccl", "dist", "dist_sync", "dist_async",
             "dist_device_sync", "dist_sync_device", "dist_embedding",
             "horovod")


_warned_async = False


def create(name="local"):
    """Factory (ref: kvstore.py — create / KVStore::Create)."""
    if not isinstance(name, str) or name not in _KV_TYPES:
        raise MXNetError("unknown KVStore type %r" % (name,))
    if name == "horovod":
        # horovod's allreduce role is played by the same XLA collectives
        name = "device"
    kv = KVStore(name)
    if name == "dist_async" and kv._async is None:
        # multi-process dist_async gets the REAL hogwild parameter server
        # (async_server.py, ref: kvstore_dist_server.h — DataHandleEx
        # async branch). Without the launcher's coordinator (single
        # process) pushes reduce synchronously instead — loud once, so a
        # ported async script knows its staleness model changed.
        global _warned_async
        if not _warned_async:
            import warnings

            warnings.warn(
                "kvstore 'dist_async' without a multi-process launcher "
                "runs with SYNCHRONOUS semantics: pushes reduce "
                "collectively, not via hogwild server-side updates. Run "
                "under tools/launch.py for the reference's async mode.",
                UserWarning, stacklevel=2)
            _warned_async = True
    return kv
