"""Neural-net ops — the reference's hot kernels, rebuilt on XLA
(ref: src/operator/nn/*: convolution, fully_connected, batch_norm, pooling,
softmax, dropout, layer_norm; cuDNN paths become lax.conv_general_dilated /
dot_general / reduce_window, which XLA tiles onto the MXU/VPU).

Layout note: the reference defaults to NCHW. All ops accept ``layout`` and
the model zoo uses NHWC on TPU (better MXU tiling); NCHW stays the API
default for parity.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .registry import register
from .. import random as _random


@register("FullyConnected", aliases=("fully_connected",))
def fully_connected(data, weight, bias=None, num_hidden=None, no_bias=False,
                    flatten=True):
    """ref: src/operator/nn/fully_connected.cc. weight is (num_hidden, in)."""
    del num_hidden
    x = data
    if flatten and x.ndim > 2:
        x = x.reshape(x.shape[0], -1)
    # no preferred_element_type: the TPU MXU already accumulates bf16
    # operands in f32, and requesting an f32 output breaks the conv/dot
    # transpose rule in backward (dtype-mismatched cotangent)
    out = jax.lax.dot_general(
        x, weight,
        dimension_numbers=(((x.ndim - 1,), (1,)), ((), ())),
    )
    if not no_bias and bias is not None:
        out = out + bias.astype(out.dtype)
    return out


def _conv_dn(layout, nd):
    if layout in (None, "NCHW", "NCW", "NCDHW"):
        lhs = "NC" + "DHW"[3 - nd:]
        out = lhs
    elif layout in ("NHWC", "NWC", "NDHWC"):
        lhs = "N" + "DHW"[3 - nd:] + "C"
        out = lhs
    else:
        raise ValueError("unsupported layout %r" % (layout,))
    rhs = "OI" + "DHW"[3 - nd:]
    return (lhs, rhs, out)


@register("Convolution", aliases=("convolution",))
def convolution(data, weight, bias=None, kernel=(), stride=(), dilate=(),
                pad=(), num_filter=0, num_group=1, no_bias=False,
                layout=None, workspace=None, cudnn_tune=None, cudnn_off=None):
    """ref: src/operator/nn/convolution.cc (+cudnn path). Weight logical
    layout is OIHW regardless of data layout, matching the reference."""
    del num_filter, workspace, cudnn_tune, cudnn_off
    nd = len(kernel)
    stride = tuple(stride) if stride else (1,) * nd
    dilate = tuple(dilate) if dilate else (1,) * nd
    pad = tuple(pad) if pad else (0,) * nd
    dn = _conv_dn(layout, nd)
    out = jax.lax.conv_general_dilated(
        data, weight,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
    )
    if not no_bias and bias is not None:
        c_ax = dn[2].index("C")
        shape = [1] * out.ndim
        shape[c_ax] = bias.shape[0]
        out = out + bias.reshape(shape).astype(out.dtype)
    return out


@register("Deconvolution")
def deconvolution(data, weight, bias=None, kernel=(), stride=(), dilate=(),
                  pad=(), adj=(), num_filter=0, num_group=1, no_bias=False,
                  layout=None, target_shape=None, workspace=None):
    """ref: src/operator/nn/deconvolution.cc — transposed conv. weight is
    (in_ch, out_ch/group, kH, kW) in the reference; we honor that."""
    del num_filter, target_shape, workspace
    nd = len(kernel)
    stride = tuple(stride) if stride else (1,) * nd
    dilate = tuple(dilate) if dilate else (1,) * nd
    pad = tuple(pad) if pad else (0,) * nd
    adj = tuple(adj) if adj else (0,) * nd
    dn = _conv_dn(layout, nd)
    # transposed conv = lhs-dilated conv with flipped kernel, IO swapped
    kern = jnp.swapaxes(weight, 0, 1)
    kern = jnp.flip(kern, axis=tuple(range(2, 2 + nd)))
    pads = [
        (dilate[i] * (kernel[i] - 1) - pad[i],
         dilate[i] * (kernel[i] - 1) - pad[i] + adj[i])
        for i in range(nd)
    ]
    if num_group != 1:
        raise NotImplementedError("grouped deconvolution not yet supported")
    out = jax.lax.conv_general_dilated(
        data, kern,
        window_strides=(1,) * nd,
        padding=pads,
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dimension_numbers=dn,
    )
    if not no_bias and bias is not None:
        c_ax = dn[2].index("C")
        shape = [1] * out.ndim
        shape[c_ax] = bias.shape[0]
        out = out + bias.reshape(shape)
    return out


@register("Activation", aliases=("activation",))
def activation_op(data, act_type="relu"):
    if act_type == "relu":
        return jnp.maximum(data, 0)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jax.nn.softplus(data)
    if act_type == "softsign":
        return jax.nn.soft_sign(data)
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "gelu_tanh":
        return jax.nn.gelu(data, approximate=True)
    if act_type == "silu" or act_type == "swish":
        return jax.nn.silu(data)
    raise ValueError("unknown act_type %r" % (act_type,))


@register("LeakyReLU")
def leaky_relu(data, gamma=None, act_type="leaky", slope=0.25,
               lower_bound=0.125, upper_bound=0.334):
    if act_type == "leaky":
        return jnp.where(data >= 0, data, slope * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(data >= 0, data, alpha * jnp.expm1(data))
    if act_type == "prelu":
        g = gamma
        shape = [1] * data.ndim
        if g.ndim == 1 and data.ndim > 1:
            shape[1] = g.shape[0]
            g = g.reshape(shape)
        return jnp.where(data >= 0, data, g * data)
    if act_type == "rrelu":
        mid = (lower_bound + upper_bound) / 2.0
        return jnp.where(data >= 0, data, mid * data)
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    raise ValueError("unknown act_type %r" % (act_type,))


@register("softmax")
def softmax(data, axis=-1, temperature=None, length=None):
    x = data
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    if length is not None:
        # mask positions >= length along `axis` (reference masked softmax)
        idx = jnp.arange(x.shape[axis])
        shape = [1] * x.ndim
        shape[axis] = x.shape[axis]
        mask = idx.reshape(shape) < length.reshape(
            length.shape + (1,) * (x.ndim - length.ndim)
        )
        x = jnp.where(mask, x, -jnp.inf)
        out = jax.nn.softmax(x, axis=axis)
        return jnp.where(mask, out, 0.0)
    return jax.nn.softmax(x, axis=axis)


@register("log_softmax")
def log_softmax(data, axis=-1, temperature=None):
    x = data
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    return jax.nn.log_softmax(x, axis=axis)


@register("softmin")
def softmin(data, axis=-1):
    return jax.nn.softmax(-data, axis=axis)


@register("softmax_cross_entropy")
def softmax_cross_entropy(data, label):
    logp = jax.nn.log_softmax(data, axis=-1)
    lbl = label.astype(jnp.int32)
    picked = jnp.take_along_axis(logp, lbl[:, None], axis=-1)
    return -jnp.sum(picked)


def _softmax_output_fwd(data, label, grad_scale, ignore_label, use_ignore,
                        multi_output, normalization):
    out = jax.nn.softmax(data, axis=-1 if not multi_output else 1)
    return out, (out, label)


def _softmax_output_bwd(grad_scale, ignore_label, use_ignore, multi_output,
                        normalization, res, ct):
    out, label = res
    ax = 1 if multi_output else -1
    lbl = label.astype(jnp.int32)
    oh = jax.nn.one_hot(lbl, out.shape[ax], dtype=out.dtype, axis=ax)
    g = out - oh
    if use_ignore:
        keep = (lbl != int(ignore_label)).astype(out.dtype)
        g = g * jnp.expand_dims(keep, ax)
    scale = grad_scale
    if normalization == "batch":
        scale = scale / out.shape[0]
    elif normalization == "valid" and use_ignore:
        scale = scale / jnp.maximum((lbl != int(ignore_label)).sum(), 1)
    g = g * scale
    return (g, jnp.zeros_like(label))


_softmax_output_core = jax.custom_vjp(
    lambda data, label, grad_scale, ignore_label, use_ignore, multi_output,
    normalization: _softmax_output_fwd(
        data, label, grad_scale, ignore_label, use_ignore, multi_output,
        normalization)[0],
    nondiff_argnums=(2, 3, 4, 5, 6),
)
_softmax_output_core.defvjp(_softmax_output_fwd, _softmax_output_bwd)


@register("SoftmaxOutput", aliases=("Softmax",))
def softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                   use_ignore=False, multi_output=False, preserve_shape=False,
                   normalization="null", out_grad=False, smooth_alpha=0.0):
    """Legacy fused softmax+CE-grad op (ref: src/operator/softmax_output.cc):
    forward is softmax; backward emits (p - onehot(label)) * grad_scale
    regardless of incoming cotangent — reproduced with jax.custom_vjp."""
    del preserve_shape, out_grad, smooth_alpha
    return _softmax_output_core(
        data, label, float(grad_scale), float(ignore_label), bool(use_ignore),
        bool(multi_output), str(normalization)
    )


@register("Dropout")
def dropout(data, p=0.5, mode="training", axes=(), train_mode=False):
    """ref: src/operator/nn/dropout.cc. ``train_mode`` comes from the
    caller (gluon layers) or is injected from the autograd context by
    the eager/executor dispatch (registry.apply_op — the reference's
    ctx.is_train)."""
    if p <= 0 or (not train_mode and mode != "always"):
        return data
    shape = list(data.shape)
    for ax in axes:
        shape[ax] = 1
    keep = 1.0 - p
    mask = jax.random.bernoulli(_random.new_key(), keep, tuple(shape))
    return jnp.where(mask, data / keep, 0.0).astype(data.dtype)


# --------------------------------------------------------------------------
# normalization
# --------------------------------------------------------------------------
def _bn_stats(x32, red):
    """Batch mean/var via the centered two-pass form. NOT E[x^2]-E[x]^2:
    that cancels catastrophically for |mean|/std >~ 1e3 (raw un-centered
    features straight into BN), clamping var to 0 and scaling outputs by
    rsqrt(eps). The second pass fuses with the normalize pass anyway."""
    mean = jnp.mean(x32, axis=red)
    shape = [1] * x32.ndim
    for i in range(x32.ndim):
        if i not in red:
            shape[i] = x32.shape[i]
    d = x32 - mean.reshape(shape)
    var = jnp.mean(d * d, axis=red)
    return mean, var


def _bn_core_fwd(eps, red, x, g, b):
    x32 = x.astype(jnp.float32)
    mean, var = _bn_stats(x32, red)
    inv = jax.lax.rsqrt(var + eps)
    shape = [1] * x.ndim
    ax = [i for i in range(x.ndim) if i not in red][0]
    shape[ax] = x.shape[ax]
    out = (x32 - mean.reshape(shape)) * (
        inv * g.astype(jnp.float32)).reshape(shape) \
        + b.astype(jnp.float32).reshape(shape)
    # residuals are the bf16 input + per-channel stats — backward
    # recomputes x32/xhat on the fly, so no f32 activation tensor is ever
    # written to HBM (the main BN traffic saving vs autodiff)
    try:
        from .. import tuning

        tuning.record_signature("batch_norm", {
            "x_shape": list(x.shape), "dtype": str(x.dtype),
            "g_shape": list(g.shape), "g_dtype": str(g.dtype),
            "eps": float(eps), "red": list(red)})
    except Exception:  # noqa: BLE001 — bookkeeping must not fail the op
        pass
    return (out.astype(x.dtype), mean, var), (x, g, mean, inv)


def _bn_core_bwd(eps, red, res, cts):
    x, g, mean, inv = res
    ct_out = cts[0]  # mean/var outputs feed stop_gradient paths only
    ax = [i for i in range(x.ndim) if i not in red][0]
    shape = [1] * x.ndim
    shape[ax] = x.shape[ax]
    n = 1
    for i in red:
        n *= x.shape[i]
    if ax == x.ndim - 1:  # channel-last (NHWC): the Pallas fast path
        from . import bn_pallas
        if bn_pallas.candidate():
            c = x.shape[ax]
            # per-shape choice (tuning table / MXT_BN_PALLAS override);
            # an eager backward passes its concrete arrays so an
            # on-device first call can feed the autotuner's timed path
            x2d = x.reshape(-1, c)
            dy2d = ct_out.reshape(-1, c)
            arrays = None
            if not isinstance(x, jax.core.Tracer):
                arrays = (x2d, dy2d, mean, inv, g)
            use_pallas, block_rows = bn_pallas.choose(n, c, x.dtype,
                                                      arrays=arrays)
            if use_pallas:
                dx2, dg, db = bn_pallas.bn_bwd_pallas(
                    x2d, dy2d, mean, inv, g, block_rows=block_rows)
                return (dx2.reshape(x.shape), dg.astype(g.dtype),
                        db.astype(g.dtype))
    dy = ct_out.astype(jnp.float32)
    xhat = (x.astype(jnp.float32) - mean.reshape(shape)) * inv.reshape(shape)
    db = jnp.sum(dy, axis=red)
    dg = jnp.sum(dy * xhat, axis=red)
    dx = (g.astype(jnp.float32) * inv).reshape(shape) * (
        dy - (db / n).reshape(shape) - xhat * (dg / n).reshape(shape))
    return dx.astype(x.dtype), dg.astype(g.dtype), db.astype(g.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _bn_core(eps, red, x, g, b):
    return _bn_core_fwd(eps, red, x, g, b)[0]


_bn_core.defvjp(_bn_core_fwd, _bn_core_bwd)


@register("BatchNorm", aliases=("batch_norm", "BatchNorm_v1"), num_outputs=3)
def batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-5,
               momentum=0.9, fix_gamma=True, use_global_stats=False,
               output_mean_var=False, axis=1, cudnn_off=False,
               train_mode=False):
    """ref: src/operator/nn/batch_norm.cc. Returns (out, mean, var); in
    training mode mean/var are the *updated running stats* the layer writes
    back (the reference mutates aux states in-place inside the kernel).
    Train-mode normalize+stats is a custom-VJP kernel: single-pass f32
    stats, bf16-only residuals (backward recomputes x_hat)."""
    del output_mean_var, cudnn_off
    ax = axis % data.ndim
    red = tuple(i for i in range(data.ndim) if i != ax)
    shape = [1] * data.ndim
    shape[ax] = data.shape[ax]
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    if train_mode and not use_global_stats:
        out, mean, var = _bn_core(float(eps), red, data, g, beta)
        new_mean = momentum * moving_mean + (1 - momentum) * mean
        new_var = momentum * moving_var + (1 - momentum) * var
        return (out,
                jax.lax.stop_gradient(new_mean),
                jax.lax.stop_gradient(new_var))
    mean, var = moving_mean, moving_var
    inv = jax.lax.rsqrt(var + eps)
    out = (data.astype(jnp.float32) - mean.reshape(shape)) * (
        inv * g.astype(jnp.float32)
    ).reshape(shape) + beta.astype(jnp.float32).reshape(shape)
    return (out.astype(data.dtype),
            jax.lax.stop_gradient(moving_mean),
            jax.lax.stop_gradient(moving_var))


def _ln_fwd(eps, ax, x, g, b):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=ax, keepdims=True)
    # centered two-pass variance — see _bn_stats for why not E[x^2]-E[x]^2
    var = jnp.mean(jnp.square(x32 - mean), axis=ax, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    shape = [1] * x.ndim
    shape[ax] = x.shape[ax]
    out = (x32 - mean) * inv * g.astype(jnp.float32).reshape(shape) + \
        b.astype(jnp.float32).reshape(shape)
    return out.astype(x.dtype), (x, g, mean, inv)


def _ln_bwd(eps, ax, res, ct):
    x, g, mean, inv = res
    n = x.shape[ax]
    shape = [1] * x.ndim
    shape[ax] = n
    dy = ct.astype(jnp.float32) * g.astype(jnp.float32).reshape(shape)
    xhat = (x.astype(jnp.float32) - mean) * inv
    dy_ct = ct.astype(jnp.float32)
    other = tuple(i for i in range(x.ndim) if i != ax % x.ndim)
    dg = jnp.sum(dy_ct * xhat, axis=other)
    db = jnp.sum(dy_ct, axis=other)
    m1 = jnp.mean(dy, axis=ax, keepdims=True)
    m2 = jnp.mean(dy * xhat, axis=ax, keepdims=True)
    dx = inv * (dy - m1 - xhat * m2)
    return dx.astype(x.dtype), dg.astype(g.dtype), db.astype(g.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _ln_core(eps, ax, x, g, b):
    return _ln_fwd(eps, ax, x, g, b)[0]


_ln_core.defvjp(_ln_fwd, _ln_bwd)


@register("LayerNorm", aliases=("layer_norm",))
def layer_norm(data, gamma, beta, axis=-1, eps=1e-5):
    """ref: src/operator/nn/layer_norm.cc — normalizes along one axis.
    Custom-VJP kernel: single-pass f32 stats, bf16-only residuals
    (backward recomputes x_hat instead of saving f32 intermediates)."""
    return _ln_core(float(eps), axis % data.ndim, data, gamma, beta)


@register("InstanceNorm")
def instance_norm(data, gamma, beta, eps=1e-3):
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    shape = [1, data.shape[1]] + [1] * (data.ndim - 2)
    return (data - mean) * jax.lax.rsqrt(var + eps) * gamma.reshape(shape) + \
        beta.reshape(shape)


@register("GroupNorm")
def group_norm(data, gamma, beta, num_groups=1, eps=1e-5):
    n, c = data.shape[0], data.shape[1]
    rest = data.shape[2:]
    x = data.reshape((n, num_groups, c // num_groups) + rest)
    red = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.var(x, axis=red, keepdims=True)
    x = (x - mean) * jax.lax.rsqrt(var + eps)
    x = x.reshape(data.shape)
    shape = [1, c] + [1] * (data.ndim - 2)
    return x * gamma.reshape(shape) + beta.reshape(shape)


@register("LRN")
def lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    """ref: src/operator/nn/lrn.cc — cross-channel local response norm."""
    sq = jnp.square(data)
    half = nsize // 2
    padded = jnp.pad(sq, [(0, 0), (half, half)] + [(0, 0)] * (data.ndim - 2))
    acc = sum(
        jax.lax.dynamic_slice_in_dim(padded, i, data.shape[1], axis=1)
        for i in range(nsize)
    )
    return data / jnp.power(knorm + alpha * acc / nsize, beta)


# --------------------------------------------------------------------------
# pooling (ref: src/operator/nn/pooling.cc) — lax.reduce_window
# --------------------------------------------------------------------------
@register("Pooling", aliases=("pooling", "Pooling_v1"))
def pooling(data, kernel=(), pool_type="max", global_pool=False, stride=(),
            pad=(), pooling_convention="valid", count_include_pad=True,
            cudnn_off=False, p_value=2, layout=None):
    del cudnn_off
    if layout in (None, "NCHW", "NCW", "NCDHW"):
        spatial = tuple(range(2, data.ndim))
    else:
        spatial = tuple(range(1, data.ndim - 1))
    nd = len(spatial)
    if global_pool:
        kernel = tuple(data.shape[ax] for ax in spatial)
        stride = (1,) * nd
        pad = (0,) * nd
    else:
        kernel = tuple(kernel)
        stride = tuple(stride) if stride else (1,) * nd
        pad = tuple(pad) if pad else (0,) * nd

    window = [1] * data.ndim
    strides = [1] * data.ndim
    pads = [(0, 0)] * data.ndim
    for i, ax in enumerate(spatial):
        window[ax] = kernel[i]
        strides[ax] = stride[i]
        if pooling_convention == "full":
            # ceil-mode: add extra right padding so the last window fits
            in_sz = data.shape[ax] + 2 * pad[i]
            rem = (in_sz - kernel[i]) % stride[i]
            extra = (stride[i] - rem) % stride[i] if rem else 0
            pads[ax] = (pad[i], pad[i] + extra)
        else:
            pads[ax] = (pad[i], pad[i])

    # NOTE: init values must be PYTHON scalars — jax pattern-matches
    # (max, -inf) / (add, 0) to reduce_window_max/sum primitives, which are
    # the ones with reverse-mode autodiff rules
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else \
            int(jnp.iinfo(data.dtype).min)
        return jax.lax.reduce_window(
            data, data.dtype.type(init), jax.lax.max, window, strides, pads)
    if pool_type in ("avg", "sum"):
        s = jax.lax.reduce_window(
            data, data.dtype.type(0), jax.lax.add, window, strides, pads)
        if pool_type == "sum":
            return s
        if count_include_pad:
            denom = 1
            for k in kernel:
                denom *= k
            return s / jnp.asarray(denom, data.dtype)
        ones = jnp.ones(data.shape, data.dtype)
        counts = jax.lax.reduce_window(
            ones, data.dtype.type(0), jax.lax.add, window, strides, pads)
        return s / counts
    if pool_type == "lp":
        s = jax.lax.reduce_window(
            jnp.power(jnp.abs(data), p_value), data.dtype.type(0),
            jax.lax.add, window, strides, pads)
        return jnp.power(s, 1.0 / p_value)
    raise ValueError("unknown pool_type %r" % (pool_type,))


@register("UpSampling")
def upsampling(data, scale=1, sample_type="nearest", num_args=1):
    del num_args
    if sample_type != "nearest":
        raise NotImplementedError("only nearest upsampling supported")
    for ax in (2, 3):
        data = jnp.repeat(data, scale, axis=ax)
    return data


@register("BilinearSampler")
def bilinear_sampler(data, grid):
    """ref: src/operator/bilinear_sampler.cc — grid in [-1, 1] NCHW.

    Out-of-image corner samples contribute ZERO (the reference's
    ``between()`` guard — zero padding, not border replication), which
    also makes the autodiff gradients match the reference's backward:
    d(data) scatters only into in-bounds corners and d(grid) sees no
    pull from outside the image."""
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1) * (w - 1) / 2
    gy = (grid[:, 1] + 1) * (h - 1) / 2
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def gather(yi, xi):
        valid = (yi >= 0) & (yi <= h - 1) & (xi >= 0) & (xi <= w - 1)
        yc = jnp.clip(yi.astype(jnp.int32), 0, h - 1)
        xc = jnp.clip(xi.astype(jnp.int32), 0, w - 1)
        flat = data.reshape(n, c, h * w)
        idx = (yc * w + xc).reshape(n, -1)
        out = jnp.take_along_axis(flat, idx[:, None, :], axis=2)
        out = out.reshape(n, c, *gx.shape[1:])
        return out * valid[:, None].astype(out.dtype)

    v00 = gather(y0, x0)
    v01 = gather(y0, x0 + 1)
    v10 = gather(y0 + 1, x0)
    v11 = gather(y0 + 1, x0 + 1)
    wx = wx[:, None]
    wy = wy[:, None]
    return (v00 * (1 - wx) * (1 - wy) + v01 * wx * (1 - wy)
            + v10 * (1 - wx) * wy + v11 * wx * wy)


# --------------------------------------------------------------------------
# spatial-transform / detection ops
# (ref: src/operator/{spatial_transformer,grid_generator,roi_pooling,
#  correlation}.cc)
# --------------------------------------------------------------------------
@register("GridGenerator")
def grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    """ref: src/operator/grid_generator.cc. affine: (N, 6) theta ->
    (N, 2, H, W) sampling grid in [-1, 1]; warp: (N, 2, H, W) flow ->
    grid (flow added to the identity grid, normalized)."""
    if transform_type == "affine":
        h, w = target_shape
        n = data.shape[0]
        ys = jnp.linspace(-1.0, 1.0, h)
        xs = jnp.linspace(-1.0, 1.0, w)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx.ravel(), gy.ravel(),
                          ones.ravel()]).astype(data.dtype)  # (3, HW)
        theta = data.reshape(n, 2, 3)
        out = jnp.einsum("nij,jk->nik", theta, base)  # (N, 2, HW)
        return out.reshape(n, 2, h, w)
    if transform_type == "warp":
        n, _, h, w = data.shape
        ys = jnp.arange(h, dtype=jnp.float32)
        xs = jnp.arange(w, dtype=jnp.float32)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        fx = data[:, 0].astype(jnp.float32) + gx
        fy = data[:, 1].astype(jnp.float32) + gy
        nx = fx * 2.0 / max(w - 1, 1) - 1.0
        ny = fy * 2.0 / max(h - 1, 1) - 1.0
        return jnp.stack([nx, ny], axis=1).astype(data.dtype)
    raise ValueError("unknown transform_type %r" % (transform_type,))


@register("SpatialTransformer")
def spatial_transformer(data, loc, target_shape=(0, 0),
                        transform_type="affine", sampler_type="bilinear",
                        cudnn_off=None):
    """ref: src/operator/spatial_transformer.cc — affine grid + bilinear
    sampling of the input feature map."""
    del cudnn_off
    if sampler_type != "bilinear":
        raise ValueError("only bilinear sampler_type is supported")
    grid = grid_generator(loc, transform_type, target_shape)
    return bilinear_sampler(data, grid)


@register("ROIPooling")
def roi_pooling(data, rois, pooled_size=(1, 1), spatial_scale=1.0):
    """ref: src/operator/roi_pooling.cc — max pool each ROI into a fixed
    (ph, pw) grid. rois: (R, 5) [batch_idx, x1, y1, x2, y2] in image
    coords; boundaries replicate the reference's floor/ceil rounding."""
    ph, pw = pooled_size
    n, c, h, w = data.shape
    # at least f32 for the bin geometry, but never BELOW the input's
    # precision (f64 numeric-grad sweeps would otherwise see f32 noise)
    ct = jnp.promote_types(data.dtype, jnp.float32)

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale).astype(ct)
        y1 = jnp.round(roi[2] * spatial_scale).astype(ct)
        x2 = jnp.round(roi[3] * spatial_scale).astype(ct)
        y2 = jnp.round(roi[4] * spatial_scale).astype(ct)
        rh = jnp.maximum(y2 - y1 + 1.0, 1.0)
        rw = jnp.maximum(x2 - x1 + 1.0, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        fmap = data[b]  # (C, H, W)
        iy = jnp.arange(h, dtype=ct)
        ix = jnp.arange(w, dtype=ct)
        # bin index boundaries: [start, end) per output cell
        ys = y1 + jnp.arange(ph, dtype=ct) * bin_h
        ye = y1 + (jnp.arange(ph, dtype=ct) + 1) * bin_h
        xs_ = x1 + jnp.arange(pw, dtype=ct) * bin_w
        xe = x1 + (jnp.arange(pw, dtype=ct) + 1) * bin_w
        row_m = (iy[None, :] >= jnp.floor(ys)[:, None]) & \
                (iy[None, :] < jnp.ceil(ye)[:, None])      # (ph, H)
        col_m = (ix[None, :] >= jnp.floor(xs_)[:, None]) & \
                (ix[None, :] < jnp.ceil(xe)[:, None])      # (pw, W)
        mask = row_m[:, None, :, None] & col_m[None, :, None, :]
        neg = jnp.asarray(-jnp.inf, ct)
        vals = jnp.where(mask[None], fmap[:, None, None, :, :]
                         .astype(ct), neg)
        out = jnp.max(vals, axis=(3, 4))  # (C, ph, pw)
        return jnp.where(jnp.isfinite(out), out, 0.0)

    out = jax.vmap(one_roi)(rois.astype(ct))
    return out.astype(data.dtype)


@register("Correlation")
def correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True):
    """ref: src/operator/correlation.cc (FlowNet cost volume). Output
    channel k is the per-pixel patch correlation of data1 with data2
    shifted by the k-th displacement in a (2d+1)^2 grid."""
    n, c, h, w = data1.shape
    d = max_displacement // stride2
    if pad_size:
        pad = [(0, 0), (0, 0), (pad_size, pad_size), (pad_size, pad_size)]
        data1 = jnp.pad(data1, pad)
        data2 = jnp.pad(data2, pad)
    # zero-pad by the displacement range so shifts bring in zeros at the
    # borders (the reference zero-pads; jnp.roll would wrap the far edge
    # around and correlate opposite borders)
    m2 = d * stride2
    hh, ww = data1.shape[2], data1.shape[3]
    data2p = jnp.pad(data2, [(0, 0), (0, 0), (m2, m2), (m2, m2)])
    outs = []
    for dy in range(-d, d + 1):
        for dx in range(-d, d + 1):
            oy = m2 + dy * stride2
            ox = m2 + dx * stride2
            shifted = data2p[:, :, oy:oy + hh, ox:ox + ww]
            if is_multiply:
                prod = data1 * shifted
            else:
                prod = jnp.abs(data1 - shifted)
            m = jnp.mean(prod, axis=1)  # mean over channels
            if kernel_size > 1:
                k = kernel_size
                m = jax.lax.reduce_window(
                    m, m.dtype.type(0), jax.lax.add, (1, k, k), (1, 1, 1),
                    [(0, 0), (k // 2, k // 2), (k // 2, k // 2)]
                ) / (k * k)
            outs.append(m)
    out = jnp.stack(outs, axis=1)  # (N, (2d+1)^2, H', W')
    if stride1 > 1:
        out = out[:, :, ::stride1, ::stride1]
    return out


@register("Crop", num_outputs=1)
def crop_op(*args, num_args=1, offset=(0, 0), h_w=(0, 0),
            center_crop=False):
    """Spatial crop of NCHW data (ref: src/operator/crop.cc — the
    FCN-era Crop op; `mx.nd.crop` is a different op, an alias of
    `slice`). With num_args=2 the second input is a shape reference and
    the output matches its (H, W); otherwise h_w gives the target size.
    center_crop centers the window, else `offset` is its top-left
    corner."""
    data = args[0]
    h, w = data.shape[2], data.shape[3]
    if num_args == 2 or len(args) == 2:
        th, tw = args[1].shape[2], args[1].shape[3]
    else:
        th, tw = h_w
    if th > h or tw > w:
        raise ValueError(
            "crop size (%d, %d) exceeds input (%d, %d)" % (th, tw, h, w))
    if center_crop:
        y0, x0 = (h - th) // 2, (w - tw) // 2
    else:
        y0, x0 = offset
        if y0 < 0 or x0 < 0 or y0 + th > h or x0 + tw > w:
            raise ValueError(
                "crop offset %s + size (%d, %d) outside input (%d, %d)"
                % (offset, th, tw, h, w))
    return data[:, :, y0:y0 + th, x0:x0 + tw]
