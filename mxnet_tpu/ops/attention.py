"""Flash attention — Pallas TPU kernel (SURVEY §5 long-context plan; the
reference composes attention from batch_dot+softmax at GluonNLP level with
O(T^2) memory — no fused kernel exists there, this is the TPU-native
upgrade).

Forward is an online-softmax Pallas kernel: Q blocks stream over K/V blocks
held in VMEM, never materializing the (T, T) score matrix in HBM. Backward
recomputes scores blockwise in XLA from the saved logsumexp (standard
flash-v2 recipe; XLA fuses the recompute into the dq/dk/dv matmuls).

Layout: (B, H, T, D) with D the head dim — MXU-friendly (T, D) @ (D, T)
tiles, fp32 accumulation via preferred_element_type.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from .registry import register

# block sizes come from the config registry (MXT_FLASH_BLOCK_Q/K) or,
# when neither is pinned, from the per-shape tuning table
# (tuning/autotune.py) — a bad value fails the attention call with a
# typed error instead of breaking package import
from .. import config as _config


def _block_cfg(name):
    v = int(_config.get(name))
    if v < 8 or v % 8:
        raise MXNetError("%s must be a positive multiple of 8 (TPU "
                         "sublane), got %d" % (name, v))
    return v


def default_blocks():
    """(block_q, block_k) from MXT_FLASH_BLOCK_Q/K, re-read on every
    call — the old first-use memo latched one value for the process
    lifetime, so tests and tpu_watch sweeps could never change blocks
    without a fresh interpreter. The values are plain ints, so jit keys
    stay stable as long as the config does.

    These blocks cover the *training/prefill* flash kernel only. Decode
    shapes (one query token per sequence over a paged KV cache) resolve
    through the tuning table's decode-shape buckets instead —
    ``tuning.resolve_paged`` keys on (batch, heads, head_dim, page_size,
    max-pages bucket) and picks a head-block config for
    :func:`ragged_paged_attention`; MXT_FLASH_BLOCK_Q/K never apply
    there (a Tq=1 query has no query block to tile)."""
    return (_block_cfg("MXT_FLASH_BLOCK_Q"),
            _block_cfg("MXT_FLASH_BLOCK_K"))


def blocks_pinned():
    """True when the user pinned the blocks (env var or set_default) —
    the A/B-sweep override that bypasses the tuning table."""
    return (_config.is_set("MXT_FLASH_BLOCK_Q")
            or _config.is_set("MXT_FLASH_BLOCK_K"))


def _tuned_config(q, k, v, bias, causal, sm_scale):
    """Per-shape kernel decision: pinned blocks win (legacy/global
    behavior), otherwise the tuning table answers — a table hit, or a
    measured/heuristic autotune pass recorded under this shape bucket.
    The returned dict carries the XLA-vs-Pallas choice per shape; the
    device gate (_use_pallas) still applies on top."""
    if str(_config.get("MXT_TUNE_MODE")).lower() == "off" \
            or blocks_pinned():
        bq, bk = default_blocks()
        return {"backend": "pallas", "block_q": bq, "block_k": bk,
                "source": "pinned"}
    from .. import tuning

    return tuning.resolve_attention(
        q.shape, k.shape[2], str(q.dtype), causal,
        arrays=(q, k, v, bias, sm_scale))
_NEG_INF = -1e30
_LSE_LANES = 128  # lane-pad for the lse output (TPU (8,128) tiling)


def _attention_reference(q, k, v, bias, causal, sm_scale):
    """Plain-XLA reference (also the CPU path). O(T^2) memory."""
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores * sm_scale
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        scores = jnp.where(mask, scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------
def _flash_fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref, *,
                      block_k, causal, sm_scale, kv_len, q_len):
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32)  # (BQ, D)
    block_q = q.shape[0]
    iq = pl.program_id(1)
    q_off = iq * block_q
    # pin scalars to 32-bit: with jax_enable_x64 on, Python floats trace as
    # f64 and Mosaic cannot lower the resulting f64 constants/casts
    sm_scale = jnp.float32(sm_scale)
    neg_inf = jnp.float32(_NEG_INF)

    m = jnp.full((block_q,), neg_inf, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, q.shape[1]), jnp.float32)

    num_kv = pl.cdiv(kv_len, block_k)

    def body(ik, carry):
        m_i, l_i, acc_i = carry
        k_blk = k_ref[0, pl.ds(ik * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(ik * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # (BQ, BK)
        if bias_ref is not None:
            s = s + bias_ref[0, 0, pl.ds(ik * block_k, block_k)].astype(
                jnp.float32)[None, :]
        col = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = col < kv_len  # tail-block padding mask
        if causal:
            # bottom-right alignment (matches reference tril(k=Tk-Tq)):
            # query row i attends keys up to i + (Tk - Tq)
            row = q_off + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = jnp.logical_and(valid, col <= row + (kv_len - q_len))
        s = jnp.where(valid, s, neg_inf)

        m_new = jnp.maximum(m_i, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_i - m_new)
        l_new = l_i * alpha + jnp.sum(p, axis=1)
        acc_new = acc_i * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    # i32 bounds: with jax_enable_x64 on (MXNet dtype parity) a plain
    # Python-int loop index traces as i64, which Mosaic cannot lower
    m, l, acc = jax.lax.fori_loop(jnp.int32(0), jnp.int32(num_kv), body,
                                  (m, l, acc))
    l = jnp.maximum(l, jnp.float32(1e-30))
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)
    # lse is stored lane-broadcast as (block_q, 128): Mosaic rejects a
    # (1, block_q) block on a 2-D output (sublane dim of 1), so we follow
    # the official TPU flash kernel's MIN_BLOCK_SIZE padding layout
    lse_ref[0] = jnp.broadcast_to((m + jnp.log(l))[:, None],
                                  (block_q, _LSE_LANES))


def _flash_forward_pallas(q, k, v, bias, causal, sm_scale, block_q, block_k,
                          interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    block_q = min(block_q, Tq)
    block_k = min(block_k, Tk)
    # pad sequence dims to block multiples: partial blocks would otherwise
    # hit dynamic-slice start clamping and read/write shifted rows
    pad_q = (-Tq) % block_q
    pad_k = (-Tk) % block_k
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        if bias is not None:
            bias = jnp.pad(bias, ((0, 0), (0, 0), (0, 0), (0, pad_k)))
    Tqp, Tkp = Tq + pad_q, Tk + pad_k
    qf = q.reshape(B * H, Tqp, D)
    kf = k.reshape(B * H, Tkp, D)
    vf = v.reshape(B * H, Tkp, D)

    # index maps return np.int32 zeros: under jax_enable_x64 a literal 0
    # traces as i64, which Mosaic rejects in the index-map signature
    z = np.int32(0)
    in_specs = [
        pl.BlockSpec((1, block_q, D), lambda bh, iq: (bh, iq, z),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, Tkp, D), lambda bh, iq: (bh, z, z),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, Tkp, D), lambda bh, iq: (bh, z, z),
                     memory_space=pltpu.VMEM),
    ]
    args = [qf, kf, vf]
    if bias is not None:
        # additive key-bias (B, H, 1, Tk) or (B, 1, 1, Tk) → (B*H, 1, Tk);
        # kept 3-D so the (1, 1, Tkp) block satisfies Mosaic's tiling rule
        # (a (1, Tkp) block on a 2-D array has an untiled sublane dim)
        bflat = jnp.broadcast_to(bias, (B, H, 1, Tkp)).reshape(B * H, 1, Tkp)
        in_specs.append(pl.BlockSpec((1, 1, Tkp), lambda bh, iq: (bh, z, z),
                                     memory_space=pltpu.VMEM))
        args.append(bflat)

    if bias is not None:
        def kernel(q_ref, k_ref, v_ref, b_ref, o_ref, lse_ref):
            _flash_fwd_kernel(q_ref, k_ref, v_ref, b_ref, o_ref, lse_ref,
                              block_k=block_k, causal=causal,
                              sm_scale=sm_scale, kv_len=Tk, q_len=Tq)
    else:
        def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref):
            _flash_fwd_kernel(q_ref, k_ref, v_ref, None, o_ref, lse_ref,
                              block_k=block_k, causal=causal,
                              sm_scale=sm_scale, kv_len=Tk, q_len=Tq)

    grid = (B * H, Tqp // block_q)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, iq: (bh, iq, z),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, _LSE_LANES), lambda bh, iq: (bh, iq, z),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tqp, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Tqp, _LSE_LANES), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    out = out.reshape(B, H, Tqp, D)[:, :, :Tq]
    lse = lse[:, :, 0].reshape(B, H, Tqp)[:, :, :Tq]
    return out, lse


# ---------------------------------------------------------------------------
# chunked-XLA path for long sequences (K/V too big for whole-sequence VMEM
# residency; lax.scan streams KV chunks with the same online softmax —
# O(Tq * chunk) memory, fused by XLA)
# ---------------------------------------------------------------------------
_VMEM_KV_BYTES = 4 * 1024 * 1024  # per-(batch,head) K+V budget
LONG_CHUNK = 1024


def _kv_fits_vmem(k):
    return 2 * k.shape[2] * k.shape[3] * k.dtype.itemsize <= _VMEM_KV_BYTES


def _chunk_kv(x, chunk):
    B, H, Tk, D = x.shape
    pad = (-Tk) % chunk
    if pad:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))
    return x.reshape(B, H, (Tk + pad) // chunk, chunk, D), pad


def _attention_scan_fwd(q, k, v, bias, causal, sm_scale, chunk=LONG_CHUNK):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    kc, pad = _chunk_kv(k, chunk)
    vc, _ = _chunk_kv(v, chunk)
    nchunks = kc.shape[2]
    if bias is not None:
        bias_p = jnp.pad(bias, ((0, 0), (0, 0), (0, 0), (0, pad)),
                         constant_values=_NEG_INF)
        bc = jnp.moveaxis(
            bias_p.reshape(bias.shape[0], bias.shape[1], 1, nchunks, chunk),
            3, 0)
    qf = q.astype(jnp.float32)

    def body(carry, xs):
        m_i, l_i, acc_i = carry
        if bias is not None:
            k_c, v_c, b_c, idx = xs
        else:
            k_c, v_c, idx = xs
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_c.astype(jnp.float32),
                       preferred_element_type=jnp.float32) * sm_scale
        if bias is not None:
            s = s + b_c.astype(jnp.float32)
        col = idx * chunk + jnp.arange(chunk)
        valid = col[None, :] < Tk
        if causal:
            row = jnp.arange(Tq)
            valid = jnp.logical_and(
                valid, col[None, :] <= row[:, None] + (Tk - Tq))
        s = jnp.where(valid[None, None], s, _NEG_INF)
        m_new = jnp.maximum(m_i, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m_i - m_new)
        l_new = l_i * alpha + jnp.sum(p, axis=-1)
        acc_new = acc_i * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_c.astype(jnp.float32))
        return (m_new, l_new, acc_new), None

    init = (jnp.full((B, H, Tq), _NEG_INF, jnp.float32),
            jnp.zeros((B, H, Tq), jnp.float32),
            jnp.zeros((B, H, Tq, D), jnp.float32))
    idxs = jnp.arange(nchunks)
    xs = (jnp.moveaxis(kc, 2, 0), jnp.moveaxis(vc, 2, 0), bc, idxs) \
        if bias is not None else \
        (jnp.moveaxis(kc, 2, 0), jnp.moveaxis(vc, 2, 0), idxs)
    (m, l, acc), _ = jax.lax.scan(body, init, xs)
    l = jnp.maximum(l, 1e-30)
    return (acc / l[..., None]).astype(q.dtype), m + jnp.log(l)


def _bwd_chunked(q, k, v, bias, out, lse, do, causal, sm_scale,
                 chunk=LONG_CHUNK):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * out.astype(jnp.float32), axis=-1)  # (B,H,Tq)
    kc, pad = _chunk_kv(k, chunk)
    vc, _ = _chunk_kv(v, chunk)
    nchunks = kc.shape[2]
    if bias is not None:
        bias_p = jnp.pad(bias, ((0, 0), (0, 0), (0, 0), (0, pad)),
                         constant_values=_NEG_INF)
        bc = jnp.moveaxis(
            bias_p.reshape(bias.shape[0], bias.shape[1], 1, nchunks, chunk),
            3, 0)

    def body(dq_acc, xs):
        if bias is not None:
            k_c, v_c, b_c, idx = xs
        else:
            k_c, v_c, idx = xs
        kcf = k_c.astype(jnp.float32)
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kcf,
                       preferred_element_type=jnp.float32) * sm_scale
        if bias is not None:
            s = s + b_c.astype(jnp.float32)
        col = idx * chunk + jnp.arange(chunk)
        valid = col[None, :] < Tk
        if causal:
            row = jnp.arange(Tq)
            valid = jnp.logical_and(
                valid, col[None, :] <= row[:, None] + (Tk - Tq))
        s = jnp.where(valid[None, None], s, _NEG_INF)
        p = jnp.exp(s - lse[..., None])
        dv_c = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
        dp = jnp.einsum("bhqd,bhkd->bhqk", dof, v_c.astype(jnp.float32))
        ds = p * (dp - delta[..., None]) * sm_scale
        dq_acc = dq_acc + jnp.einsum("bhqk,bhkd->bhqd", ds, kcf)
        dk_c = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
        db_c = jnp.sum(ds, axis=2) / sm_scale  # (B,H,chunk)
        return dq_acc, (dk_c, dv_c, db_c)

    idxs = jnp.arange(nchunks)
    xs = (jnp.moveaxis(kc, 2, 0), jnp.moveaxis(vc, 2, 0), bc, idxs) \
        if bias is not None else \
        (jnp.moveaxis(kc, 2, 0), jnp.moveaxis(vc, 2, 0), idxs)
    dq, (dk_s, dv_s, db_s) = jax.lax.scan(body, jnp.zeros_like(qf), xs)
    dk = jnp.moveaxis(dk_s, 0, 2).reshape(B, H, Tk + pad, D)[:, :, :Tk]
    dv = jnp.moveaxis(dv_s, 0, 2).reshape(B, H, Tk + pad, D)[:, :, :Tk]
    dbias = None
    if bias is not None:
        db = jnp.moveaxis(db_s, 0, 2).reshape(B, H, Tk + pad)[:, :, :Tk]
        dbias = db[:, :, None, :]
        if bias.shape[1] == 1:
            dbias = jnp.sum(dbias, axis=1, keepdims=True)
        dbias = dbias.astype(bias.dtype)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            dbias)


# ---------------------------------------------------------------------------
# custom vjp: pallas forward, XLA-recompute backward
# ---------------------------------------------------------------------------
def _use_pallas():
    # the TPU backend registers as 'tpu' (or 'axon' via the PJRT tunnel
    # plugin); anything else (cpu, gpu) takes the XLA paths
    return jax.default_backend() in ("tpu", "axon")


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash_core(q, k, v, bias, causal, sm_scale):
    out, _ = _flash_fwd(q, k, v, bias, causal, sm_scale)
    return out


def _flash_fwd(q, k, v, bias, causal, sm_scale):
    _record_flash_signature(q, k, v, bias, causal, sm_scale)
    if not _kv_fits_vmem(k):
        out, lse = _attention_scan_fwd(q, k, v, bias, causal, sm_scale)
    else:
        cfg = _tuned_config(q, k, v, bias, causal, sm_scale)
        if cfg.get("backend") == "pallas" and _use_pallas():
            out, lse = _flash_forward_pallas(
                q, k, v, bias, causal, sm_scale,
                int(cfg["block_q"]), int(cfg["block_k"]), interpret=False)
        else:
            # per-shape XLA choice (small shapes, or a tuned decision
            # that XLA's fused reference wins here), and every non-TPU
            # backend
            out = _attention_reference(q, k, v, bias, causal, sm_scale)
            lse = None
    return out, (q, k, v, bias, out, lse)


def _record_flash_signature(q, k, v, bias, causal, sm_scale):
    """Remember this dispatch's shape signature for tuning.warmup()'s
    AOT replay (deduplicated in the table; a fresh serving replica
    compiles these ahead of traffic)."""
    try:
        from .. import tuning

        tuning.record_signature("flash_attention", {
            "q_shape": list(q.shape), "k_shape": list(k.shape),
            "v_shape": list(v.shape),
            "bias_shape": None if bias is None else list(bias.shape),
            "bias_dtype": None if bias is None else str(bias.dtype),
            "dtype": str(q.dtype), "causal": bool(causal),
            "sm_scale": float(sm_scale)})
    except Exception:  # noqa: BLE001 — bookkeeping must not fail the op
        pass


_BWD_SCORE_BYTES = 256 * 1024 * 1024  # peak score-matrix budget in backward


def _flash_bwd(causal, sm_scale, res, do):
    q, k, v, bias, out, lse = res
    B, H, Tq, _ = q.shape
    Tk = k.shape[2]
    score_bytes = B * H * Tq * Tk * 4
    if not _kv_fits_vmem(k) or score_bytes > _BWD_SCORE_BYTES:
        # keep backward O(Tq * chunk): a forward that fit VMEM can still
        # have a score matrix far too big to materialize (e.g. T=8k)
        if lse is None:
            _, lse = _attention_scan_fwd(q, k, v, bias, causal, sm_scale)
        chunk = int(max(128, min(
            Tk, _BWD_SCORE_BYTES // max(1, B * H * Tq * 4))))
        return _bwd_chunked(q, k, v, bias, out, lse, do, causal, sm_scale,
                            chunk=chunk)
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf,
                   preferred_element_type=jnp.float32) * sm_scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        s = jnp.where(mask, s, _NEG_INF)
    if lse is not None:
        p = jnp.exp(s - lse[..., None])
    else:
        p = jax.nn.softmax(s, axis=-1)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
    dp = jnp.einsum("bhqd,bhkd->bhqk", dof, vf)
    delta = jnp.sum(dof * out.astype(jnp.float32), axis=-1, keepdims=True)
    ds = p * (dp - delta) * sm_scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, kf).astype(q.dtype)
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf).astype(k.dtype)
    dbias = None
    if bias is not None:
        db = ds / sm_scale
        # reduce over broadcast dims of the (B, H|1, 1, Tk) bias
        dbias = jnp.sum(db, axis=2, keepdims=True)
        if bias.shape[1] == 1:
            dbias = jnp.sum(dbias, axis=1, keepdims=True)
        dbias = dbias.astype(bias.dtype)
    return dq, dk, dv.astype(v.dtype), dbias


_flash_core.defvjp(_flash_fwd, _flash_bwd)


@register("flash_attention", aliases=("_contrib_flash_attention",))
def flash_attention(query, key, value, bias=None, causal=False,
                    sm_scale=None):
    """Fused scaled-dot-product attention. query/key/value: (B, H, T, D);
    bias: optional additive (B, H|1, 1, Tk) mask (use large negatives to
    mask). Returns (B, H, Tq, D).

    Inside ``parallel.sequence_scope(mesh, axis, schedule)`` this
    dispatches to a sequence-parallel schedule (ring KV rotation, or
    Ulysses head all-to-all when heads divide and there is no bias) —
    the hook that makes every attention user sequence-parallel without
    model changes."""
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(query.shape[-1]))
    from ..parallel.sequence import current_sequence_scope, ring_attention

    scope = current_sequence_scope()
    if scope is not None and query.shape[2] == key.shape[2]:
        # the scope covers sequence-sharded SELF-attention;
        # rectangular attention (cross-attention, Tq=1 decode steps)
        # falls through to the flash kernel untouched
        mesh, seq_axis, schedule = scope
        if jax.process_count() > 1:
            raise MXNetError(
                "sequence_scope's eager dispatch is single-process; on "
                "multi-host meshes call parallel.ring_attention inside "
                "your pjit/shard_map program instead")
        from ..parallel.sequence import ulysses_attention

        if (schedule == "ulysses" and bias is None
                and query.shape[1] % mesh.shape[seq_axis] == 0):
            out = ulysses_attention(query, key, value, mesh=mesh,
                                    seq_axis=seq_axis,
                                    causal=bool(causal),
                                    sm_scale=float(sm_scale))
        else:  # ring handles biases and any head count
            out = ring_attention(query, key, value, bias=bias, mesh=mesh,
                                 seq_axis=seq_axis, causal=bool(causal),
                                 sm_scale=float(sm_scale))
        # bring the mesh-sharded result back to a single device so it
        # composes with unsharded surrounding ops on the eager path
        # (device_put is traceable; under full-program jit it's just a
        # sharding constraint XLA folds away)
        out = jax.device_put(
            out, jax.sharding.SingleDeviceSharding(
                mesh.devices.flat[0]))
        return out
    return _flash_core(query, key, value, bias, bool(causal),
                       float(sm_scale))


@register("attention_padding_bias", differentiable=False)
def make_padding_bias(valid_length, max_len=None, dtype="float32"):
    """(B,) lengths → additive (B, 1, 1, T) bias: 0 for valid, -1e30 after.
    ``max_len`` (the key sequence length) is required."""
    if not max_len:
        raise ValueError("attention_padding_bias requires max_len= (the "
                         "key sequence length)")
    idx = jnp.arange(max_len)[None, :]
    mask = idx < valid_length.astype(jnp.int32)[:, None]
    bias = jnp.where(mask, 0.0, _NEG_INF).astype(jnp.dtype(dtype))
    return bias[:, None, None, :]


# ---------------------------------------------------------------------------
# ragged / paged decode attention (serving; PAPERS.md arXiv 2604.15464)
# ---------------------------------------------------------------------------
def ragged_attention_reference(q, k, v, valid_length, sm_scale=None):
    """Dense masked reference for ragged decode — the correctness oracle
    for :func:`ragged_paged_attention`.

    One query token per sequence attends its own prefix: ``q`` is
    ``(B, H, D)`` (or ``(B, H, 1, D)``), ``k``/``v`` are dense
    ``(B, H, Tmax, D)``, ``valid_length`` is ``(B,)`` — sequence ``b``
    sees exactly keys ``[0, valid_length[b])``; everything after is
    masked with the same -1e30 bias ``make_padding_bias`` produces, so
    the paged kernel and this path share one masking definition."""
    squeeze = q.ndim == 3
    if squeeze:
        q = q[:, :, None, :]
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(q.shape[-1]))
    bias = make_padding_bias(valid_length, max_len=k.shape[2],
                             dtype="float32")
    out = _attention_reference(q, k, v, bias, False, float(sm_scale))
    return out[:, :, 0] if squeeze else out


def _paged_gather_reference(q, k_pages, v_pages, page_table, context_lens,
                            sm_scale, k_scales=None, v_scales=None):
    """XLA path: gather the page table into dense K/V and run the masked
    reference. Correct everywhere (the CPU/serving-test path) and the
    per-shape alternative the tuning table may prefer on-chip for short
    contexts, where one fused gather+softmax beats the kernel's
    page-at-a-time grid.

    Quantized pools (int8 pages + per-(position, head) amax planes)
    dequantize AFTER the gather — only the sequence's own pages pay the
    int8->f32 convert, never the whole pool."""
    B = q.shape[0]
    P, S, H, D = k_pages.shape
    max_pages = page_table.shape[1]
    flat = page_table.reshape(-1)
    kg = k_pages[flat].reshape(B, max_pages, S, H, D)
    vg = v_pages[flat].reshape(B, max_pages, S, H, D)
    if k_scales is not None:
        kg = kg.astype(jnp.float32) * (
            k_scales[flat].reshape(B, max_pages, S, H)
            * (1.0 / 127.0))[..., None]
        vg = vg.astype(jnp.float32) * (
            v_scales[flat].reshape(B, max_pages, S, H)
            * (1.0 / 127.0))[..., None]
    k = jnp.transpose(kg.reshape(B, max_pages * S, H, D), (0, 2, 1, 3))
    v = jnp.transpose(vg.reshape(B, max_pages * S, H, D), (0, 2, 1, 3))
    return ragged_attention_reference(q, k, v, context_lens, sm_scale)


def _paged_decode_kernel(pt_ref, cl_ref, q_ref, k_ref, v_ref, o_ref,
                         m_scr, l_scr, acc_scr, *, page_size, block_h,
                         sm_scale):
    """One (sequence, head-block, page) grid step of the ragged paged
    decode kernel. The page axis is the innermost (sequential) grid
    dimension, so the online-softmax state rides VMEM scratch across a
    sequence's pages — the flash recipe with the KV stream indirected
    through the page table (pt_ref/cl_ref are scalar-prefetch refs; the
    BlockSpec index map already used pt_ref to DMA this step's page)."""
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    j = pl.program_id(2)
    npages = pl.num_programs(2)
    sm_scale = jnp.float32(sm_scale)
    neg_inf = jnp.float32(_NEG_INF)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, neg_inf, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    q = q_ref[0].astype(jnp.float32)          # (block_h, D)
    k = k_ref[0].astype(jnp.float32)          # (page_size, block_h, D)
    v = v_ref[0].astype(jnp.float32)
    length = cl_ref[b]
    # tokens this page covers; everything at/after the sequence length
    # (ragged tail, pages past the last used one) masks to -inf
    col = j * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (block_h, page_size), 1)
    valid = col < length

    # per-head matvecs, statically unrolled over the head block (the
    # head-batched dot_general has no Mosaic lowering; block_h is the
    # tuned unroll width)
    rows = [jax.lax.dot_general(q[h:h + 1], k[:, h, :],
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
            for h in range(block_h)]
    s = jnp.concatenate(rows, axis=0) * sm_scale   # (block_h, page_size)
    s = jnp.where(valid, s, neg_inf)

    m_prev = m_scr[...]                        # (block_h, LANES), lane-bcast
    l_prev = l_scr[...]
    m_new = jnp.maximum(m_prev, jnp.broadcast_to(
        jnp.max(s, axis=1, keepdims=True), m_prev.shape))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new[:, :1])
    l_new = l_prev * alpha + jnp.broadcast_to(
        jnp.sum(p, axis=1, keepdims=True), l_prev.shape)
    pv_rows = [jax.lax.dot_general(p[h:h + 1], v[:, h, :],
                                   (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)
               for h in range(block_h)]
    m_scr[...] = m_new
    l_scr[...] = l_new
    acc_scr[...] = acc_scr[...] * alpha[:, :1] \
        + jnp.concatenate(pv_rows, axis=0)

    @pl.when(j == npages - 1)
    def _finish():
        l = jnp.maximum(l_scr[...][:, :1], jnp.float32(1e-30))
        o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)


def _paged_decode_pallas(q, k_pages, v_pages, page_table, context_lens,
                         sm_scale, block_h, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, D = q.shape
    P, S, Hk, Dk = k_pages.shape
    max_pages = page_table.shape[1]
    block_h = max(1, min(int(block_h), H))
    while H % block_h:  # candidates are divisors; pinned values may not be
        block_h -= 1
    page_table = page_table.astype(jnp.int32)
    context_lens = context_lens.astype(jnp.int32)

    z = np.int32(0)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H // block_h, max_pages),
        in_specs=[
            pl.BlockSpec((1, block_h, D),
                         lambda b, hb, j, pt, cl: (b, hb, z)),
            # page indirection: the page table names which pool page this
            # grid step streams in (a padded slot reads page 0, fully
            # masked by the ragged length check)
            pl.BlockSpec((1, S, block_h, D),
                         lambda b, hb, j, pt, cl: (pt[b, j], z, hb, z)),
            pl.BlockSpec((1, S, block_h, D),
                         lambda b, hb, j, pt, cl: (pt[b, j], z, hb, z)),
        ],
        out_specs=pl.BlockSpec((1, block_h, D),
                               lambda b, hb, j, pt, cl: (b, hb, z)),
        scratch_shapes=[
            pltpu.VMEM((block_h, _LSE_LANES), jnp.float32),
            pltpu.VMEM((block_h, _LSE_LANES), jnp.float32),
            pltpu.VMEM((block_h, D), jnp.float32),
        ],
    )
    kernel = functools.partial(_paged_decode_kernel, page_size=S,
                               block_h=block_h, sm_scale=sm_scale)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        interpret=interpret,
    )(page_table, context_lens, q, k_pages, v_pages)


def _record_paged_signature(q, k_pages, page_table, sm_scale,
                            quantized=False):
    """Remember this decode dispatch's shape signature so a fresh
    serving replica's tuning.warmup() can AOT-compile the paged
    attention program before the first request lands."""
    try:
        from .. import tuning

        tuning.record_signature("paged_attention", {
            "q_shape": list(q.shape), "pool_shape": list(k_pages.shape),
            "max_pages": int(page_table.shape[1]),
            "pool_dtype": str(k_pages.dtype),
            "quantized": bool(quantized),
            "dtype": str(q.dtype), "sm_scale": float(sm_scale)})
    except Exception:  # noqa: BLE001 — bookkeeping must not fail the op
        pass


@register("ragged_paged_attention", differentiable=False)
def ragged_paged_attention(query, k_pages, v_pages, page_table,
                           context_lens, sm_scale=None, interpret=None,
                           k_scales=None, v_scales=None):
    """Decode-time attention over a paged KV cache — one query token per
    sequence gathers its K/V prefix through a page table (PAPERS.md
    arXiv 2604.15464; the serving sibling of :func:`flash_attention`).

    ``query``: (B, H, D) — this step's single token per sequence.
    ``k_pages``/``v_pages``: (num_pages, page_size, H, D) device pools.
    ``page_table``: (B, max_pages) int32 — pool page ids per sequence,
    in order; padded slots may repeat any valid page (they are masked).
    ``context_lens``: (B,) int32 — tokens of live prefix per sequence
    (ragged: any mix of lengths, including 1). Returns (B, H, D).

    Backend choice and the head-block config come from the tuning table
    (``tuning.resolve_paged``), exactly like the flash kernel's blocks;
    ``interpret=True`` forces the Pallas kernel in interpret mode (the
    CPU parity path tests use).

    ``k_scales``/``v_scales`` — (num_pages, page_size, H) per-row amax
    planes — mark the pools int8-quantized: the gather fallback
    dequantizes after the page gather. The Pallas kernel has no
    quantized lowering yet, so quantized pools always take the XLA
    path (the tuning-table backend choice applies to f32 pools only)."""
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(query.shape[-1]))
    sm_scale = float(sm_scale)
    _record_paged_signature(query, k_pages, page_table, sm_scale,
                            quantized=k_scales is not None)
    if k_scales is not None:
        return _paged_gather_reference(query, k_pages, v_pages,
                                       page_table, context_lens, sm_scale,
                                       k_scales, v_scales)
    from .. import tuning

    cfg = tuning.resolve_paged(
        query.shape, k_pages.shape[1], page_table.shape[1],
        str(query.dtype))
    if interpret:
        return _paged_decode_pallas(query, k_pages, v_pages, page_table,
                                    context_lens, sm_scale,
                                    int(cfg.get("block_h", 1)),
                                    interpret=True)
    if cfg.get("backend") == "pallas" and _use_pallas():
        return _paged_decode_pallas(query, k_pages, v_pages, page_table,
                                    context_lens, sm_scale,
                                    int(cfg.get("block_h", 1)),
                                    interpret=False)
    return _paged_gather_reference(query, k_pages, v_pages, page_table,
                                   context_lens, sm_scale)
