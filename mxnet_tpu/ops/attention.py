"""Flash attention — Pallas TPU kernel (SURVEY §5 long-context plan; the
reference composes attention from batch_dot+softmax at GluonNLP level with
O(T^2) memory — no fused kernel exists there, this is the TPU-native
upgrade).

Forward is an online-softmax Pallas kernel: Q blocks stream over K/V blocks
held in VMEM, never materializing the (T, T) score matrix in HBM. Backward
recomputes scores blockwise in XLA from the saved logsumexp (standard
flash-v2 recipe; XLA fuses the recompute into the dq/dk/dv matmuls).

Layout: (B, H, T, D) with D the head dim — MXU-friendly (T, D) @ (D, T)
tiles, fp32 accumulation via preferred_element_type.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from .registry import register

# block sizes come from the config registry (MXT_FLASH_BLOCK_Q/K) or,
# when neither is pinned, from the per-shape tuning table
# (tuning/autotune.py) — a bad value fails the attention call with a
# typed error instead of breaking package import
from .. import config as _config


def _block_cfg(name):
    v = int(_config.get(name))
    if v < 8 or v % 8:
        raise MXNetError("%s must be a positive multiple of 8 (TPU "
                         "sublane), got %d" % (name, v))
    return v


def default_blocks():
    """(block_q, block_k) from MXT_FLASH_BLOCK_Q/K, re-read on every
    call — the old first-use memo latched one value for the process
    lifetime, so tests and tpu_watch sweeps could never change blocks
    without a fresh interpreter. The values are plain ints, so jit keys
    stay stable as long as the config does."""
    return (_block_cfg("MXT_FLASH_BLOCK_Q"),
            _block_cfg("MXT_FLASH_BLOCK_K"))


def blocks_pinned():
    """True when the user pinned the blocks (env var or set_default) —
    the A/B-sweep override that bypasses the tuning table."""
    return (_config.is_set("MXT_FLASH_BLOCK_Q")
            or _config.is_set("MXT_FLASH_BLOCK_K"))


def _tuned_config(q, k, v, bias, causal, sm_scale):
    """Per-shape kernel decision: pinned blocks win (legacy/global
    behavior), otherwise the tuning table answers — a table hit, or a
    measured/heuristic autotune pass recorded under this shape bucket.
    The returned dict carries the XLA-vs-Pallas choice per shape; the
    device gate (_use_pallas) still applies on top."""
    if str(_config.get("MXT_TUNE_MODE")).lower() == "off" \
            or blocks_pinned():
        bq, bk = default_blocks()
        return {"backend": "pallas", "block_q": bq, "block_k": bk,
                "source": "pinned"}
    from .. import tuning

    return tuning.resolve_attention(
        q.shape, k.shape[2], str(q.dtype), causal,
        arrays=(q, k, v, bias, sm_scale))
_NEG_INF = -1e30
_LSE_LANES = 128  # lane-pad for the lse output (TPU (8,128) tiling)


def _attention_reference(q, k, v, bias, causal, sm_scale):
    """Plain-XLA reference (also the CPU path). O(T^2) memory."""
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores * sm_scale
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        scores = jnp.where(mask, scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------
def _flash_fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref, *,
                      block_k, causal, sm_scale, kv_len, q_len):
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32)  # (BQ, D)
    block_q = q.shape[0]
    iq = pl.program_id(1)
    q_off = iq * block_q
    # pin scalars to 32-bit: with jax_enable_x64 on, Python floats trace as
    # f64 and Mosaic cannot lower the resulting f64 constants/casts
    sm_scale = jnp.float32(sm_scale)
    neg_inf = jnp.float32(_NEG_INF)

    m = jnp.full((block_q,), neg_inf, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, q.shape[1]), jnp.float32)

    num_kv = pl.cdiv(kv_len, block_k)

    def body(ik, carry):
        m_i, l_i, acc_i = carry
        k_blk = k_ref[0, pl.ds(ik * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(ik * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # (BQ, BK)
        if bias_ref is not None:
            s = s + bias_ref[0, 0, pl.ds(ik * block_k, block_k)].astype(
                jnp.float32)[None, :]
        col = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = col < kv_len  # tail-block padding mask
        if causal:
            # bottom-right alignment (matches reference tril(k=Tk-Tq)):
            # query row i attends keys up to i + (Tk - Tq)
            row = q_off + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = jnp.logical_and(valid, col <= row + (kv_len - q_len))
        s = jnp.where(valid, s, neg_inf)

        m_new = jnp.maximum(m_i, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_i - m_new)
        l_new = l_i * alpha + jnp.sum(p, axis=1)
        acc_new = acc_i * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    # i32 bounds: with jax_enable_x64 on (MXNet dtype parity) a plain
    # Python-int loop index traces as i64, which Mosaic cannot lower
    m, l, acc = jax.lax.fori_loop(jnp.int32(0), jnp.int32(num_kv), body,
                                  (m, l, acc))
    l = jnp.maximum(l, jnp.float32(1e-30))
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)
    # lse is stored lane-broadcast as (block_q, 128): Mosaic rejects a
    # (1, block_q) block on a 2-D output (sublane dim of 1), so we follow
    # the official TPU flash kernel's MIN_BLOCK_SIZE padding layout
    lse_ref[0] = jnp.broadcast_to((m + jnp.log(l))[:, None],
                                  (block_q, _LSE_LANES))


def _flash_forward_pallas(q, k, v, bias, causal, sm_scale, block_q, block_k,
                          interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    block_q = min(block_q, Tq)
    block_k = min(block_k, Tk)
    # pad sequence dims to block multiples: partial blocks would otherwise
    # hit dynamic-slice start clamping and read/write shifted rows
    pad_q = (-Tq) % block_q
    pad_k = (-Tk) % block_k
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        if bias is not None:
            bias = jnp.pad(bias, ((0, 0), (0, 0), (0, 0), (0, pad_k)))
    Tqp, Tkp = Tq + pad_q, Tk + pad_k
    qf = q.reshape(B * H, Tqp, D)
    kf = k.reshape(B * H, Tkp, D)
    vf = v.reshape(B * H, Tkp, D)

    # index maps return np.int32 zeros: under jax_enable_x64 a literal 0
    # traces as i64, which Mosaic rejects in the index-map signature
    z = np.int32(0)
    in_specs = [
        pl.BlockSpec((1, block_q, D), lambda bh, iq: (bh, iq, z),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, Tkp, D), lambda bh, iq: (bh, z, z),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, Tkp, D), lambda bh, iq: (bh, z, z),
                     memory_space=pltpu.VMEM),
    ]
    args = [qf, kf, vf]
    if bias is not None:
        # additive key-bias (B, H, 1, Tk) or (B, 1, 1, Tk) → (B*H, 1, Tk);
        # kept 3-D so the (1, 1, Tkp) block satisfies Mosaic's tiling rule
        # (a (1, Tkp) block on a 2-D array has an untiled sublane dim)
        bflat = jnp.broadcast_to(bias, (B, H, 1, Tkp)).reshape(B * H, 1, Tkp)
        in_specs.append(pl.BlockSpec((1, 1, Tkp), lambda bh, iq: (bh, z, z),
                                     memory_space=pltpu.VMEM))
        args.append(bflat)

    if bias is not None:
        def kernel(q_ref, k_ref, v_ref, b_ref, o_ref, lse_ref):
            _flash_fwd_kernel(q_ref, k_ref, v_ref, b_ref, o_ref, lse_ref,
                              block_k=block_k, causal=causal,
                              sm_scale=sm_scale, kv_len=Tk, q_len=Tq)
    else:
        def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref):
            _flash_fwd_kernel(q_ref, k_ref, v_ref, None, o_ref, lse_ref,
                              block_k=block_k, causal=causal,
                              sm_scale=sm_scale, kv_len=Tk, q_len=Tq)

    grid = (B * H, Tqp // block_q)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, iq: (bh, iq, z),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, _LSE_LANES), lambda bh, iq: (bh, iq, z),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tqp, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Tqp, _LSE_LANES), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    out = out.reshape(B, H, Tqp, D)[:, :, :Tq]
    lse = lse[:, :, 0].reshape(B, H, Tqp)[:, :, :Tq]
    return out, lse


# ---------------------------------------------------------------------------
# chunked-XLA path for long sequences (K/V too big for whole-sequence VMEM
# residency; lax.scan streams KV chunks with the same online softmax —
# O(Tq * chunk) memory, fused by XLA)
# ---------------------------------------------------------------------------
_VMEM_KV_BYTES = 4 * 1024 * 1024  # per-(batch,head) K+V budget
LONG_CHUNK = 1024


def _kv_fits_vmem(k):
    return 2 * k.shape[2] * k.shape[3] * k.dtype.itemsize <= _VMEM_KV_BYTES


def _chunk_kv(x, chunk):
    B, H, Tk, D = x.shape
    pad = (-Tk) % chunk
    if pad:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))
    return x.reshape(B, H, (Tk + pad) // chunk, chunk, D), pad


def _attention_scan_fwd(q, k, v, bias, causal, sm_scale, chunk=LONG_CHUNK):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    kc, pad = _chunk_kv(k, chunk)
    vc, _ = _chunk_kv(v, chunk)
    nchunks = kc.shape[2]
    if bias is not None:
        bias_p = jnp.pad(bias, ((0, 0), (0, 0), (0, 0), (0, pad)),
                         constant_values=_NEG_INF)
        bc = jnp.moveaxis(
            bias_p.reshape(bias.shape[0], bias.shape[1], 1, nchunks, chunk),
            3, 0)
    qf = q.astype(jnp.float32)

    def body(carry, xs):
        m_i, l_i, acc_i = carry
        if bias is not None:
            k_c, v_c, b_c, idx = xs
        else:
            k_c, v_c, idx = xs
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_c.astype(jnp.float32),
                       preferred_element_type=jnp.float32) * sm_scale
        if bias is not None:
            s = s + b_c.astype(jnp.float32)
        col = idx * chunk + jnp.arange(chunk)
        valid = col[None, :] < Tk
        if causal:
            row = jnp.arange(Tq)
            valid = jnp.logical_and(
                valid, col[None, :] <= row[:, None] + (Tk - Tq))
        s = jnp.where(valid[None, None], s, _NEG_INF)
        m_new = jnp.maximum(m_i, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m_i - m_new)
        l_new = l_i * alpha + jnp.sum(p, axis=-1)
        acc_new = acc_i * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_c.astype(jnp.float32))
        return (m_new, l_new, acc_new), None

    init = (jnp.full((B, H, Tq), _NEG_INF, jnp.float32),
            jnp.zeros((B, H, Tq), jnp.float32),
            jnp.zeros((B, H, Tq, D), jnp.float32))
    idxs = jnp.arange(nchunks)
    xs = (jnp.moveaxis(kc, 2, 0), jnp.moveaxis(vc, 2, 0), bc, idxs) \
        if bias is not None else \
        (jnp.moveaxis(kc, 2, 0), jnp.moveaxis(vc, 2, 0), idxs)
    (m, l, acc), _ = jax.lax.scan(body, init, xs)
    l = jnp.maximum(l, 1e-30)
    return (acc / l[..., None]).astype(q.dtype), m + jnp.log(l)


def _bwd_chunked(q, k, v, bias, out, lse, do, causal, sm_scale,
                 chunk=LONG_CHUNK):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * out.astype(jnp.float32), axis=-1)  # (B,H,Tq)
    kc, pad = _chunk_kv(k, chunk)
    vc, _ = _chunk_kv(v, chunk)
    nchunks = kc.shape[2]
    if bias is not None:
        bias_p = jnp.pad(bias, ((0, 0), (0, 0), (0, 0), (0, pad)),
                         constant_values=_NEG_INF)
        bc = jnp.moveaxis(
            bias_p.reshape(bias.shape[0], bias.shape[1], 1, nchunks, chunk),
            3, 0)

    def body(dq_acc, xs):
        if bias is not None:
            k_c, v_c, b_c, idx = xs
        else:
            k_c, v_c, idx = xs
        kcf = k_c.astype(jnp.float32)
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kcf,
                       preferred_element_type=jnp.float32) * sm_scale
        if bias is not None:
            s = s + b_c.astype(jnp.float32)
        col = idx * chunk + jnp.arange(chunk)
        valid = col[None, :] < Tk
        if causal:
            row = jnp.arange(Tq)
            valid = jnp.logical_and(
                valid, col[None, :] <= row[:, None] + (Tk - Tq))
        s = jnp.where(valid[None, None], s, _NEG_INF)
        p = jnp.exp(s - lse[..., None])
        dv_c = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
        dp = jnp.einsum("bhqd,bhkd->bhqk", dof, v_c.astype(jnp.float32))
        ds = p * (dp - delta[..., None]) * sm_scale
        dq_acc = dq_acc + jnp.einsum("bhqk,bhkd->bhqd", ds, kcf)
        dk_c = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
        db_c = jnp.sum(ds, axis=2) / sm_scale  # (B,H,chunk)
        return dq_acc, (dk_c, dv_c, db_c)

    idxs = jnp.arange(nchunks)
    xs = (jnp.moveaxis(kc, 2, 0), jnp.moveaxis(vc, 2, 0), bc, idxs) \
        if bias is not None else \
        (jnp.moveaxis(kc, 2, 0), jnp.moveaxis(vc, 2, 0), idxs)
    dq, (dk_s, dv_s, db_s) = jax.lax.scan(body, jnp.zeros_like(qf), xs)
    dk = jnp.moveaxis(dk_s, 0, 2).reshape(B, H, Tk + pad, D)[:, :, :Tk]
    dv = jnp.moveaxis(dv_s, 0, 2).reshape(B, H, Tk + pad, D)[:, :, :Tk]
    dbias = None
    if bias is not None:
        db = jnp.moveaxis(db_s, 0, 2).reshape(B, H, Tk + pad)[:, :, :Tk]
        dbias = db[:, :, None, :]
        if bias.shape[1] == 1:
            dbias = jnp.sum(dbias, axis=1, keepdims=True)
        dbias = dbias.astype(bias.dtype)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            dbias)


# ---------------------------------------------------------------------------
# custom vjp: pallas forward, XLA-recompute backward
# ---------------------------------------------------------------------------
def _use_pallas():
    # the TPU backend registers as 'tpu' (or 'axon' via the PJRT tunnel
    # plugin); anything else (cpu, gpu) takes the XLA paths
    return jax.default_backend() in ("tpu", "axon")


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash_core(q, k, v, bias, causal, sm_scale):
    out, _ = _flash_fwd(q, k, v, bias, causal, sm_scale)
    return out


def _flash_fwd(q, k, v, bias, causal, sm_scale):
    _record_flash_signature(q, k, v, bias, causal, sm_scale)
    if not _kv_fits_vmem(k):
        out, lse = _attention_scan_fwd(q, k, v, bias, causal, sm_scale)
    else:
        cfg = _tuned_config(q, k, v, bias, causal, sm_scale)
        if cfg.get("backend") == "pallas" and _use_pallas():
            out, lse = _flash_forward_pallas(
                q, k, v, bias, causal, sm_scale,
                int(cfg["block_q"]), int(cfg["block_k"]), interpret=False)
        else:
            # per-shape XLA choice (small shapes, or a tuned decision
            # that XLA's fused reference wins here), and every non-TPU
            # backend
            out = _attention_reference(q, k, v, bias, causal, sm_scale)
            lse = None
    return out, (q, k, v, bias, out, lse)


def _record_flash_signature(q, k, v, bias, causal, sm_scale):
    """Remember this dispatch's shape signature for tuning.warmup()'s
    AOT replay (deduplicated in the table; a fresh serving replica
    compiles these ahead of traffic)."""
    try:
        from .. import tuning

        tuning.record_signature("flash_attention", {
            "q_shape": list(q.shape), "k_shape": list(k.shape),
            "v_shape": list(v.shape),
            "bias_shape": None if bias is None else list(bias.shape),
            "bias_dtype": None if bias is None else str(bias.dtype),
            "dtype": str(q.dtype), "causal": bool(causal),
            "sm_scale": float(sm_scale)})
    except Exception:  # noqa: BLE001 — bookkeeping must not fail the op
        pass


_BWD_SCORE_BYTES = 256 * 1024 * 1024  # peak score-matrix budget in backward


def _flash_bwd(causal, sm_scale, res, do):
    q, k, v, bias, out, lse = res
    B, H, Tq, _ = q.shape
    Tk = k.shape[2]
    score_bytes = B * H * Tq * Tk * 4
    if not _kv_fits_vmem(k) or score_bytes > _BWD_SCORE_BYTES:
        # keep backward O(Tq * chunk): a forward that fit VMEM can still
        # have a score matrix far too big to materialize (e.g. T=8k)
        if lse is None:
            _, lse = _attention_scan_fwd(q, k, v, bias, causal, sm_scale)
        chunk = int(max(128, min(
            Tk, _BWD_SCORE_BYTES // max(1, B * H * Tq * 4))))
        return _bwd_chunked(q, k, v, bias, out, lse, do, causal, sm_scale,
                            chunk=chunk)
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf,
                   preferred_element_type=jnp.float32) * sm_scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        s = jnp.where(mask, s, _NEG_INF)
    if lse is not None:
        p = jnp.exp(s - lse[..., None])
    else:
        p = jax.nn.softmax(s, axis=-1)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
    dp = jnp.einsum("bhqd,bhkd->bhqk", dof, vf)
    delta = jnp.sum(dof * out.astype(jnp.float32), axis=-1, keepdims=True)
    ds = p * (dp - delta) * sm_scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, kf).astype(q.dtype)
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf).astype(k.dtype)
    dbias = None
    if bias is not None:
        db = ds / sm_scale
        # reduce over broadcast dims of the (B, H|1, 1, Tk) bias
        dbias = jnp.sum(db, axis=2, keepdims=True)
        if bias.shape[1] == 1:
            dbias = jnp.sum(dbias, axis=1, keepdims=True)
        dbias = dbias.astype(bias.dtype)
    return dq, dk, dv.astype(v.dtype), dbias


_flash_core.defvjp(_flash_fwd, _flash_bwd)


@register("flash_attention", aliases=("_contrib_flash_attention",))
def flash_attention(query, key, value, bias=None, causal=False,
                    sm_scale=None):
    """Fused scaled-dot-product attention. query/key/value: (B, H, T, D);
    bias: optional additive (B, H|1, 1, Tk) mask (use large negatives to
    mask). Returns (B, H, Tq, D).

    Inside ``parallel.sequence_scope(mesh, axis, schedule)`` this
    dispatches to a sequence-parallel schedule (ring KV rotation, or
    Ulysses head all-to-all when heads divide and there is no bias) —
    the hook that makes every attention user sequence-parallel without
    model changes."""
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(query.shape[-1]))
    from ..parallel.sequence import current_sequence_scope, ring_attention

    scope = current_sequence_scope()
    if scope is not None and query.shape[2] == key.shape[2]:
        # the scope covers sequence-sharded SELF-attention;
        # rectangular attention (cross-attention, Tq=1 decode steps)
        # falls through to the flash kernel untouched
        mesh, seq_axis, schedule = scope
        if jax.process_count() > 1:
            raise MXNetError(
                "sequence_scope's eager dispatch is single-process; on "
                "multi-host meshes call parallel.ring_attention inside "
                "your pjit/shard_map program instead")
        from ..parallel.sequence import ulysses_attention

        if (schedule == "ulysses" and bias is None
                and query.shape[1] % mesh.shape[seq_axis] == 0):
            out = ulysses_attention(query, key, value, mesh=mesh,
                                    seq_axis=seq_axis,
                                    causal=bool(causal),
                                    sm_scale=float(sm_scale))
        else:  # ring handles biases and any head count
            out = ring_attention(query, key, value, bias=bias, mesh=mesh,
                                 seq_axis=seq_axis, causal=bool(causal),
                                 sm_scale=float(sm_scale))
        # bring the mesh-sharded result back to a single device so it
        # composes with unsharded surrounding ops on the eager path
        # (device_put is traceable; under full-program jit it's just a
        # sharding constraint XLA folds away)
        out = jax.device_put(
            out, jax.sharding.SingleDeviceSharding(
                mesh.devices.flat[0]))
        return out
    return _flash_core(query, key, value, bias, bool(causal),
                       float(sm_scale))


@register("attention_padding_bias", differentiable=False)
def make_padding_bias(valid_length, max_len=None, dtype="float32"):
    """(B,) lengths → additive (B, 1, 1, T) bias: 0 for valid, -1e30 after.
    ``max_len`` (the key sequence length) is required."""
    if not max_len:
        raise ValueError("attention_padding_bias requires max_len= (the "
                         "key sequence length)")
    idx = jnp.arange(max_len)[None, :]
    mask = idx < valid_length.astype(jnp.int32)[:, None]
    bias = jnp.where(mask, 0.0, _NEG_INF).astype(jnp.dtype(dtype))
    return bias[:, None, None, :]
