"""Legacy output-layer loss ops + gradient-control ops
(ref: src/operator/regression_output{.cc,-inl.h}, src/operator/make_loss.cc,
src/operator/tensor/elemwise_unary_op_basic.cc — BlockGrad).

Like SoftmaxOutput, these ops' backward IGNORES the incoming cotangent and
emits the fused loss gradient — the executor's backward() seeds loss heads
with ones and these custom vjps produce the training signal, reproducing
the reference's "loss layer" semantics.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


def _regression_core(transform, grad_fn):
    def fwd(data, label, grad_scale):
        return transform(data), (data, label)

    def bwd(grad_scale, res, ct):
        data, label = res
        # the reference reshapes a same-size label onto data
        # (regression_output-inl.h) — a (B,) label against (B,1) data
        # must NOT broadcast to (B,B)
        lab = label.reshape(data.shape) if label.size == data.size \
            else label
        num_output = max(1, int(jnp.size(data)) // max(1, data.shape[0]))
        g = grad_fn(transform(data), lab) * (grad_scale / num_output)
        return (g.astype(data.dtype), jnp.zeros_like(label))

    core = jax.custom_vjp(
        lambda data, label, grad_scale: fwd(data, label, grad_scale)[0],
        nondiff_argnums=(2,),
    )
    core.defvjp(fwd, bwd)
    return core


_linear_core = _regression_core(lambda d: d, lambda o, l: o - l)
_mae_core = _regression_core(lambda d: d, lambda o, l: jnp.sign(o - l))
_logistic_core = _regression_core(jax.nn.sigmoid, lambda o, l: o - l)


@register("LinearRegressionOutput")
def linear_regression_output(data, label, grad_scale=1.0):
    """Identity forward; backward = (data - label) * scale / num_output
    (ref: regression_output-inl.h)."""
    return _linear_core(data, label, float(grad_scale))


@register("MAERegressionOutput")
def mae_regression_output(data, label, grad_scale=1.0):
    return _mae_core(data, label, float(grad_scale))


@register("LogisticRegressionOutput")
def logistic_regression_output(data, label, grad_scale=1.0):
    """Sigmoid forward; backward = (sigmoid(data) - label) * scale."""
    return _logistic_core(data, label, float(grad_scale))


def _make_loss_fwd(data, grad_scale, valid_thresh, normalization):
    return data, data


def _make_loss_bwd(grad_scale, valid_thresh, normalization, res, ct):
    scale = jnp.asarray(grad_scale, res.dtype)
    if normalization == "batch":
        scale = scale / res.shape[0]
    elif normalization == "valid":
        # divide by the count of elements above valid_thresh
        # (ref: make_loss.cc — MakeLossGradKernel with valid normalization)
        num_valid = jnp.maximum(
            jnp.sum(res > valid_thresh).astype(res.dtype), 1.0)
        scale = scale / num_valid
    return (jnp.broadcast_to(scale, res.shape).astype(res.dtype),)


_make_loss_core = jax.custom_vjp(
    lambda data, grad_scale, valid_thresh, normalization: data,
    nondiff_argnums=(1, 2, 3),
)
_make_loss_core.defvjp(_make_loss_fwd, _make_loss_bwd)


@register("MakeLoss", aliases=("make_loss",))
def make_loss(data, grad_scale=1.0, valid_thresh=0.0, normalization="null"):
    """Marks a symbol as a loss: forward passes through, backward emits
    d(sum(data))/d(data) * grad_scale (ref: src/operator/make_loss.cc)."""
    return _make_loss_core(data, float(grad_scale), float(valid_thresh),
                           str(normalization))


@register("BlockGrad", aliases=("stop_gradient",), differentiable=False)
def block_grad(data):
    """Gradient barrier (ref: elemwise_unary_op_basic.cc — BlockGrad)."""
    return jax.lax.stop_gradient(data)


@register("identity", aliases=("_copy",))
def identity(data):
    return data


import functools


@functools.lru_cache(maxsize=None)
def _svm_core(margin, reg_coef, use_linear):
    def fwd(data, label):
        return data, (data, label)

    def bwd(res, ct):
        del ct  # loss head: cotangent ignored, like SoftmaxOutput
        data, label = res
        n, k = data.shape
        y = label.astype(jnp.int32)
        x_y = jnp.take_along_axis(data, y[:, None], axis=1)  # (n, 1)
        viol = (x_y - data) < margin  # margin violated per class
        onehot = jax.nn.one_hot(y, k, dtype=data.dtype)
        viol = jnp.logical_and(viol, onehot == 0)
        if use_linear:  # L1-SVM: hinge
            g = viol.astype(data.dtype) * reg_coef
        else:  # L2-SVM: squared hinge (the reference default)
            g = jnp.where(viol, 2.0 * reg_coef * (margin - (x_y - data)),
                          0.0).astype(data.dtype)
        g = g - onehot * g.sum(axis=1, keepdims=True)
        return (g, jnp.zeros_like(label))

    core = jax.custom_vjp(lambda data, label: fwd(data, label)[0])
    core.defvjp(fwd, bwd)
    return core


@register("SVMOutput")
def svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
               use_linear=False):
    """Multiclass SVM loss head (ref: src/operator/svm_output-inl.h):
    identity forward; backward emits the (squared) hinge gradient —
    for each class j != y with x_y - x_j < margin, push x_j down and
    x_y up. use_linear selects L1-SVM; default is L2 (squared hinge)."""
    return _svm_core(float(margin), float(regularization_coefficient),
                     bool(use_linear))(data, label)
