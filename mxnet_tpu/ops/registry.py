"""Operator registry — one registry serves both execution modes.

Mirrors the reference's nnvm op registry role (ref: src/operator/** —
NNVM_REGISTER_OP; invariant: imperative Invoke and symbolic GraphExecutor
dispatch the same registered ops). Here each op is a *pure JAX function*
``fn(*jax_arrays, **static_params) -> array | tuple``:

- imperative mode calls it eagerly (XLA async dispatch plays ThreadedEngine);
- autograd records its ``jax.vjp`` closure (plays FGradient);
- hybridize/Symbol trace through it into one XLA program (plays CachedOp /
  GraphExecutor).
"""
from __future__ import annotations

import functools
import inspect

import jax


def _random_mod():
    from .. import random as _random
    return _random


def _config():
    from .. import config
    return config


_launches = None  # profiler.record_launch, bound on first dispatch


def _count_launch():
    # thread-safe: op dispatch also happens on prefetcher/deferred-read
    # threads, so the increment goes through the profiler's lock
    global _launches
    if _launches is None:
        from .. import profiler
        _launches = profiler.record_launch
    _launches()

__all__ = ["Op", "register", "get_op", "list_ops", "apply_op"]

_OPS: dict[str, "Op"] = {}
_ALIASES: dict[str, str] = {}


class Op:
    __slots__ = ("name", "fn", "differentiable", "num_outputs", "wrt")

    def __init__(self, name, fn, differentiable=True, num_outputs=1, wrt=None):
        self.name = name
        self.fn = fn
        self.differentiable = differentiable
        self.num_outputs = num_outputs
        # indices of array inputs that can carry gradient (None = all)
        self.wrt = wrt

    def __repr__(self):
        return "Op(%s)" % self.name


def register(name, aliases=(), differentiable=True, num_outputs=1, wrt=None):
    """Decorator: register ``fn`` under a reference op name."""

    def deco(fn):
        op = Op(name, fn, differentiable=differentiable,
                num_outputs=num_outputs, wrt=wrt)
        _OPS[name] = op
        for a in aliases:
            _ALIASES[a] = name
        fn._mxt_op = op
        return fn

    return deco


def get_op(name) -> Op:
    if name in _OPS:
        return _OPS[name]
    if name in _ALIASES:
        return _OPS[_ALIASES[name]]
    raise KeyError("operator %r is not registered" % (name,))


def list_ops():
    return sorted(_OPS)


def _normalize_kwargs(kwargs):
    out = {}
    for k, v in kwargs.items():
        if isinstance(v, list):
            v = tuple(v)
        out[k] = v
    return out


@functools.lru_cache(maxsize=None)
def fn_params(fn):
    """Accepted parameter names of an op fn (None if uninspectable).
    Keyed on the fn object so re-registering an op name can't serve
    stale signatures."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return None
    return frozenset(sig.parameters)


def _accepts_train_mode(op):
    return "train_mode" in (fn_params(op.fn) or ())


def apply_op(op, *inputs, out=None, **kwargs):
    """Invoke a registered op on NDArrays (imperative path).

    Plays Imperative::Invoke (ref: src/imperative/imperative.cc): unwrap to
    jax.Array, run the pure fn (recording the vjp closure when autograd is
    on), wrap outputs. Returns NDArray or tuple of NDArray.
    """
    from .. import autograd as ag
    from ..ndarray.ndarray import NDArray

    if isinstance(op, str):
        op = get_op(op)
    kwargs = _normalize_kwargs(kwargs)
    # ops that behave differently in training (Dropout/BatchNorm/RNN
    # dropout) read the imperative context like the reference's
    # ctx.is_train (imperative.cc) unless the caller pins train_mode
    if "train_mode" not in kwargs and _accepts_train_mode(op):
        kwargs["train_mode"] = ag.is_training()
    raw = [x.data if isinstance(x, NDArray) else x for x in inputs]
    fn = functools.partial(op.fn, **kwargs) if kwargs else op.fn
    _count_launch()  # one imperative invoke = one dispatched execution

    parents = None
    if ag.is_recording() and op.differentiable:
        parents = [
            getattr(x, "_ag_node", None) if isinstance(x, NDArray) else None
            for x in inputs
        ]
        if not any(parents):
            parents = None

    if parents is not None:
        # capture PRNG keys drawn during the forward so a create_graph
        # replay (autograd._grad_create_graph) reproduces stochastic ops
        # (dropout) bit-for-bit
        drawn_keys = []
        with _random_mod().capture_keys(drawn_keys):
            out_raw, vjp_fn = jax.vjp(fn, *raw)
        if _config().get("MXT_AG_LEAN_TAPE"):
            replay_fn = None  # create_graph raises; peak memory shrinks
            raw_kept = None
        elif drawn_keys:
            def replay_fn(*r, _fn=fn, _keys=drawn_keys):
                with _random_mod().replay_keys(_keys):
                    return _fn(*r)
            raw_kept = raw
        else:
            replay_fn = fn
            raw_kept = raw
    else:
        out_raw = fn(*raw)

    multi = isinstance(out_raw, tuple)
    outs_raw = list(out_raw) if multi else [out_raw]

    node = None
    if parents is not None:
        if multi:
            wrapped_vjp = vjp_fn
        else:
            def wrapped_vjp(cts, _vjp=vjp_fn):
                return _vjp(cts[0])

        node = ag.AGNode(
            wrapped_vjp, parents, [(o.shape, o.dtype) for o in outs_raw],
            name=op.name, fwd_fn=replay_fn, in_vals=raw_kept,
        )

    results = []
    for i, o in enumerate(outs_raw):
        nd = NDArray(o)
        if node is not None:
            nd._ag_node = (node, i)
        results.append(nd)

    if out is not None:
        if multi:
            raise ValueError("out= not supported for multi-output op %s" % op.name)
        out._set_data(results[0].data)
        # rebind history too: stale nodes would feed backward from the
        # overwritten computation
        out._ag_node = (node, 0) if node is not None else None
        return out
    return tuple(results) if multi else results[0]
