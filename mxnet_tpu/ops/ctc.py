"""CTC loss (ref: src/operator/nn/ctc_loss.cc — the reference wraps
warp-ctc/cuDNN; here the standard log-semiring alpha recursion runs as a
`lax.scan` over time — one fused XLA program, static shapes (padded
label path, masked lengths), gradients via autodiff of the scan, which
XLA rematerializes efficiently on TPU."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

__all__ = ["ctc_loss"]

_NEG_INF = -1e30


def _ctc_alpha_scan(log_probs, ext_labels, ext_mask, data_mask):
    """log-alpha recursion. log_probs: (T, N, C); ext_labels: (N, S) with
    blanks interleaved; ext_mask: (N, S) valid-slot mask; data_mask:
    (T, N). Returns final alpha (N, S)."""
    N, S = ext_labels.shape

    lp_ext_all = jnp.take_along_axis(
        log_probs,
        jnp.broadcast_to(ext_labels[None], (log_probs.shape[0], N, S)),
        axis=2)                                        # (T, N, S)

    # skip-connection allowed where label differs from two slots back
    # (and the slot is a non-blank, i.e. odd position)
    same_as_two_back = jnp.concatenate(
        [jnp.ones((N, 2), dtype=bool),
         ext_labels[:, 2:] == ext_labels[:, :-2]], axis=1)
    can_skip = (~same_as_two_back) & (jnp.arange(S)[None, :] % 2 == 1)

    alpha0 = jnp.full((N, S), _NEG_INF)
    alpha0 = alpha0.at[:, 0].set(lp_ext_all[0, :, 0])
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(ext_mask[:, 1], lp_ext_all[0, :, 1], _NEG_INF))

    def shift(a, k):
        return jnp.concatenate(
            [jnp.full((N, k), _NEG_INF), a[:, :-k]], axis=1)

    def step(alpha, inputs):
        lp_t, m_t = inputs                      # (N, S), (N,)
        stay = alpha
        diag = shift(alpha, 1)
        skip = jnp.where(can_skip, shift(alpha, 2), _NEG_INF)
        new = jnp.logaddexp(jnp.logaddexp(stay, diag), skip) + lp_t
        new = jnp.where(ext_mask, new, _NEG_INF)
        # past the sample's length the alpha is carried through unchanged
        new = jnp.where(m_t[:, None], new, alpha)
        return new, None

    alpha, _ = jax.lax.scan(step, alpha0,
                            (lp_ext_all[1:], data_mask[1:]))
    return alpha


@register("CTCLoss", aliases=("ctc_loss", "_contrib_CTCLoss",
                              "_contrib_ctc_loss"), wrt=(0,))
def ctc_loss(data, label, data_lengths=None, label_lengths=None,
             use_data_lengths=False, use_label_lengths=False,
             blank_label="first"):
    """ref: ctc_loss.cc — CTCLossOp. data: (T, N, C) unnormalized
    activations (softmax applied internally, like the reference); label:
    (N, L) padded class indices. Without explicit label_lengths, padding
    uses 0 for blank_label='first' (classes are 1-based) and -1
    otherwise. Returns per-sample negative log likelihood (N,)."""
    T, N, C = data.shape
    L = label.shape[1]
    log_probs = jax.nn.log_softmax(data.astype(jnp.float32), axis=-1)

    label = label.astype(jnp.int32)
    if blank_label == "first":
        blank = 0
        pad = 0
    else:
        blank = C - 1
        pad = -1

    if use_label_lengths and label_lengths is not None:
        lab_len = label_lengths.astype(jnp.int32)
    else:
        lab_len = jnp.sum((label != pad).astype(jnp.int32), axis=1)
    if use_data_lengths and data_lengths is not None:
        dat_len = data_lengths.astype(jnp.int32)
    else:
        dat_len = jnp.full((N,), T, dtype=jnp.int32)

    # interleave blanks: S = 2L+1 slots [b, l1, b, l2, ..., b]
    S = 2 * L + 1
    pos = jnp.arange(S)
    lab_idx = jnp.clip((pos - 1) // 2, 0, L - 1)
    gathered = jnp.take_along_axis(
        label, jnp.broadcast_to(lab_idx, (N, S)), axis=1)
    ext_labels = jnp.where(pos[None, :] % 2 == 1, gathered, blank)
    ext_labels = jnp.clip(ext_labels, 0, C - 1)
    ext_mask = pos[None, :] < (2 * lab_len[:, None] + 1)

    data_mask = jnp.arange(T)[:, None] < dat_len[None, :]  # (T, N)

    alpha = _ctc_alpha_scan(log_probs, ext_labels, ext_mask, data_mask)

    last = 2 * lab_len            # blank after the last label
    a_last = jnp.take_along_axis(alpha, last[:, None], axis=1)[:, 0]
    a_prev = jnp.take_along_axis(
        alpha, jnp.maximum(last - 1, 0)[:, None], axis=1)[:, 0]
    a_prev = jnp.where(lab_len > 0, a_prev, _NEG_INF)
    loss = -jnp.logaddexp(a_last, a_prev)
    return loss.astype(data.dtype)
