"""Reduction ops (ref: src/operator/tensor/broadcast_reduce_op_value.cc).

Reference semantics: ``axis`` may be int/tuple/None, ``keepdims`` bool,
``exclude=True`` reduces over every axis *not* listed.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _norm_axis(axis, ndim, exclude):
    if axis is None:
        ax = None
    else:
        if isinstance(axis, int):
            axis = (axis,)
        ax = tuple(a % ndim for a in axis)
    if exclude:
        if ax is None:
            ax = ()
        ax = tuple(i for i in range(ndim) if i not in ax)
    return ax


def _mk_reduce(jfn):
    def fn(a, axis=None, keepdims=False, exclude=False):
        ax = _norm_axis(axis, a.ndim, exclude)
        return jfn(a, axis=ax, keepdims=bool(keepdims))

    return fn


register("sum", aliases=("sum_axis",))(_mk_reduce(jnp.sum))
register("mean")(_mk_reduce(jnp.mean))
register("prod")(_mk_reduce(jnp.prod))
register("nansum")(_mk_reduce(jnp.nansum))
register("nanprod")(_mk_reduce(jnp.nanprod))
register("max", aliases=("max_axis",))(_mk_reduce(jnp.max))
register("min", aliases=("min_axis",))(_mk_reduce(jnp.min))


@register("argmax", differentiable=False)
def argmax(a, axis=None, keepdims=False):
    out = jnp.argmax(a, axis=axis, keepdims=bool(keepdims))
    return out.astype(jnp.float32)  # reference returns real dtype indices


@register("argmin", differentiable=False)
def argmin(a, axis=None, keepdims=False):
    out = jnp.argmin(a, axis=axis, keepdims=bool(keepdims))
    return out.astype(jnp.float32)


@register("argmax_channel", differentiable=False)
def argmax_channel(a):
    return jnp.argmax(a, axis=1).astype(jnp.float32)


@register("norm")
def norm(a, ord=2, axis=None, keepdims=False):
    ax = axis if axis is None or isinstance(axis, int) else tuple(axis)
    if ord == 1:
        return jnp.sum(jnp.abs(a), axis=ax, keepdims=bool(keepdims))
    return jnp.sqrt(jnp.sum(jnp.square(a), axis=ax, keepdims=bool(keepdims)))


@register("logsumexp")
def logsumexp(a, axis=None, keepdims=False):
    import jax.scipy.special as jsp

    return jsp.logsumexp(a, axis=axis, keepdims=bool(keepdims))
