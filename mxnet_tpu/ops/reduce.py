"""Reduction ops (ref: src/operator/tensor/broadcast_reduce_op_value.cc).

Reference semantics: ``axis`` may be int/tuple/None, ``keepdims`` bool,
``exclude=True`` reduces over every axis *not* listed.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _norm_axis(axis, ndim, exclude):
    if axis is None:
        ax = None
    else:
        if isinstance(axis, int):
            axis = (axis,)
        ax = tuple(a % ndim for a in axis)
    if exclude:
        if ax is None:
            ax = ()
        ax = tuple(i for i in range(ndim) if i not in ax)
    return ax


def _mk_reduce(jfn):
    def fn(a, axis=None, keepdims=False, exclude=False):
        ax = _norm_axis(axis, a.ndim, exclude)
        return jfn(a, axis=ax, keepdims=bool(keepdims))

    return fn


register("sum", aliases=("sum_axis",))(_mk_reduce(jnp.sum))
register("mean")(_mk_reduce(jnp.mean))
register("prod")(_mk_reduce(jnp.prod))
register("nansum")(_mk_reduce(jnp.nansum))
register("nanprod")(_mk_reduce(jnp.nanprod))
register("max", aliases=("max_axis",))(_mk_reduce(jnp.max))
register("min", aliases=("min_axis",))(_mk_reduce(jnp.min))


@register("argmax", differentiable=False)
def argmax(a, axis=None, keepdims=False, dtype=None):
    out = jnp.argmax(a, axis=axis, keepdims=bool(keepdims))
    # reference default returns real-dtype indices; f32 is exact only to
    # 2^24, so large-tensor users pass dtype='int64' (the same escape
    # hatch the reference grew for its large-tensor support)
    return out.astype(jnp.dtype(dtype) if dtype else jnp.float32)


@register("argmin", differentiable=False)
def argmin(a, axis=None, keepdims=False, dtype=None):
    out = jnp.argmin(a, axis=axis, keepdims=bool(keepdims))
    return out.astype(jnp.dtype(dtype) if dtype else jnp.float32)


@register("argmax_channel", differentiable=False)
def argmax_channel(a):
    return jnp.argmax(a, axis=1).astype(jnp.float32)


@register("norm")
def norm(a, ord=2, axis=None, keepdims=False):
    ax = axis if axis is None or isinstance(axis, int) else tuple(axis)
    if ord == 1:
        return jnp.sum(jnp.abs(a), axis=ax, keepdims=bool(keepdims))
    return jnp.sqrt(jnp.sum(jnp.square(a), axis=ax, keepdims=bool(keepdims)))


@register("logsumexp")
def logsumexp(a, axis=None, keepdims=False):
    import jax.scipy.special as jsp

    return jsp.logsumexp(a, axis=axis, keepdims=bool(keepdims))


@register("cumsum")
def cumsum(a, axis=None, dtype=None):
    """ref: src/operator/numpy/np_cumsum.cc."""
    from ..base import get_dtype

    dt = get_dtype(dtype) if dtype else None
    return jnp.cumsum(a, axis=axis, dtype=dt)


@register("cumprod")
def cumprod(a, axis=None, dtype=None):
    from ..base import get_dtype

    dt = get_dtype(dtype) if dtype else None
    return jnp.cumprod(a, axis=axis, dtype=dt)


@register("moments", num_outputs=2)
def moments(data, axes=None, keepdims=False):
    """ref: src/operator/nn/moments.cc — (mean, var) in one op."""
    ax = tuple(axes) if axes is not None else None
    mean = jnp.mean(data, axis=ax, keepdims=True)
    var = jnp.mean(jnp.square(data - mean), axis=ax, keepdims=keepdims)
    if not keepdims:
        mean = mean.reshape(var.shape) if var.ndim else mean.reshape(())
    return mean, var
