"""Fused optimizer update ops.

The reference runs optimizer updates as engine ops so they stay async and
fused (ref: src/operator/optimizer_op.cc — sgd_update, sgd_mom_update,
adam_update, rmsprop_update, ftrl_update, signsgd_update, signum_update,
nag_mom_update, lamb_update_phase1/2, and the mp_* multi-precision
variants). Here each is ONE jitted XLA program (all elementwise math fuses
into a single kernel on TPU), written functionally: the op returns the
updated buffers and the ``nd``-level wrapper writes them back in place,
preserving the reference's mutate-in-place calling convention.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .registry import register

__all__ = ["install_inplace_wrappers"]


def _prep(grad, wd, weight, rescale_grad, clip_gradient):
    g = grad.astype(jnp.float32) if weight.dtype == jnp.float32 else grad
    g = g * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g + wd * weight


@register("sgd_update", differentiable=False)
@functools.partial(jax.jit, static_argnames=("clip_gradient", "lazy_update"))
def sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
               clip_gradient=None, lazy_update=True):
    del lazy_update  # dense path; row_sparse handled in sparse module
    g = _prep(grad, wd, weight, rescale_grad, clip_gradient)
    return (weight - lr * g).astype(weight.dtype)


@register("sgd_mom_update", differentiable=False, num_outputs=2)
@functools.partial(jax.jit, static_argnames=("clip_gradient", "lazy_update"))
def sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=None, lazy_update=True):
    del lazy_update
    g = _prep(grad, wd, weight, rescale_grad, clip_gradient)
    mom_new = momentum * mom - lr * g
    return (weight + mom_new).astype(weight.dtype), mom_new


@register("mp_sgd_update", differentiable=False, num_outputs=2)
@functools.partial(jax.jit, static_argnames=("clip_gradient", "lazy_update"))
def mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                  clip_gradient=None, lazy_update=True):
    del lazy_update
    g = _prep(grad.astype(jnp.float32), wd, weight32, rescale_grad,
              clip_gradient)
    w32 = weight32 - lr * g
    return w32.astype(weight.dtype), w32


@register("mp_sgd_mom_update", differentiable=False, num_outputs=3)
@functools.partial(jax.jit, static_argnames=("clip_gradient", "lazy_update"))
def mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=None,
                      lazy_update=True):
    del lazy_update
    g = _prep(grad.astype(jnp.float32), wd, weight32, rescale_grad,
              clip_gradient)
    mom_new = momentum * mom - lr * g
    w32 = weight32 + mom_new
    return w32.astype(weight.dtype), mom_new, w32


@register("nag_mom_update", differentiable=False, num_outputs=2)
@functools.partial(jax.jit, static_argnames=("clip_gradient",))
def nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=None):
    g = _prep(grad, wd, weight, rescale_grad, clip_gradient)
    mom_new = momentum * mom + g
    return (weight - lr * (g + momentum * mom_new)).astype(weight.dtype), \
        mom_new


@register("mp_nag_mom_update", differentiable=False, num_outputs=3)
@functools.partial(jax.jit, static_argnames=("clip_gradient",))
def mp_nag_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=None):
    g = _prep(grad.astype(jnp.float32), wd, weight32, rescale_grad,
              clip_gradient)
    mom_new = momentum * mom + g
    w32 = weight32 - lr * (g + momentum * mom_new)
    return w32.astype(weight.dtype), mom_new, w32


@register("adam_update", differentiable=False, num_outputs=3)
@functools.partial(jax.jit, static_argnames=("clip_gradient", "lazy_update"))
def adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=None,
                lazy_update=True):
    del lazy_update
    g = _prep(grad, wd, weight, rescale_grad, clip_gradient)
    mean_new = beta1 * mean + (1.0 - beta1) * g
    var_new = beta2 * var + (1.0 - beta2) * g * g
    w = weight - lr * mean_new / (jnp.sqrt(var_new) + epsilon)
    return w.astype(weight.dtype), mean_new, var_new


@register("adamw_update", differentiable=False, num_outputs=3)
@functools.partial(jax.jit, static_argnames=("clip_gradient",))
def adamw_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, wd=0.0, eta=1.0, rescale_grad=1.0,
                 clip_gradient=None):
    """Decoupled weight decay (ref: src/operator/contrib/adamw.cc)."""
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    mean_new = beta1 * mean + (1.0 - beta1) * g
    var_new = beta2 * var + (1.0 - beta2) * g * g
    w = weight - eta * (lr * mean_new / (jnp.sqrt(var_new) + epsilon)
                        + wd * weight)
    return w.astype(weight.dtype), mean_new, var_new


@register("rmsprop_update", differentiable=False, num_outputs=2)
@functools.partial(jax.jit, static_argnames=("clip_gradient", "clip_weights"))
def rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.9, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_gradient=None,
                   clip_weights=None):
    g = _prep(grad, wd, weight, rescale_grad, clip_gradient)
    n_new = (1.0 - gamma1) * g * g + gamma1 * n
    w = weight - lr * g / jnp.sqrt(n_new + epsilon)
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w.astype(weight.dtype), n_new


@register("rmspropalex_update", differentiable=False, num_outputs=4)
@functools.partial(jax.jit, static_argnames=("clip_gradient", "clip_weights"))
def rmspropalex_update(weight, grad, n, g_state, delta, lr=0.001, gamma1=0.9,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=None, clip_weights=None):
    """Centered RMSProp (Graves'13 variant; ref: rmspropalex_update)."""
    g = _prep(grad, wd, weight, rescale_grad, clip_gradient)
    n_new = (1.0 - gamma1) * g * g + gamma1 * n
    g_new = (1.0 - gamma1) * g + gamma1 * g_state
    delta_new = gamma2 * delta - lr * g / jnp.sqrt(n_new - g_new * g_new
                                                   + epsilon)
    w = weight + delta_new
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w.astype(weight.dtype), n_new, g_new, delta_new


@register("ftrl_update", differentiable=False, num_outputs=3)
@functools.partial(jax.jit, static_argnames=("clip_gradient",))
def ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=None):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    n_new = n + g * g
    z_new = z + g - (jnp.sqrt(n_new) - jnp.sqrt(n)) / lr * weight
    w = (jnp.sign(z_new) * lamda1 - z_new) / \
        ((beta + jnp.sqrt(n_new)) / lr + wd) * (jnp.abs(z_new) > lamda1)
    return w.astype(weight.dtype), z_new, n_new


@register("signsgd_update", differentiable=False)
@functools.partial(jax.jit, static_argnames=("clip_gradient",))
def signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=None):
    g = _prep(grad, wd, weight, rescale_grad, clip_gradient)
    return (weight - lr * jnp.sign(g)).astype(weight.dtype)


@register("signum_update", differentiable=False, num_outputs=2)
@functools.partial(jax.jit, static_argnames=("clip_gradient",))
def signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=None, wd_lh=0.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    mom_new = momentum * mom - (1.0 - momentum) * (g + wd * weight)
    w = (1.0 - lr * wd_lh) * weight + lr * jnp.sign(mom_new)
    return w.astype(weight.dtype), mom_new


@register("lamb_update_phase1", differentiable=False, num_outputs=3)
@functools.partial(jax.jit, static_argnames=("clip_gradient", "bias_correction"))
def lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                       epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                       rescale_grad=1.0, clip_gradient=None):
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    mean_new = beta1 * mean + (1.0 - beta1) * g
    var_new = beta2 * var + (1.0 - beta2) * g * g
    if bias_correction:
        mean_hat = mean_new / (1.0 - beta1 ** t)
        var_hat = var_new / (1.0 - beta2 ** t)
    else:
        mean_hat, var_hat = mean_new, var_new
    update = mean_hat / (jnp.sqrt(var_hat) + epsilon) + wd * weight
    return update, mean_new, var_new


@register("lamb_update_phase2", differentiable=False)
@jax.jit
def lamb_update_phase2(weight, g_update, r1, r2, lr=0.001,
                       lower_bound=-1.0, upper_bound=-1.0):
    r1c = jnp.where(lower_bound >= 0, jnp.maximum(r1, lower_bound), r1)
    r1c = jnp.where(upper_bound >= 0, jnp.minimum(r1c, upper_bound), r1c)
    ratio = jnp.where(jnp.logical_and(r1c > 0, r2 > 0), r1c / r2, 1.0)
    return (weight - lr * ratio * g_update).astype(weight.dtype)


# --------------------------------------------------------------------------
# in-place calling convention at the nd.* level:
# nd.sgd_mom_update(w, g, mom, lr=...) updates w and mom in place, returns w
# (the reference's out=weight idiom). Buffer order = op input order.
# --------------------------------------------------------------------------
_INPLACE = {
    # op name -> number of leading NDArray args that receive updated buffers
    "sgd_update": 1,
    "sgd_mom_update": 2,
    "mp_sgd_update": None,  # special: (weight, grad, weight32)
    "mp_sgd_mom_update": None,
    "nag_mom_update": 2,
    "mp_nag_mom_update": None,
    "adam_update": 3,
    "adamw_update": 3,
    "rmsprop_update": 2,
    "rmspropalex_update": 4,
    "ftrl_update": 3,
    "signsgd_update": 1,
    "signum_update": 2,
}
# for mp_* ops the grad input sits between the mutated buffers
_MP_TARGETS = {
    "mp_sgd_update": (0, 2),
    "mp_sgd_mom_update": (0, 2, 3),
    "mp_nag_mom_update": (0, 2, 3),
}


def install_inplace_wrappers(mod):
    """Override the generated nd.* functions for optimizer ops with
    mutate-in-place wrappers (called from mxnet_tpu/ndarray/__init__.py)."""
    from .registry import apply_op

    def make(name, n_targets):
        def wrapped(*args, out=None, **kwargs):
            res = apply_op(name, *args, **kwargs)
            if not isinstance(res, tuple):
                res = (res,)
            if n_targets is None:
                targets = [args[i] for i in _MP_TARGETS[name]]
            else:
                # mutated buffers are args[0] (weight), then the state
                # buffers which follow grad: args[2:2+n-1]
                targets = [args[0]] + list(args[2: 2 + n_targets - 1])
            for t, r in zip(targets, res):
                t._set_data(r.data)
            if out is not None and out is not args[0]:
                out._set_data(res[0].data)
                return out
            return args[0]

        wrapped.__name__ = name
        return wrapped

    for name, n in _INPLACE.items():
        setattr(mod, name, make(name, n))
