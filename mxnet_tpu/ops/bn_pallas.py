"""Pallas fused BatchNorm backward (ref: src/operator/nn/batch_norm.cu —
the reference's hand-fused CUDA BN backward; PERF.md round-3 analysis:
ResNet-50's backward is HBM-bandwidth-bound and the BN backward's
reductions are the fusible traffic).

Shape model: activations flattened to (M, C) with channel last (the NHWC
fast path — lane dimension = channels). Two passes, each reading x and
dy exactly once:

  pass 1 (reduce): db = Σ dy,  dg = Σ dy·x̂   — one joint read
  pass 2 (dx):     dx = g·inv · (dy − db/n − x̂·dg/n)

x̂ is recomputed from (x, mean, inv) in both passes — no f32 activation
residual is ever materialized (same policy as the XLA custom-VJP path in
nn._bn_core_bwd). Cross-block accumulation exploits the TPU grid's
sequential iteration: the (1, C) accumulator block maps to the same
tile every step, zeroed at step 0.

Gated by ``MXT_BN_PALLAS=1`` (default off until chip-measured — round-2
lesson: interpret-mode-green kernels can still fail Mosaic lowering, so
the TPU lane carries a hardware parity test).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:  # pallas import kept lazy-safe for exotic builds
    from jax.experimental import pallas as pl
    _HAVE_PALLAS = True
except Exception:  # noqa: BLE001
    _HAVE_PALLAS = False


def _block_rows(c, per_buf_bytes=1 << 21):
    """Rows per block so one f32 (BM, C) buffer stays ≤ per_buf_bytes."""
    bm = per_buf_bytes // (4 * max(c, 1))
    bm = max(8, min(1024, bm))
    return (bm // 8) * 8  # sublane multiple


def _reduce_kernel(m_true, x_ref, dy_ref, mean_ref, inv_ref,
                   db_ref, dg_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        db_ref[...] = jnp.zeros_like(db_ref)
        dg_ref[...] = jnp.zeros_like(dg_ref)

    bm = x_ref.shape[0]
    row = i * bm + jax.lax.broadcasted_iota(jnp.int32, (bm, 1), 0)
    mask = row < m_true
    # select-to-zero BOTH factors: an out-of-bounds row's padding is
    # unspecified — NaN·0 (a multiply mask) would still poison the sum
    dy = jnp.where(mask, dy_ref[...].astype(jnp.float32), 0.0)
    xhat = jnp.where(
        mask,
        (x_ref[...].astype(jnp.float32) - mean_ref[...]) * inv_ref[...],
        0.0)
    db_ref[...] += jnp.sum(dy, axis=0, keepdims=True)
    dg_ref[...] += jnp.sum(dy * xhat, axis=0, keepdims=True)


def _dx_kernel(n_scale, x_ref, dy_ref, mean_ref, inv_ref, g_ref,
               db_ref, dg_ref, dx_ref):
    dy = dy_ref[...].astype(jnp.float32)
    xhat = (x_ref[...].astype(jnp.float32) - mean_ref[...]) * inv_ref[...]
    dx = (g_ref[...] * inv_ref[...]) * (
        dy - db_ref[...] * n_scale - xhat * (dg_ref[...] * n_scale))
    dx_ref[...] = dx.astype(dx_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret", "block_rows"))
def bn_bwd_pallas(x2d, dy2d, mean, inv, g, interpret=False,
                  block_rows=None):
    """Fused BN backward on (M, C) channel-last activations.

    ``block_rows`` overrides the VMEM-budget heuristic with a tuned
    value (tuning/autotune.py — must be a positive multiple of 8; the
    kernel pads and row-masks the last block, so any legal value works
    for any M). Returns (dx (M, C) in x's dtype, dg (C,) f32,
    db (C,) f32).
    """
    m, c = x2d.shape
    bm = int(block_rows) if block_rows else _block_rows(c)
    if bm < 8 or bm % 8:
        raise ValueError("block_rows must be a positive multiple of 8 "
                         "(TPU sublane), got %d" % bm)
    grid = ((m + bm - 1) // bm,)
    mean_r = mean.reshape(1, c).astype(jnp.float32)
    inv_r = inv.reshape(1, c).astype(jnp.float32)
    g_r = g.reshape(1, c).astype(jnp.float32)

    row_spec = pl.BlockSpec((bm, c), lambda i: (i, 0))
    chan_spec = pl.BlockSpec((1, c), lambda i: (0, 0))

    db, dg = pl.pallas_call(
        functools.partial(_reduce_kernel, m),
        grid=grid,
        in_specs=[row_spec, row_spec, chan_spec, chan_spec],
        out_specs=[chan_spec, chan_spec],
        out_shape=[jax.ShapeDtypeStruct((1, c), jnp.float32),
                   jax.ShapeDtypeStruct((1, c), jnp.float32)],
        interpret=interpret,
    )(x2d, dy2d, mean_r, inv_r)

    n_scale = 1.0 / float(m)
    dx = pl.pallas_call(
        functools.partial(_dx_kernel, n_scale),
        grid=grid,
        in_specs=[row_spec, row_spec, chan_spec, chan_spec, chan_spec,
                  chan_spec, chan_spec],
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct((m, c), x2d.dtype),
        interpret=interpret,
    )(x2d, dy2d, mean_r, inv_r, g_r, db, dg)
    return dx, dg.reshape(c), db.reshape(c)


def available():
    return _HAVE_PALLAS


def enabled():
    from .. import config
    if not (_HAVE_PALLAS and config.get("MXT_BN_PALLAS")):
        return False
    # compiled Mosaic path needs a real TPU; CPU tests drive the kernel
    # directly with interpret=True instead
    return jax.default_backend() in ("tpu", "axon")


def candidate():
    """Cheap gate: could the compiled Mosaic path run at all here?
    (Keeps the XLA backward from paying reshape/choice work on CPU.)"""
    return _HAVE_PALLAS and jax.default_backend() in ("tpu", "axon")


def choose(m, c, dtype, arrays=None):
    """Per-shape routing decision for the channel-last BN backward —
    the per-call replacement for the global ``MXT_BN_PALLAS`` switch.

    Returns ``(use_pallas, block_rows)``. An explicit ``MXT_BN_PALLAS``
    (env or set_default) keeps its global meaning for A/B sweeps;
    otherwise the tuning table answers per shape bucket (heuristic
    default: XLA — the fused kernel stays opt-in until a measured entry
    says it wins here). ``arrays`` (concrete (x2d, dy2d, mean, inv, g))
    lets an eager backward feed the autotuner's timed path on device.
    """
    from .. import config

    if not _HAVE_PALLAS or jax.default_backend() not in ("tpu", "axon"):
        return False, None
    if config.is_set("MXT_BN_PALLAS") \
            or str(config.get("MXT_TUNE_MODE")).lower() == "off":
        return bool(config.get("MXT_BN_PALLAS")), None
    from .. import tuning

    ent = tuning.resolve_bn(m, c, str(dtype), arrays=arrays)
    if ent.get("backend") == "pallas":
        return True, int(ent.get("block_rows") or 0) or None
    return False, None
