"""Elementwise binary/unary/scalar ops.

Covers the reference tensor-op families (ref: src/operator/tensor/
elemwise_binary_broadcast_op*.cc, elemwise_unary_op*.cc,
elemwise_binary_scalar_op*.cc, src/operator/mshadow_op.h scalar functor zoo).
Names and semantics follow the reference: comparisons/logicals return the
input dtype (1.0/0.0), not bool; broadcast_* ops broadcast, elemwise_* require
equal shapes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

_jsp = jax.scipy.special


# --------------------------------------------------------------------------
# broadcast binary
# --------------------------------------------------------------------------
_BINARY = {
    "broadcast_add": jnp.add,
    "broadcast_sub": jnp.subtract,
    "broadcast_mul": jnp.multiply,
    "broadcast_div": jnp.divide,
    "broadcast_mod": jnp.mod,
    "broadcast_power": jnp.power,
    "broadcast_maximum": jnp.maximum,
    "broadcast_minimum": jnp.minimum,
    "broadcast_hypot": jnp.hypot,
    "arctan2": jnp.arctan2,
}
_BINARY_ALIASES = {
    "broadcast_add": ("broadcast_plus",),
    "broadcast_sub": ("broadcast_minus",),
    # the reference's elemwise `mod` shares the broadcast kernel on XLA
    # (`_mod` is separately registered below)
    "broadcast_mod": ("mod",),
}

for _name, _jfn in _BINARY.items():

    def _mk(jfn):
        def fn(a, b):
            return jfn(a, b)

        return fn

    register(_name, aliases=_BINARY_ALIASES.get(_name, ()))(_mk(_jfn))

_COMPARE = {
    "broadcast_equal": jnp.equal,
    "broadcast_not_equal": jnp.not_equal,
    "broadcast_greater": jnp.greater,
    "broadcast_greater_equal": jnp.greater_equal,
    "broadcast_lesser": jnp.less,
    "broadcast_lesser_equal": jnp.less_equal,
    "broadcast_logical_and": jnp.logical_and,
    "broadcast_logical_or": jnp.logical_or,
    "broadcast_logical_xor": jnp.logical_xor,
}

# the reference exposes both elemwise and broadcast_* spellings of every
# comparison/logical op (elemwise requires equal shapes — a strict subset
# of broadcasting, so one XLA kernel serves both)
_COMPARE_ALIASES = {
    "broadcast_equal": ("equal", "_equal"),
    "broadcast_not_equal": ("not_equal", "_not_equal"),
    "broadcast_greater": ("greater", "_greater"),
    "broadcast_greater_equal": ("greater_equal", "_greater_equal"),
    "broadcast_lesser": ("lesser", "_lesser"),
    "broadcast_lesser_equal": ("lesser_equal", "_lesser_equal"),
    "broadcast_logical_and": ("logical_and",),
    "broadcast_logical_or": ("logical_or",),
    "broadcast_logical_xor": ("logical_xor",),
}

for _name, _jfn in _COMPARE.items():

    def _mkc(jfn):
        def fn(a, b):
            return jfn(a, b).astype(a.dtype)

        return fn

    register(_name, differentiable=False,
             aliases=_COMPARE_ALIASES.get(_name, ()))(_mkc(_jfn))


# elemwise_* (shape-equal) variants share impls with broadcast on XLA
@register("elemwise_add", aliases=("_plus", "_add"))
def elemwise_add(a, b):
    return jnp.add(a, b)


@register("elemwise_sub", aliases=("_minus", "_sub"))
def elemwise_sub(a, b):
    return jnp.subtract(a, b)


@register("elemwise_mul", aliases=("_mul",))
def elemwise_mul(a, b):
    return jnp.multiply(a, b)


@register("elemwise_div", aliases=("_div",))
def elemwise_div(a, b):
    return jnp.divide(a, b)


@register("_power")
def _power(a, b):
    return jnp.power(a, b)


@register("_maximum")
def _maximum(a, b):
    return jnp.maximum(a, b)


@register("_minimum")
def _minimum(a, b):
    return jnp.minimum(a, b)


@register("_mod")
def _mod(a, b):
    return jnp.mod(a, b)


# --------------------------------------------------------------------------
# scalar binary
# --------------------------------------------------------------------------
def _sc(v, a):
    return jnp.asarray(v, dtype=a.dtype)


@register("_plus_scalar")
def _plus_scalar(a, scalar=0.0):
    return a + _sc(scalar, a)


@register("_minus_scalar")
def _minus_scalar(a, scalar=0.0):
    return a - _sc(scalar, a)


@register("_rminus_scalar")
def _rminus_scalar(a, scalar=0.0):
    return _sc(scalar, a) - a


@register("_mul_scalar")
def _mul_scalar(a, scalar=1.0):
    return a * _sc(scalar, a)


@register("_div_scalar")
def _div_scalar(a, scalar=1.0):
    return a / _sc(scalar, a)


@register("_rdiv_scalar")
def _rdiv_scalar(a, scalar=1.0):
    return _sc(scalar, a) / a


@register("_mod_scalar")
def _mod_scalar(a, scalar=1.0):
    return jnp.mod(a, _sc(scalar, a))


@register("_rmod_scalar")
def _rmod_scalar(a, scalar=1.0):
    return jnp.mod(_sc(scalar, a), a)


@register("_power_scalar")
def _power_scalar(a, scalar=1.0):
    return jnp.power(a, _sc(scalar, a))


@register("_rpower_scalar")
def _rpower_scalar(a, scalar=1.0):
    return jnp.power(_sc(scalar, a), a)


@register("_maximum_scalar")
def _maximum_scalar(a, scalar=0.0):
    return jnp.maximum(a, _sc(scalar, a))


@register("_minimum_scalar")
def _minimum_scalar(a, scalar=0.0):
    return jnp.minimum(a, _sc(scalar, a))


@register("_hypot_scalar")
def _hypot_scalar(a, scalar=0.0):
    return jnp.hypot(a, _sc(scalar, a))


for _name, _jfn in {
    "_equal_scalar": jnp.equal,
    "_not_equal_scalar": jnp.not_equal,
    "_greater_scalar": jnp.greater,
    "_greater_equal_scalar": jnp.greater_equal,
    "_lesser_scalar": jnp.less,
    "_lesser_equal_scalar": jnp.less_equal,
    "_logical_and_scalar": jnp.logical_and,
    "_logical_or_scalar": jnp.logical_or,
    "_logical_xor_scalar": jnp.logical_xor,
}.items():

    def _mks(jfn):
        def fn(a, scalar=0.0):
            return jfn(a, jnp.asarray(scalar, a.dtype)).astype(a.dtype)

        return fn

    register(_name, differentiable=False)(_mks(_jfn))


# --------------------------------------------------------------------------
# unary
# --------------------------------------------------------------------------
def _gamma_fn(x):
    if hasattr(_jsp, "gamma"):
        return _jsp.gamma(x)
    return jnp.exp(_jsp.gammaln(x))


_UNARY = {
    "abs": jnp.abs,
    "sign": jnp.sign,
    "rint": jnp.rint,
    "round": jnp.round,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "trunc": jnp.trunc,
    "fix": jnp.trunc,
    "square": jnp.square,
    "sqrt": jnp.sqrt,
    "rsqrt": lambda x: jax.lax.rsqrt(x),
    "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "exp": jnp.exp,
    "log": jnp.log,
    "log10": jnp.log10,
    "log2": jnp.log2,
    "log1p": jnp.log1p,
    "expm1": jnp.expm1,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
    "relu": lambda x: jnp.maximum(x, 0),
    "sigmoid": jax.nn.sigmoid,
    "hard_sigmoid": lambda x: jnp.clip(0.2 * x + 0.5, 0.0, 1.0),
    "softsign": jax.nn.soft_sign,
    "erf": _jsp.erf,
    "erfinv": _jsp.erfinv,
    "gamma": _gamma_fn,
    "gammaln": _jsp.gammaln,
    "reciprocal": lambda x: 1.0 / x,
    "negative": jnp.negative,
}

_UNARY_NONDIFF = {"sign", "rint", "round", "ceil", "floor", "trunc", "fix"}

for _name, _jfn in _UNARY.items():

    def _mku(jfn):
        def fn(a):
            return jfn(a)

        return fn

    register(_name, differentiable=_name not in _UNARY_NONDIFF)(_mku(_jfn))


@register("logical_not", differentiable=False)
def logical_not(a):
    return jnp.logical_not(a).astype(a.dtype)


@register("clip")
def clip(a, a_min=None, a_max=None):
    return jnp.clip(a, a_min, a_max)


@register("gelu")
def gelu(a, approximate=True):
    return jax.nn.gelu(a, approximate=bool(approximate))


@register("smooth_l1")
def smooth_l1(a, scalar=1.0):
    s2 = float(scalar) ** 2
    absa = jnp.abs(a)
    return jnp.where(absa < 1.0 / s2, 0.5 * s2 * jnp.square(a), absa - 0.5 / s2)


@register("digamma")
def digamma(a):
    return jax.scipy.special.digamma(a)


@register("hardshrink")
def hardshrink(data, lambd=0.5):
    """ref: src/operator/tensor/elemwise_unary_op_basic.cc hard_shrink."""
    return jnp.where(jnp.abs(data) > lambd, data, 0.0).astype(data.dtype)


@register("softshrink")
def softshrink(data, lambd=0.5):
    """ref: elemwise_unary_op_basic.cc soft_shrink."""
    return (jnp.sign(data)
            * jnp.maximum(jnp.abs(data) - lambd, 0.0)).astype(data.dtype)


@register("amp_cast")
def amp_cast(data, dtype="float32"):
    """ref: src/operator/tensor/amp_cast.cc — AMP's dtype bridge."""
    from ..base import get_dtype

    return data.astype(get_dtype(dtype))


@register("amp_multicast")
def amp_multicast(*data, num_outputs=None):
    """ref: amp_cast.cc AMPMultiCast — cast all inputs to the widest
    floating dtype among them."""
    del num_outputs
    widest = jnp.result_type(*[d.dtype for d in data])
    return tuple(d.astype(widest) for d in data)


@register("all_finite", differentiable=False)
def all_finite(data, init_output=True):
    """(1,)-shaped 1.0/0.0 flag: every element finite (ref:
    src/operator/contrib/all_finite.cc — the gradient-overflow check
    behind dynamic loss scaling). init_output keeps API parity; the
    functional result is always freshly computed here."""
    del init_output
    return jnp.isfinite(data).all().reshape(1).astype(jnp.float32)


@register("multi_all_finite", differentiable=False)
def multi_all_finite(*arrays, num_arrays=None, init_output=True):
    """all_finite over several arrays at once (ref: all_finite.cc —
    MultiAllFinite; one fused check for a whole gradient set).
    num_arrays defaults to the actual count; a mismatch raises — a
    silently ignored gradient would hide an overflow from the loss
    scaler."""
    del init_output
    if num_arrays is not None and num_arrays != len(arrays):
        raise ValueError(
            "multi_all_finite got %d arrays but num_arrays=%d"
            % (len(arrays), num_arrays))
    ok = jnp.bool_(True)
    for a in arrays:
        ok = jnp.logical_and(ok, jnp.isfinite(a).all())
    return ok.reshape(1).astype(jnp.float32)
