"""Shape/layout/linear-algebra ops (ref: src/operator/tensor/matrix_op.cc,
dot.cc, concat.cc, src/operator/slice_channel.cc).

Includes the reference's reshape special codes (0, -1, -2, -3, -4 — ref:
matrix_op-inl.h ReshapeParam doc) and dot/batch_dot with transpose flags.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register

__all__ = ["infer_reshape"]


def infer_reshape(src_shape, target_shape, reverse=False):
    """Resolve a reference-style reshape spec against src_shape.

    Codes: 0 copy input dim; -1 infer one dim; -2 copy all remaining input
    dims; -3 merge two consecutive input dims; -4 split an input dim into the
    next two spec values (one may be -1).
    """
    src = list(src_shape)
    if reverse:
        src = src[::-1]
        target_shape = tuple(target_shape)[::-1]
    out = []
    src_i = 0
    i = 0
    tgt = list(target_shape)
    while i < len(tgt):
        t = tgt[i]
        if t == 0:
            out.append(src[src_i])
            src_i += 1
        elif t == -1:
            out.append(-1)
            src_i += 1
        elif t == -2:
            out.extend(src[src_i:])
            src_i = len(src)
        elif t == -3:
            out.append(src[src_i] * src[src_i + 1])
            src_i += 2
        elif t == -4:
            d1, d2 = tgt[i + 1], tgt[i + 2]
            if d1 == -1 and d2 == -1:
                raise ValueError("-4 split cannot infer both dims")
            if d1 == -1:
                d1 = src[src_i] // d2
            if d2 == -1:
                d2 = src[src_i] // d1
            out.extend([d1, d2])
            src_i += 1
            i += 2
        else:
            out.append(t)
            src_i += 1
        i += 1
    # resolve a single -1 against total size
    total = int(np.prod(src_shape)) if src_shape else 1
    if -1 in out:
        known = 1
        for d in out:
            if d != -1:
                known *= d
        idx = out.index(-1)
        out[idx] = total // max(known, 1)
    if reverse:
        out = out[::-1]
    return tuple(int(d) for d in out)


@register("reshape", aliases=("Reshape",))
def reshape(a, shape=None, reverse=False):
    new_shape = infer_reshape(a.shape, tuple(shape), reverse=bool(reverse))
    return jnp.reshape(a, new_shape)


@register("flatten", aliases=("Flatten",))
def flatten(a):
    return jnp.reshape(a, (a.shape[0], -1))


@register("transpose")
def transpose(a, axes=None):
    if axes is not None and len(axes) == 0:
        axes = None
    return jnp.transpose(a, axes)


@register("swapaxes", aliases=("SwapAxis",))
def swapaxes(a, dim1=0, dim2=0):
    return jnp.swapaxes(a, dim1, dim2)


@register("expand_dims")
def expand_dims(a, axis=0):
    return jnp.expand_dims(a, axis)


@register("squeeze")
def squeeze(a, axis=None):
    return jnp.squeeze(a, axis=axis)


@register("broadcast_to")
def broadcast_to(a, shape=None):
    # reference: 0 in target shape means keep source dim
    tgt = tuple(s if t == 0 else t for s, t in zip(a.shape, shape))
    return jnp.broadcast_to(a, tgt)


@register("broadcast_like")
def broadcast_like(a, b, lhs_axes=None, rhs_axes=None):
    if lhs_axes is None:
        return jnp.broadcast_to(a, b.shape)
    tgt = list(a.shape)
    for la, ra in zip(lhs_axes, rhs_axes):
        tgt[la % a.ndim] = b.shape[ra % b.ndim]
    return jnp.broadcast_to(a, tuple(tgt))


@register("broadcast_axis", aliases=("broadcast_axes",))
def broadcast_axis(a, axis=(), size=()):
    if isinstance(axis, int):
        axis = (axis,)
    if isinstance(size, int):
        size = (size,)
    tgt = list(a.shape)
    for ax, s in zip(axis, size):
        tgt[ax % a.ndim] = s
    return jnp.broadcast_to(a, tuple(tgt))


# --------------------------------------------------------------------------
# slicing
# --------------------------------------------------------------------------
def _norm_begin_end(shape, begin, end, step=None):
    ndim = len(shape)
    begin = list(begin) + [None] * (ndim - len(begin))
    end = list(end) + [None] * (ndim - len(end))
    step = list(step) + [None] * (ndim - len(step)) if step is not None else [None] * ndim
    slices = []
    for b, e, s in zip(begin, end, step):
        slices.append(slice(b, e, s))
    return tuple(slices)


@register("slice", aliases=("crop",))
def slice_op(a, begin=(), end=(), step=None):
    return a[_norm_begin_end(a.shape, begin, end, step)]


@register("slice_axis")
def slice_axis(a, axis=0, begin=0, end=None):
    idx = [slice(None)] * a.ndim
    idx[axis % a.ndim] = slice(begin, end)
    return a[tuple(idx)]


@register("slice_like")
def slice_like(a, b, axes=()):
    idx = [slice(None)] * a.ndim
    if not axes:
        axes = tuple(range(b.ndim))
    for ax in axes:
        idx[ax % a.ndim] = slice(0, b.shape[ax % b.ndim])
    return a[tuple(idx)]


@register("concat", aliases=("Concat",))
def concat(*args, dim=1, num_args=None):
    del num_args
    return jnp.concatenate(args, axis=dim)


@register("stack")
def stack(*args, axis=0, num_args=None):
    del num_args
    return jnp.stack(args, axis=axis)


@register("split", aliases=("SliceChannel",))
def split(a, num_outputs=1, axis=1, squeeze_axis=False):
    parts = jnp.split(a, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    if num_outputs == 1:
        return parts[0]
    return tuple(parts)


@register("split_v2")
def split_v2(a, indices_or_sections=1, axis=0, squeeze_axis=False):
    if isinstance(indices_or_sections, tuple):
        parts = jnp.split(a, list(indices_or_sections), axis=axis)
    else:
        parts = jnp.split(a, indices_or_sections, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts) if len(parts) > 1 else parts[0]


@register("tile")
def tile(a, reps=()):
    return jnp.tile(a, tuple(reps))


@register("repeat")
def repeat(a, repeats=1, axis=None):
    return jnp.repeat(a, repeats, axis=axis)


@register("flip", aliases=("reverse",))
def flip(a, axis=()):
    if isinstance(axis, int):
        axis = (axis,)
    return jnp.flip(a, axis=tuple(axis))


@register("pad", aliases=("Pad",))
def pad(a, mode="constant", pad_width=(), constant_value=0.0):
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(a.ndim)]
    if mode == "constant":
        return jnp.pad(a, pw, mode="constant", constant_values=constant_value)
    if mode == "edge":
        return jnp.pad(a, pw, mode="edge")
    if mode == "reflect":
        return jnp.pad(a, pw, mode="reflect")
    raise ValueError("unknown pad mode %r" % (mode,))


@register("where")
def where(condition, x, y):
    return jnp.where(condition.astype(bool), x, y)


@register("diag")
def diag(a, k=0):
    if a.ndim == 1:
        return jnp.diag(a, k=k)
    return jnp.diagonal(a, offset=k, axis1=-2, axis2=-1)


# --------------------------------------------------------------------------
# dot / batch_dot — the MXU path
# --------------------------------------------------------------------------
@register("dot")
def dot(a, b, transpose_a=False, transpose_b=False):
    """N-D dot: contract last axis of a with first axis of b
    (ref: src/operator/tensor/dot-inl.h). Lowers to dot_general → MXU."""
    if transpose_a:
        a = jnp.transpose(a)
    if transpose_b:
        b = jnp.transpose(b)
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]))


@register("batch_dot")
def batch_dot(a, b, transpose_a=False, transpose_b=False):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


@register("khatri_rao")
def khatri_rao(*args):
    out = args[0]
    for m in args[1:]:
        out = jnp.einsum("i...,j...->ij...", out, m).reshape(
            out.shape[0] * m.shape[0], *out.shape[1:]
        )
    return out


@register("L2Normalization")
def l2_normalization(a, eps=1e-10, mode="instance"):
    if mode == "instance":
        ax = tuple(range(1, a.ndim))
    elif mode == "channel":
        ax = (1,)
    elif mode == "spatial":
        ax = tuple(range(2, a.ndim))
    else:
        raise ValueError("unknown mode %r" % (mode,))
    nrm = jnp.sqrt(jnp.sum(jnp.square(a), axis=ax, keepdims=True) + eps)
    return a / nrm


@register("norm_like_cast", aliases=("cast", "Cast"))
def cast(a, dtype="float32"):
    from ..base import get_dtype

    return a.astype(get_dtype(dtype))


@register("_zeros", aliases=("zeros_op",), differentiable=False)
def _zeros(shape=(), dtype="float32"):
    """Nullary creation op (ref: src/operator/tensor/init_op.cc — _zeros);
    the symbolic form backs mx.sym.zeros / rnn begin_state."""
    from ..base import get_dtype

    return jnp.zeros(shape, dtype=get_dtype(dtype))


@register("_ones", aliases=("ones_op",), differentiable=False)
def _ones(shape=(), dtype="float32"):
    """ref: init_op.cc — _ones."""
    from ..base import get_dtype

    return jnp.ones(shape, dtype=get_dtype(dtype))


@register("zeros_like")
def zeros_like(a):
    return jnp.zeros_like(a)


@register("ones_like")
def ones_like(a):
    return jnp.ones_like(a)


@register("shape_array", differentiable=False)
def shape_array(a):
    return jnp.asarray(a.shape, dtype=jnp.int64)


@register("size_array", differentiable=False)
def size_array(a):
    return jnp.asarray([a.size], dtype=jnp.int64)


# --------------------------------------------------------------------------
# block/space rearrangement + index transforms
# (ref: src/operator/tensor/matrix_op.cc DepthToSpace/SpaceToDepth,
#  ravel.cc, src/operator/tensor/indexing_op.cc batch_take)
# --------------------------------------------------------------------------
@register("tril")
def tril(a, k=0):
    return jnp.tril(a, k)


@register("triu")
def triu(a, k=0):
    return jnp.triu(a, k)


@register("depth_to_space")
def depth_to_space(data, block_size=1):
    """ref: matrix_op.cc DepthToSpace (DCR): (N, C*b^2, H, W) ->
    (N, C, H*b, W*b), y[n,c,h*b+i,w*b+j] = x[n,(i*b+j)*C+c,h,w]."""
    n, cbb, h, w = data.shape
    b = block_size
    c = cbb // (b * b)
    x = data.reshape(n, b, b, c, h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(n, c, h * b, w * b)


@register("space_to_depth")
def space_to_depth(data, block_size=1):
    """Inverse of depth_to_space (ref: matrix_op.cc SpaceToDepth)."""
    n, c, hb, wb = data.shape
    b = block_size
    h, w = hb // b, wb // b
    x = data.reshape(n, c, h, b, w, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h, w)


@register("reshape_like")
def reshape_like(lhs, rhs, lhs_begin=None, lhs_end=None, rhs_begin=None,
                 rhs_end=None):
    """ref: matrix_op.cc ReshapeLike — reshape lhs's [lhs_begin, lhs_end)
    dims to rhs's [rhs_begin, rhs_end) dims (whole shape by default)."""
    ls, rs = list(lhs.shape), list(rhs.shape)
    lb = 0 if lhs_begin is None else lhs_begin % (len(ls) + 1)
    le = len(ls) if lhs_end is None else lhs_end % (len(ls) + 1)
    rb = 0 if rhs_begin is None else rhs_begin % (len(rs) + 1)
    re_ = len(rs) if rhs_end is None else rhs_end % (len(rs) + 1)
    new_shape = ls[:lb] + rs[rb:re_] + ls[le:]
    return lhs.reshape(new_shape)


@register("unravel_index", differentiable=False)
def unravel_index(data, shape=()):
    """ref: ravel.cc — flat indices -> (ndim, ...) coordinates."""
    coords = jnp.unravel_index(data.astype(jnp.int64), tuple(shape))
    return jnp.stack(coords, axis=0)


@register("ravel_multi_index", differentiable=False)
def ravel_multi_index(data, shape=()):
    """ref: ravel.cc — (ndim, ...) coordinates -> flat indices."""
    shape = tuple(shape)
    strides = np.cumprod((1,) + shape[:0:-1])[::-1]
    flat = sum(data[i].astype(jnp.int64) * int(strides[i])
               for i in range(len(shape)))
    return flat


@register("batch_take")
def batch_take(a, indices):
    """ref: indexing_op.cc BatchTake — out[i] = a[i, indices[i]]."""
    idx = indices.astype(jnp.int32).reshape(-1)
    return jnp.take_along_axis(a, idx[:, None], axis=1)[:, 0]


@register("choose_element_0index")
def choose_element_0index(lhs, rhs):
    """Legacy alias of batch_take with float indices
    (ref: src/operator/swapaxis.cc-era legacy ops)."""
    return batch_take(lhs, rhs)


@register("fill_element_0index")
def fill_element_0index(lhs, mhs, rhs):
    """ref: legacy op — out = lhs with out[i, rhs[i]] = mhs[i]."""
    idx = rhs.astype(jnp.int32).reshape(-1)
    return lhs.at[jnp.arange(lhs.shape[0]), idx].set(mhs)


# --------------------------------------------------------------------------
# im2col / col2im (ref: src/operator/nn/im2col.h — the reference's conv
# lowering helpers, exposed as ops)
# --------------------------------------------------------------------------
def _im2col_raw(data, kernel, stride, dilate, pad):
    patches = jax.lax.conv_general_dilated_patches(
        data,
        filter_shape=tuple(kernel),
        window_strides=tuple(stride),
        padding=[(p, p) for p in pad],
        rhs_dilation=tuple(dilate),
    )  # (N, C*prod(kernel), *out_spatial)
    return patches.reshape(patches.shape[0], patches.shape[1], -1)


@register("im2col")
def im2col(data, kernel=(), stride=(), dilate=(), pad=()):
    """ref: im2col.h — (N, C, *spatial) -> (N, C*prod(kernel), L)."""
    nd_ = len(kernel)
    stride = tuple(stride) if stride else (1,) * nd_
    dilate = tuple(dilate) if dilate else (1,) * nd_
    pad = tuple(pad) if pad else (0,) * nd_
    return _im2col_raw(data, kernel, stride, dilate, pad)


@register("col2im")
def col2im(data, output_size=(), kernel=(), stride=(), dilate=(), pad=()):
    """ref: im2col.h col2im — scatter-add patches back to an image.
    Exactly the linear transpose of im2col, computed as such."""
    nd_ = len(kernel)
    stride = tuple(stride) if stride else (1,) * nd_
    dilate = tuple(dilate) if dilate else (1,) * nd_
    pad = tuple(pad) if pad else (0,) * nd_
    n = data.shape[0]
    spatial = tuple(output_size)
    c = data.shape[1] // int(np.prod(kernel))
    img_aval = jax.ShapeDtypeStruct((n, c) + spatial, data.dtype)

    def fwd(img):
        return _im2col_raw(img, kernel, stride, dilate, pad)

    (img,) = jax.linear_transpose(fwd, img_aval)(data)
    return img
