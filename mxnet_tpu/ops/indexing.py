"""Indexing / gather / scatter / ordering / sequence ops
(ref: src/operator/tensor/indexing_op.cc, ordering_op.cc,
src/operator/sequence_*.cc).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


@register("take")
def take(a, indices, axis=0, mode="clip"):
    idx = indices.astype(jnp.int32)
    if mode == "wrap":
        idx = jnp.mod(idx, a.shape[axis])
    else:  # clip (default) — also what makes gather TPU-safe
        idx = jnp.clip(idx, 0, a.shape[axis] - 1)
    return jnp.take(a, idx, axis=axis)


@register("Embedding")
def embedding(data, weight, input_dim=None, output_dim=None, dtype=None,
              sparse_grad=False):
    """Embedding lookup (ref: src/operator/tensor/indexing_op.cc — Embedding).

    On TPU this is a gather feeding the MXU-free path; the row_sparse
    gradient variant lives in the sparse module.
    """
    del input_dim, output_dim, dtype, sparse_grad
    idx = jnp.clip(data.astype(jnp.int32), 0, weight.shape[0] - 1)
    return jnp.take(weight, idx, axis=0)


@register("pick")
def pick(a, index, axis=-1, keepdims=False, mode="clip"):
    idx = jnp.clip(index.astype(jnp.int32), 0, a.shape[axis] - 1)
    out = jnp.take_along_axis(a, jnp.expand_dims(idx, axis=axis), axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return out


@register("one_hot", differentiable=False)
def one_hot(indices, depth=0, on_value=1.0, off_value=0.0, dtype="float32"):
    from ..base import get_dtype

    dt = get_dtype(dtype)
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth, dtype=dt)
    return oh * jnp.asarray(on_value, dt) + (1 - oh) * jnp.asarray(off_value, dt)


@register("gather_nd")
def gather_nd(data, indices):
    """indices shape (M, ...) selects from the first M axes of data
    (ref: indexing_op.cc — gather_nd)."""
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    idx_tuple = tuple(
        jnp.clip(idx[i], 0, data.shape[i] - 1) for i in range(m)
    )
    return data[idx_tuple]


@register("scatter_nd")
def scatter_nd(data, indices, shape=None):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    out = jnp.zeros(tuple(shape), dtype=data.dtype)
    idx_tuple = tuple(idx[i] for i in range(m))
    return out.at[idx_tuple].add(data)


@register("index_copy")
def index_copy(old, index, new):
    return old.at[index.astype(jnp.int32)].set(new)


@register("index_add")
def index_add(old, index, new):
    return old.at[index.astype(jnp.int32)].add(new)


@register("boolean_mask", differentiable=False)
def boolean_mask(data, index, axis=0):
    """Dynamic-shape op: eager only (under jit the output shape cannot be
    static on TPU; reference's contrib BooleanMask has the same data
    dependence)."""
    import numpy as np

    mask = np.asarray(index).astype(bool)
    keep = np.flatnonzero(mask)
    return jnp.take(data, jnp.asarray(keep), axis=axis)


# --------------------------------------------------------------------------
# ordering (ref: src/operator/tensor/ordering_op.cc)
# --------------------------------------------------------------------------
@register("sort")
def sort(a, axis=-1, is_ascend=True):
    out = jnp.sort(a, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out


@register("argsort", differentiable=False)
def argsort(a, axis=-1, is_ascend=True, dtype="float32"):
    from ..base import get_dtype

    out = jnp.argsort(a, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(get_dtype(dtype))


@register("topk", differentiable=False)
def topk(a, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    from ..base import get_dtype

    dt = get_dtype(dtype)
    ax = axis % a.ndim if axis is not None else a.ndim - 1
    src = -a if is_ascend else a
    moved = jnp.moveaxis(src, ax, -1)
    vals, idxs = jax.lax.top_k(moved, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, ax)
    idxs = jnp.moveaxis(idxs, -1, ax)
    if ret_typ == "indices":
        return idxs.astype(dt)
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return (vals, idxs.astype(dt))
    if ret_typ == "mask":
        oh = jax.nn.one_hot(jnp.moveaxis(idxs, ax, -1), a.shape[ax], dtype=a.dtype)
        mask = oh.sum(axis=-2)
        return jnp.moveaxis(mask, -1, ax)
    raise ValueError("unknown ret_typ %r" % (ret_typ,))


# --------------------------------------------------------------------------
# sequence ops (ref: src/operator/sequence_mask.cc etc.) — axis layout
# (max_len, batch, ...) with use_sequence_length flag, as in the reference.
# --------------------------------------------------------------------------
def _seq_mask(lengths, maxlen):
    return jnp.arange(maxlen)[:, None] < lengths[None, :].astype(jnp.int32)


@register("SequenceMask")
def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    if not use_sequence_length or sequence_length is None:
        return data
    t_ax = axis
    maxlen = data.shape[t_ax]
    mask = _seq_mask(sequence_length, maxlen)  # (T, B)
    if t_ax == 1:
        mask = mask.T
    shape = [1] * data.ndim
    shape[t_ax] = data.shape[t_ax]
    shape[1 - t_ax] = data.shape[1 - t_ax]
    mask = mask.reshape(shape)
    return jnp.where(mask, data, jnp.asarray(value, data.dtype))


@register("SequenceLast")
def sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        idx = [slice(None)] * data.ndim
        idx[axis] = -1
        return data[tuple(idx)]
    last = (sequence_length.astype(jnp.int32) - 1)  # (B,)
    moved = jnp.moveaxis(data, axis, 0)  # (T, B, ...)
    return jnp.take_along_axis(
        moved, last.reshape((1, -1) + (1,) * (moved.ndim - 2)), axis=0
    )[0]


@register("SequenceReverse")
def sequence_reverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    moved = jnp.moveaxis(data, axis, 0)
    T = moved.shape[0]
    if not use_sequence_length or sequence_length is None:
        rev = jnp.flip(moved, axis=0)
    else:
        lens = sequence_length.astype(jnp.int32)  # (B,)
        t = jnp.arange(T)[:, None]  # (T,1)
        src = jnp.where(t < lens[None, :], lens[None, :] - 1 - t, t)  # (T,B)
        src = src.reshape((T, -1) + (1,) * (moved.ndim - 2))
        rev = jnp.take_along_axis(moved, jnp.broadcast_to(src, moved.shape), axis=0)
    return jnp.moveaxis(rev, 0, axis)
