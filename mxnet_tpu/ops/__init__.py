"""Operator library. Importing this package registers all ops
(analog of the reference's static NNVM_REGISTER_OP registration)."""
from . import registry
from . import elemwise
from . import reduce
from . import matrix
from . import indexing
from . import nn
from . import random_ops
from . import rnn
from . import optimizer_ops
from . import loss_output
from . import attention
from . import linalg
from . import contrib_ops
from . import ctc
from . import quantization

from .registry import apply_op, get_op, list_ops, register, Op

__all__ = ["apply_op", "get_op", "list_ops", "register", "Op"]
