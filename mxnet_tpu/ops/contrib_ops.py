"""Contrib op family (ref: src/operator/contrib/* — "port on demand" per
SURVEY §2.2): FFT, index_copy/index_add, count_sketch, boolean_mask, the
SSD triple (MultiBoxPrior/MultiBoxTarget/MultiBoxDetection), and the RPN
Proposal op — all static-shape XLA programs (greedy NMS as fori_loop).

Registered under both the bare name and the reference's ``_contrib_``
prefix so nd/sym namespaces resolve either spelling.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register


@register("fft", aliases=("_contrib_fft",))
def fft(data, compute_size=None):
    """ref: src/operator/contrib/fft.cc — FFT along the last axis;
    output interleaves (real, imag) so the last dim doubles."""
    del compute_size
    ct = jnp.promote_types(data.dtype, jnp.float32)
    out = jnp.fft.fft(data.astype(ct), axis=-1)
    inter = jnp.stack([out.real, out.imag], axis=-1)
    return inter.reshape(data.shape[:-1] + (2 * data.shape[-1],)).astype(
        data.dtype)


@register("ifft", aliases=("_contrib_ifft",))
def ifft(data, compute_size=None):
    """ref: contrib/fft.cc IFFT — input interleaves (real, imag); the
    reference does NOT normalize by n (matches its docs)."""
    del compute_size
    n = data.shape[-1] // 2
    ct = jnp.promote_types(data.dtype, jnp.float32)
    x = data.astype(ct).reshape(data.shape[:-1] + (n, 2))
    comp = jax.lax.complex(x[..., 0], x[..., 1])
    out = jnp.fft.ifft(comp, axis=-1).real * n
    return out.astype(data.dtype)


@register("index_copy", aliases=("_contrib_index_copy",))
def index_copy(old, index, new):
    """ref: contrib/index_copy.cc — copy rows of `new` into `old` at
    `index` positions along axis 0."""
    return old.at[index.astype(jnp.int32)].set(new)


@register("index_add", aliases=("_contrib_index_add",))
def index_add(data, index, value):
    """Scatter-add rows (companion of index_copy)."""
    return data.at[index.astype(jnp.int32)].add(value)


@register("count_sketch", aliases=("_contrib_count_sketch",))
def count_sketch(data, h, s, out_dim=0):
    """ref: contrib/count_sketch.cc — random-hash feature sketch:
    out[n, h[i]] += s[i] * data[n, i] with sign hashes s in {-1, +1}."""
    if int(out_dim) <= 0:
        raise ValueError("count_sketch requires out_dim > 0 (got %r); the "
                         "reference treats it as a required parameter"
                         % (out_dim,))
    n, d = data.shape
    idx = h.astype(jnp.int32).reshape(-1)[:d]
    sign = s.astype(data.dtype).reshape(-1)[:d]
    out = jnp.zeros((n, int(out_dim)), data.dtype)
    return out.at[:, idx].add(data * sign[None, :])


@register("boolean_mask", aliases=("_contrib_boolean_mask",),
          differentiable=False)
def boolean_mask(data, index, axis=0):
    """ref: contrib/boolean_mask.cc. Output shape is data-dependent —
    usable eagerly; inside jit/symbol tracing the dynamic shape is
    rejected by XLA (same class of limitation as the reference's
    shape-inference pass, which special-cases this op)."""
    mask = index.astype(bool)
    keep = jnp.nonzero(mask)[0]
    return jnp.take(data, keep, axis=axis)


@register("MultiBoxPrior", aliases=("_contrib_MultiBoxPrior",),
          differentiable=False)
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """ref: src/operator/contrib/multibox_prior.cc — SSD anchor boxes.

    data: (N, C, H, W) feature map (only H/W used). Returns
    (1, H*W*(len(sizes)+len(ratios)-1), 4) corner-format anchors in
    [0, 1] coordinates, matching the reference's anchor ordering: for
    each pixel, every size with ratios[0] first, then the remaining
    ratios with sizes[0].
    """
    h, w = data.shape[2], data.shape[3]
    sizes = tuple(sizes)
    ratios = tuple(ratios)
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    cy = (np.arange(h) + offsets[0]) * step_y
    cx = (np.arange(w) + offsets[1]) * step_x
    # anchor (width, height) list per the reference's enumeration:
    # sizes-first with ratios[0], then remaining ratios with sizes[0]
    whs = []
    r0 = np.sqrt(ratios[0])
    for s in sizes:
        whs.append((s * r0, s / r0))
    for r in ratios[1:]:
        sr = np.sqrt(r)
        whs.append((sizes[0] * sr, sizes[0] / sr))
    whs = np.asarray(whs)  # (A, 2)
    gy, gx = np.meshgrid(cy, cx, indexing="ij")
    centers = np.stack([gx.ravel(), gy.ravel()], axis=1)  # (HW, 2) x,y
    half = whs / 2.0
    mins = centers[:, None, :] - half[None, :, :]
    maxs = centers[:, None, :] + half[None, :, :]
    anchors = np.concatenate([mins, maxs], axis=2).reshape(-1, 4)
    if clip:
        anchors = np.clip(anchors, 0.0, 1.0)
    return jnp.asarray(anchors[None], jnp.float32)


def _corner_to_center(boxes):
    w = boxes[..., 2] - boxes[..., 0]
    h = boxes[..., 3] - boxes[..., 1]
    cx = boxes[..., 0] + w * 0.5
    cy = boxes[..., 1] + h * 0.5
    return cx, cy, w, h


def _iou_matrix(a, b):
    """(N, 4) x (M, 4) corner boxes -> (N, M) IOU."""
    ix0 = jnp.maximum(a[:, None, 0], b[None, :, 0])
    iy0 = jnp.maximum(a[:, None, 1], b[None, :, 1])
    ix1 = jnp.minimum(a[:, None, 2], b[None, :, 2])
    iy1 = jnp.minimum(a[:, None, 3], b[None, :, 3])
    inter = jnp.maximum(ix1 - ix0, 0) * jnp.maximum(iy1 - iy0, 0)
    area_a = jnp.maximum(a[:, 2] - a[:, 0], 0) * \
        jnp.maximum(a[:, 3] - a[:, 1], 0)
    area_b = jnp.maximum(b[:, 2] - b[:, 0], 0) * \
        jnp.maximum(b[:, 3] - b[:, 1], 0)
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-12), 0.0)


def _iou_row(boxes, cand_box):
    """IOU of one box against (N, 4) corner boxes — O(N) per NMS step,
    so no quadratic IOU buffer is ever materialized."""
    ix0 = jnp.maximum(boxes[:, 0], cand_box[0])
    iy0 = jnp.maximum(boxes[:, 1], cand_box[1])
    ix1 = jnp.minimum(boxes[:, 2], cand_box[2])
    iy1 = jnp.minimum(boxes[:, 3], cand_box[3])
    inter = jnp.maximum(ix1 - ix0, 0) * jnp.maximum(iy1 - iy0, 0)
    area = jnp.maximum(boxes[:, 2] - boxes[:, 0], 0) * \
        jnp.maximum(boxes[:, 3] - boxes[:, 1], 0)
    cand_area = jnp.maximum(cand_box[2] - cand_box[0], 0) * \
        jnp.maximum(cand_box[3] - cand_box[1], 0)
    union = area + cand_area - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-12), 0.0)


def _greedy_nms(boxes, order, keep, thresh, class_ids=None,
                rank_gate=None):
    """Shared greedy suppression (the one loop behind MultiBoxDetection,
    Proposal, and box_nms): walk candidates in score order; a live
    candidate kills every OTHER box with IOU > thresh (same class only,
    unless class_ids is None). rank_gate[i] False means the i-th ranked
    candidate cannot suppress (but can still be suppressed). Returns the
    alive mask."""
    n = boxes.shape[0]
    if rank_gate is None:
        rank_gate = jnp.ones((n,), bool)

    def body(i, alive):
        cand = order[i]
        is_live = alive[cand] & keep[cand] & rank_gate[i]
        pair = _iou_row(boxes, boxes[cand]) > thresh
        if class_ids is not None:
            pair = pair & (class_ids == class_ids[cand])
        kill = pair & is_live
        kill = kill.at[cand].set(False)
        return alive & ~kill

    return jax.lax.fori_loop(0, n, body, keep)


@register("MultiBoxTarget", aliases=("_contrib_MultiBoxTarget",),
          differentiable=False, num_outputs=3)
def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """ref: src/operator/contrib/multibox_target.cc — match anchors to
    ground-truth boxes and encode regression targets.

    anchor: (1, A, 4) corners; label: (B, O, 5+) rows
    [cls, x1, y1, x2, y2] with cls -1 padding; cls_pred (B, C, A) class
    scores drive hard negative mining when negative_mining_ratio > 0: the
    unmatched anchors with best_iou < negative_mining_thresh are ranked by
    their hottest non-background score and only the top
    max(ratio * num_positive, minimum_negative_samples) stay background
    training samples — every other negative gets cls_target = ignore_label
    so the loss skips it. Returns (loc_target (B, A*4), loc_mask (B, A*4),
    cls_target (B, A)) where cls_target is 1 + gt class for matched
    anchors, 0 for selected background, ignore_label for mined-out.
    """
    mine = negative_mining_ratio is not None and negative_mining_ratio > 0
    anchors = anchor.reshape(-1, 4)
    a_cx, a_cy, a_w, a_h = _corner_to_center(anchors)
    vx, vy, vw, vh = variances

    def one_sample(lbl, pred):
        cls = lbl[:, 0]
        boxes = lbl[:, 1:5]
        valid = cls >= 0
        iou = _iou_matrix(anchors, boxes)  # (A, O)
        iou = jnp.where(valid[None, :], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1)           # per anchor
        best_iou = jnp.max(iou, axis=1)
        matched = best_iou >= overlap_threshold
        # reference also force-matches each gt's best anchor; padding
        # rows (cls=-1) must not scatter at all — route their writes to
        # an out-of-range index that mode="drop" discards, else a padded
        # gt whose (meaningless) argmax lands on the same anchor as a
        # valid gt would clobber the valid force-match
        best_anchor = jnp.argmax(iou, axis=0)       # (O,)
        n_anchor = anchors.shape[0]
        scatter_to = jnp.where(valid, best_anchor, n_anchor)
        forced = jnp.zeros(n_anchor, bool).at[scatter_to].set(
            True, mode="drop")
        gt_for_forced = jnp.zeros(n_anchor, jnp.int32).at[scatter_to].set(
            jnp.arange(boxes.shape[0], dtype=jnp.int32), mode="drop")
        use_forced = forced & ~matched
        assigned = jnp.where(use_forced, gt_for_forced,
                             best_gt.astype(jnp.int32))
        matched = matched | forced
        g = boxes[assigned]
        g_cx, g_cy, g_w, g_h = _corner_to_center(g)
        g_w = jnp.maximum(g_w, 1e-8)
        g_h = jnp.maximum(g_h, 1e-8)
        t = jnp.stack([
            (g_cx - a_cx) / a_w / vx,
            (g_cy - a_cy) / a_h / vy,
            jnp.log(g_w / a_w) / vw,
            jnp.log(g_h / a_h) / vh,
        ], axis=1)  # (A, 4)
        mask = matched[:, None].astype(t.dtype)
        if mine:
            # hard negative mining (ref: multibox_target.cc negative
            # mining branch): hardness = hottest non-background score
            hardness = jnp.max(pred[1:, :], axis=0)  # (A,)
            eligible = (~matched) & (best_iou < negative_mining_thresh)
            num_pos = jnp.sum(matched)
            num_neg = jnp.maximum(
                (num_pos * negative_mining_ratio).astype(jnp.int32),
                jnp.int32(minimum_negative_samples))
            score = jnp.where(eligible, hardness, -jnp.inf)
            order = jnp.argsort(-score)
            rank = jnp.argsort(order)  # rank[i] = position of anchor i
            keep_neg = eligible & (rank < num_neg)
            cls_t = jnp.where(
                matched, cls[assigned].astype(jnp.float32) + 1.0,
                jnp.where(keep_neg, 0.0, jnp.float32(ignore_label)))
        else:
            cls_t = jnp.where(matched,
                              cls[assigned].astype(jnp.float32) + 1.0, 0.0)
        return (t * mask).reshape(-1), jnp.broadcast_to(
            mask, t.shape).reshape(-1), cls_t

    loc_t, loc_m, cls_t = jax.vmap(one_sample)(
        label.astype(jnp.float32), cls_pred.astype(jnp.float32))
    return loc_t, loc_m, cls_t


@register("MultiBoxDetection", aliases=("_contrib_MultiBoxDetection",),
          differentiable=False)
def multibox_detection(cls_prob, loc_pred, anchor, clip=True,
                       threshold=0.01, background_id=0,
                       nms_threshold=0.5, force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """ref: src/operator/contrib/multibox_detection.cc — decode anchor
    offsets and run class-wise greedy NMS with static shapes.

    cls_prob: (B, C, A) softmax probs incl. background class 0;
    loc_pred: (B, A*4); anchor: (1, A, 4). Returns (B, A, 6) rows
    [cls_id, score, x1, y1, x2, y2]; suppressed/below-threshold rows
    have cls_id -1 (the reference's invalid marker).
    """
    anchors = anchor.reshape(-1, 4)
    a_cx, a_cy, a_w, a_h = _corner_to_center(anchors)
    vx, vy, vw, vh = variances
    A = anchors.shape[0]

    def one_sample(probs, loc):
        loc = loc.reshape(-1, 4)
        cx = loc[:, 0] * vx * a_w + a_cx
        cy = loc[:, 1] * vy * a_h + a_cy
        w = jnp.exp(loc[:, 2] * vw) * a_w
        h = jnp.exp(loc[:, 3] * vh) * a_h
        boxes = jnp.stack([cx - w / 2, cy - h / 2,
                           cx + w / 2, cy + h / 2], axis=1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        # best non-background class per anchor; the output id is 0-based
        # over FOREGROUND classes (reference convention: class - 1)
        if background_id != 0:
            raise ValueError("only background_id=0 is supported "
                             "(the reference's fixed convention)")
        fg = probs[1:]
        cls_id = jnp.argmax(fg, axis=0).astype(jnp.int32)
        score = jnp.max(fg, axis=0)
        keep = score > threshold
        order = jnp.argsort(-jnp.where(keep, score, -jnp.inf))
        if nms_topk > 0:
            in_topk = jnp.arange(A) < nms_topk
        else:
            in_topk = jnp.ones((A,), bool)
        alive = _greedy_nms(
            boxes, order, keep, nms_threshold,
            class_ids=None if force_suppress else cls_id,
            rank_gate=in_topk)
        final = alive & keep
        if nms_topk > 0:
            # reference invalidates detections ranked beyond top-k
            # outright (multibox_detection-inl.h: out[idx] = -1)
            topk_mask = jnp.zeros((A,), bool).at[
                order[:min(nms_topk, A)]].set(True)
            final = final & topk_mask
        out = jnp.concatenate([
            jnp.where(final, cls_id, -1)[:, None].astype(boxes.dtype),
            jnp.where(final, score, -1)[:, None].astype(boxes.dtype),
            boxes,
        ], axis=1)
        # reference output ordering: valid detections first, sorted by
        # descending score; suppressed rows (-1) trail
        rank = jnp.argsort(-jnp.where(final, score, -jnp.inf))
        return out[rank]

    return jax.vmap(one_sample)(cls_prob.astype(jnp.float32),
                                loc_pred.astype(jnp.float32))


@register("Proposal", aliases=("_contrib_Proposal",),
          differentiable=False)
def proposal(cls_prob, bbox_pred, im_info, scales=(4, 8, 16, 32),
             ratios=(0.5, 1, 2), feature_stride=16, threshold=0.7,
             rpn_pre_nms_top_n=6000, rpn_post_nms_top_n=300,
             rpn_min_size=16, output_score=False):
    """ref: src/operator/contrib/proposal.cc — RPN region proposals.

    cls_prob: (B, 2*A, H, W) objectness (bg/fg per anchor);
    bbox_pred: (B, 4*A, H, W) deltas; im_info: (B, 3) [height, width,
    scale]. Returns (B*post_nms, 5) rows [batch_idx, x1, y1, x2, y2]
    (+ scores as a second output when output_score). Static shapes
    throughout: NMS is the same greedy fori_loop as MultiBoxDetection,
    short batches pad with the best surviving row (reference pads too).
    """
    B, twoA, H, W = cls_prob.shape
    A = len(scales) * len(ratios)
    if twoA != 2 * A:
        raise ValueError(
            "cls_prob has %d channels but scales x ratios implies %d "
            "anchors (need 2 per anchor)" % (twoA, A))
    # reference GenerateAnchors (py-faster-rcnn enumeration): base box
    # [0, 0, stride-1, stride-1], ratio anchors use ROUNDED widths/
    # heights around the (stride-1)/2 center, then scale multiplies
    base_size = feature_stride
    ctr = (base_size - 1) * 0.5
    base_area = base_size * base_size
    whs = []
    for r in ratios:
        w_r = np.round(np.sqrt(base_area / r))
        h_r = np.round(w_r * r)
        for sc in scales:
            whs.append((w_r * sc, h_r * sc))
    whs = np.asarray(whs)  # (A, 2) — ratio-major, scale-minor (reference)
    ys = np.arange(H) * feature_stride + ctr
    xs = np.arange(W) * feature_stride + ctr
    gy, gx = np.meshgrid(ys, xs, indexing="ij")
    centers = np.stack([gx.ravel(), gy.ravel()], axis=1)  # (HW, 2)
    # corner = center -+ (wh - 1) / 2, matching _mkanchors
    base = np.concatenate([
        centers[:, None, :] - (whs[None] - 1) / 2,
        centers[:, None, :] + (whs[None] - 1) / 2,
    ], axis=2).reshape(-1, 4)  # (HW*A, 4) pixel corners
    base = jnp.asarray(base, jnp.float32)
    n_total = base.shape[0]
    a_cx, a_cy, a_w, a_h = _corner_to_center(base)
    pre_n = min(rpn_pre_nms_top_n, n_total)
    post_n = min(rpn_post_nms_top_n, pre_n)

    def one_sample(probs, deltas, info):
        fg = probs[A:]  # (A, H, W) foreground scores
        score = fg.transpose(1, 2, 0).reshape(-1)  # HW-major, anchor-minor
        d = deltas.reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        cx = d[:, 0] * a_w + a_cx
        cy = d[:, 1] * a_h + a_cy
        w = jnp.exp(jnp.clip(d[:, 2], -10, 10)) * a_w
        h = jnp.exp(jnp.clip(d[:, 3], -10, 10)) * a_h
        boxes = jnp.stack([cx - w / 2, cy - h / 2,
                           cx + w / 2, cy + h / 2], axis=1)
        im_h, im_w = info[0], info[1]
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0, im_w - 1),
            jnp.clip(boxes[:, 1], 0, im_h - 1),
            jnp.clip(boxes[:, 2], 0, im_w - 1),
            jnp.clip(boxes[:, 3], 0, im_h - 1),
        ], axis=1)
        min_size = rpn_min_size * info[2]
        big = ((boxes[:, 2] - boxes[:, 0] + 1) >= min_size) & \
              ((boxes[:, 3] - boxes[:, 1] + 1) >= min_size)
        score = jnp.where(big, score, -jnp.inf)
        # pre-NMS top-k
        top_score, top_idx = jax.lax.top_k(score, pre_n)
        top_boxes = boxes[top_idx]
        keep0 = jnp.isfinite(top_score)
        # rows already score-sorted: order is the identity
        alive = _greedy_nms(top_boxes, jnp.arange(pre_n), keep0,
                            threshold)
        # select post_n survivors in rank order; short batches cycle
        # through the survivors, as the reference does (proposal.cc:
        # keep[i % num_keep])
        surv_rank = jnp.where(alive, jnp.arange(pre_n), pre_n)
        ordered = jnp.sort(surv_rank)
        n_keep = jnp.maximum(jnp.sum(alive), 1)
        picked = ordered[jnp.arange(post_n) % n_keep]
        picked = jnp.where(picked == pre_n, 0, picked)
        # filtered-out rows carry -inf internally; the reference emits a
        # finite -1 sentinel (FilterBox) so downstream math stays NaN-free
        out_score = jnp.where(jnp.isfinite(top_score[picked]),
                              top_score[picked], -1.0)
        return top_boxes[picked], out_score

    all_boxes, all_scores = jax.vmap(one_sample)(
        cls_prob.astype(jnp.float32), bbox_pred.astype(jnp.float32),
        im_info.astype(jnp.float32))
    batch_idx = jnp.repeat(jnp.arange(B, dtype=jnp.float32), post_n)
    rois = jnp.concatenate([batch_idx[:, None],
                            all_boxes.reshape(-1, 4)], axis=1)
    if output_score:
        return rois, all_scores.reshape(-1, 1)
    return rois


@register("box_iou", aliases=("_contrib_box_iou",), differentiable=False)
def box_iou(lhs, rhs, format="corner"):
    """ref: src/operator/contrib/bounding_box.cc BoxIOU — pairwise IOU
    of (..., N, 4) x (..., M, 4) boxes."""
    if format == "center":
        def to_corner(b):
            cx, cy, w, h = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
            return jnp.stack([cx - w / 2, cy - h / 2,
                              cx + w / 2, cy + h / 2], axis=-1)
        lhs, rhs = to_corner(lhs), to_corner(rhs)
    elif format != "corner":
        raise ValueError("format must be 'corner' or 'center'")
    l2 = lhs.reshape(-1, 4)
    r2 = rhs.reshape(-1, 4)
    iou = _iou_matrix(l2, r2)
    return iou.reshape(lhs.shape[:-1] + rhs.shape[:-1])


@register("box_nms", aliases=("_contrib_box_nms",), differentiable=False)
def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1,
            force_suppress=False, in_format="corner",
            out_format="corner"):
    """ref: bounding_box.cc BoxNMS — greedy NMS over (B, N, K) rows;
    suppressed rows have every element set to -1, survivors are sorted
    by descending score (the reference's output contract)."""
    if in_format != "corner" or out_format != "corner":
        raise ValueError("only corner box format is supported")
    squeeze = data.ndim == 2
    if squeeze:
        data = data[None]
    B, N, K = data.shape

    if K < coord_start + 4:
        raise ValueError("box_nms rows have %d elements; coord_start=%d "
                         "needs at least %d" % (K, coord_start,
                                                coord_start + 4))

    def one(batch):
        score = batch[:, score_index]
        boxes = batch[:, coord_start:coord_start + 4]
        keep = score > valid_thresh
        order = jnp.argsort(-jnp.where(keep, score, -jnp.inf))
        # reference topk semantics: only the top-k ranked boxes ACT as
        # suppressors; beyond-topk boxes survive unless suppressed
        rank_gate = (jnp.arange(N) < topk) if topk > 0 else None
        cls_ids = batch[:, id_index] \
            if (id_index >= 0 and not force_suppress) else None
        alive = _greedy_nms(boxes, order, keep, overlap_thresh,
                            class_ids=cls_ids, rank_gate=rank_gate)
        final = alive & keep
        out = jnp.where(final[:, None], batch, -1.0)
        rank = jnp.argsort(-jnp.where(final, score, -jnp.inf))
        return out[rank]

    out = jax.vmap(one)(data.astype(jnp.float32))
    return out[0] if squeeze else out


@register("ROIAlign", aliases=("_contrib_ROIAlign",))
def roi_align(data, rois, pooled_size=(1, 1), spatial_scale=1.0,
              sample_ratio=-1, position_sensitive=False, aligned=False):
    """ref: src/operator/contrib/roi_align.cc (Mask R-CNN pooling):
    average of bilinear samples on a regular grid per output bin —
    differentiable in `data`, unlike ROIPooling's hard max.

    data: (N, C, H, W); rois: (R, 5) [batch_idx, x1, y1, x2, y2] in
    image coordinates. sample_ratio > 0 fixes the per-bin-axis sample
    count; <= 0 uses the reference's ADAPTIVE ceil(roi_size/pooled_size)
    per ROI — realized under static shapes by sampling a static-bound
    grid (bounded by the feature-map/pooled ratio, capped at 8 axes
    samples) and mask-averaging only each ROI's own count, so the
    numerics match the reference exactly for ROIs up to 8x the bin grid
    and clamp to 8 beyond. position_sensitive is not supported.
    """
    if position_sensitive:
        raise ValueError("position_sensitive ROIAlign is not supported")
    import math as _math

    ph, pw = pooled_size
    n, c, h, w = data.shape
    adaptive = sample_ratio <= 0
    ns = int(sample_ratio) if not adaptive else int(
        min(8, max(1, _math.ceil(h / ph), _math.ceil(w / pw))))
    offset = 0.5 if aligned else 0.0

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1 = roi[1] * spatial_scale - offset
        y1 = roi[2] * spatial_scale - offset
        x2 = roi[3] * spatial_scale - offset
        y2 = roi[4] * spatial_scale - offset
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        bin_w = rw / pw
        bin_h = rh / ph
        if adaptive:  # ceil(bin size) samples, per ROI (roi_align.cc)
            ns_h = jnp.clip(jnp.ceil(rh / ph), 1.0, float(ns))
            ns_w = jnp.clip(jnp.ceil(rw / pw), 1.0, float(ns))
        else:
            ns_h = ns_w = jnp.float32(ns)
        # sample grid: ns x ns points per bin; rows/cols past the ROI's
        # own (ns_h, ns_w) count are masked out of the average
        iy = jnp.arange(ph, dtype=jnp.float32)
        ix = jnp.arange(pw, dtype=jnp.float32)
        sy = jnp.arange(ns, dtype=jnp.float32)
        gy = (y1 + iy[:, None] * bin_h
              + (sy[None, :] + 0.5) * bin_h / ns_h)  # (ph, ns)
        gx = (x1 + ix[:, None] * bin_w
              + (sy[None, :] + 0.5) * bin_w / ns_w)  # (pw, ns)
        yy = gy.reshape(-1)  # (ph*ns,)
        xx = gx.reshape(-1)  # (pw*ns,)
        # reference bilinear_interpolate: samples beyond [-1, size] are
        # exactly zero (roi_align.cc); inside, coords clamp to the border
        oob_y = (yy < -1.0) | (yy > h)
        oob_x = (xx < -1.0) | (xx > w)
        yy = jnp.clip(yy, 0.0, h - 1.0)
        xx = jnp.clip(xx, 0.0, w - 1.0)
        y0f = jnp.floor(yy)
        x0f = jnp.floor(xx)
        y0 = y0f.astype(jnp.int32)
        x0 = x0f.astype(jnp.int32)
        y1i = jnp.minimum(y0 + 1, h - 1)
        x1i = jnp.minimum(x0 + 1, w - 1)
        wy = jnp.clip(yy - y0f, 0.0, 1.0)
        wx = jnp.clip(xx - x0f, 0.0, 1.0)
        fmap = data[b]  # (C, H, W)
        # gather the 4 corners for the full (ph*ns, pw*ns) grid
        v00 = fmap[:, y0[:, None], x0[None, :]]
        v01 = fmap[:, y0[:, None], x1i[None, :]]
        v10 = fmap[:, y1i[:, None], x0[None, :]]
        v11 = fmap[:, y1i[:, None], x1i[None, :]]
        top = v00 * (1 - wx)[None, None, :] + v01 * wx[None, None, :]
        bot = v10 * (1 - wx)[None, None, :] + v11 * wx[None, None, :]
        vals = top * (1 - wy)[None, :, None] + bot * wy[None, :, None]
        zero = oob_y[None, :, None] | oob_x[None, None, :]
        vals = jnp.where(zero, 0.0, vals)
        # average each ROI's own (ns_h x ns_w) samples inside each bin
        vals = vals.reshape(c, ph, ns, pw, ns)
        my = (sy < ns_h).astype(vals.dtype)  # (ns,)
        mw = (sy < ns_w).astype(vals.dtype)
        wgt = my[None, None, :, None, None] * mw[None, None, None, None, :]
        return (vals * wgt).sum(axis=(2, 4)) / (ns_h * ns_w)  # (C, ph, pw)

    return jax.vmap(one_roi)(rois.astype(jnp.float32))


@register("DeformableConvolution",
          aliases=("_contrib_DeformableConvolution",))
def deformable_convolution(data, offset, weight, bias=None, kernel=(3, 3),
                           stride=(1, 1), dilate=(1, 1), pad=(0, 0),
                           num_filter=0, num_group=1,
                           num_deformable_group=1, no_bias=False,
                           workspace=None, layout=None):
    """ref: src/operator/contrib/deformable_convolution.cc (Deformable
    ConvNets v1): each kernel tap samples the input at its regular grid
    position PLUS a learned offset, via bilinear interpolation
    (out-of-image samples are zero, like the reference's im2col).

    data: (N, C, H, W); offset: (N, 2*G_d*kh*kw, Ho, Wo) with per-tap
    (dy, dx) pairs; weight: (F, C/num_group, kh, kw). Built as a
    gather-based im2col followed by one MXU matmul per group.
    """
    del num_filter, workspace
    if layout not in (None, "NCHW"):
        raise ValueError("DeformableConvolution supports NCHW only")
    kh, kw = kernel
    sh, sw = stride if stride else (1, 1)
    dh, dw = dilate if dilate else (1, 1)
    ph, pw = pad if pad else (0, 0)
    n, c, h, w = data.shape
    f = weight.shape[0]
    ho = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    wo = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    gd = num_deformable_group
    if c % num_group or f % num_group:
        raise ValueError("channels not divisible by num_group")
    if c % gd:
        raise ValueError("channels not divisible by num_deformable_group")

    # base sampling grid per output position and tap (pixel coords)
    oy = jnp.arange(ho) * sh - ph
    ox = jnp.arange(wo) * sw - pw
    ky = jnp.arange(kh) * dh
    kx = jnp.arange(kw) * dw
    base_y = oy[:, None, None, None] + ky[None, None, :, None]  # ho,1,kh,1
    base_x = ox[None, :, None, None] + kx[None, None, None, :]  # 1,wo,1,kw
    ct = jnp.promote_types(data.dtype, jnp.float32)
    base_y = jnp.broadcast_to(base_y, (ho, wo, kh, kw)).astype(ct)
    base_x = jnp.broadcast_to(base_x, (ho, wo, kh, kw)).astype(ct)

    def one_sample(x, off):
        # off: (2*gd*kh*kw, ho, wo) -> (gd, kh, kw, 2, ho, wo)
        off = off.reshape(gd, kh, kw, 2, ho, wo)

        def sample_group(xg, og):
            # xg: (c/gd, H, W); og: (kh, kw, 2, ho, wo)
            sy = base_y + og[:, :, 0].transpose(2, 3, 0, 1)  # ho,wo,kh,kw
            sx = base_x + og[:, :, 1].transpose(2, 3, 0, 1)
            oob = (sy <= -1.0) | (sy >= h) | (sx <= -1.0) | (sx >= w)
            y0 = jnp.floor(sy)
            x0 = jnp.floor(sx)
            wy = sy - y0
            wx = sx - x0
            y0i = y0.astype(jnp.int32)
            x0i = x0.astype(jnp.int32)
            y1i = y0i + 1
            x1i = x0i + 1
            # reference deformable_im2col bilinear: corners OUTSIDE the
            # image contribute zero (implicit zero padding) — this is
            # what makes zero offsets + pad reproduce plain Convolution
            vy0 = (y0i >= 0) & (y0i <= h - 1)
            vy1 = (y1i >= 0) & (y1i <= h - 1)
            vx0 = (x0i >= 0) & (x0i <= w - 1)
            vx1 = (x1i >= 0) & (x1i <= w - 1)
            y0c = jnp.clip(y0i, 0, h - 1)
            y1c = jnp.clip(y1i, 0, h - 1)
            x0c = jnp.clip(x0i, 0, w - 1)
            x1c = jnp.clip(x1i, 0, w - 1)
            v00 = jnp.where((vy0 & vx0)[None], xg[:, y0c, x0c], 0.0)
            v01 = jnp.where((vy0 & vx1)[None], xg[:, y0c, x1c], 0.0)
            v10 = jnp.where((vy1 & vx0)[None], xg[:, y1c, x0c], 0.0)
            v11 = jnp.where((vy1 & vx1)[None], xg[:, y1c, x1c], 0.0)
            val = (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
                   + v10 * wy * (1 - wx) + v11 * wy * wx)
            return jnp.where(oob[None], 0.0, val)  # (c/gd, ho, wo, kh, kw)

        cols = jax.vmap(sample_group)(
            x.reshape(gd, c // gd, h, w), off)  # (gd, c/gd, ho,wo,kh,kw)
        return cols.reshape(c, ho, wo, kh, kw)

    cols = jax.vmap(one_sample)(data.astype(ct), offset.astype(ct))
    # (N, C, ho, wo, kh, kw) -> grouped matmul with (F, C/g, kh, kw)
    cg = c // num_group
    fg = f // num_group
    cols = cols.reshape(n, num_group, cg, ho, wo, kh, kw)
    wg = weight.astype(ct).reshape(num_group, fg, cg, kh, kw)
    out = jnp.einsum("ngchwyx,gfcyx->ngfhw", cols, wg)
    out = out.reshape(n, f, ho, wo).astype(data.dtype)
    if not no_bias and bias is not None:
        out = out + bias.reshape(1, f, 1, 1).astype(out.dtype)
    return out
