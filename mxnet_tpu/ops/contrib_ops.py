"""Contrib op family (ref: src/operator/contrib/* — "port on demand" per
SURVEY §2.2): FFT, index_copy/index_add, count_sketch, boolean_mask, and
the SSD MultiBoxPrior anchor generator.

Registered under both the bare name and the reference's ``_contrib_``
prefix so nd/sym namespaces resolve either spelling.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register


@register("fft", aliases=("_contrib_fft",))
def fft(data, compute_size=None):
    """ref: src/operator/contrib/fft.cc — FFT along the last axis;
    output interleaves (real, imag) so the last dim doubles."""
    del compute_size
    ct = jnp.promote_types(data.dtype, jnp.float32)
    out = jnp.fft.fft(data.astype(ct), axis=-1)
    inter = jnp.stack([out.real, out.imag], axis=-1)
    return inter.reshape(data.shape[:-1] + (2 * data.shape[-1],)).astype(
        data.dtype)


@register("ifft", aliases=("_contrib_ifft",))
def ifft(data, compute_size=None):
    """ref: contrib/fft.cc IFFT — input interleaves (real, imag); the
    reference does NOT normalize by n (matches its docs)."""
    del compute_size
    n = data.shape[-1] // 2
    ct = jnp.promote_types(data.dtype, jnp.float32)
    x = data.astype(ct).reshape(data.shape[:-1] + (n, 2))
    comp = jax.lax.complex(x[..., 0], x[..., 1])
    out = jnp.fft.ifft(comp, axis=-1).real * n
    return out.astype(data.dtype)


@register("index_copy", aliases=("_contrib_index_copy",))
def index_copy(old, index, new):
    """ref: contrib/index_copy.cc — copy rows of `new` into `old` at
    `index` positions along axis 0."""
    return old.at[index.astype(jnp.int32)].set(new)


@register("index_add", aliases=("_contrib_index_add",))
def index_add(data, index, value):
    """Scatter-add rows (companion of index_copy)."""
    return data.at[index.astype(jnp.int32)].add(value)


@register("count_sketch", aliases=("_contrib_count_sketch",))
def count_sketch(data, h, s, out_dim=0):
    """ref: contrib/count_sketch.cc — random-hash feature sketch:
    out[n, h[i]] += s[i] * data[n, i] with sign hashes s in {-1, +1}."""
    if int(out_dim) <= 0:
        raise ValueError("count_sketch requires out_dim > 0 (got %r); the "
                         "reference treats it as a required parameter"
                         % (out_dim,))
    n, d = data.shape
    idx = h.astype(jnp.int32).reshape(-1)[:d]
    sign = s.astype(data.dtype).reshape(-1)[:d]
    out = jnp.zeros((n, int(out_dim)), data.dtype)
    return out.at[:, idx].add(data * sign[None, :])


@register("boolean_mask", aliases=("_contrib_boolean_mask",),
          differentiable=False)
def boolean_mask(data, index, axis=0):
    """ref: contrib/boolean_mask.cc. Output shape is data-dependent —
    usable eagerly; inside jit/symbol tracing the dynamic shape is
    rejected by XLA (same class of limitation as the reference's
    shape-inference pass, which special-cases this op)."""
    mask = index.astype(bool)
    keep = jnp.nonzero(mask)[0]
    return jnp.take(data, keep, axis=axis)


@register("MultiBoxPrior", aliases=("_contrib_MultiBoxPrior",),
          differentiable=False)
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """ref: src/operator/contrib/multibox_prior.cc — SSD anchor boxes.

    data: (N, C, H, W) feature map (only H/W used). Returns
    (1, H*W*(len(sizes)+len(ratios)-1), 4) corner-format anchors in
    [0, 1] coordinates, matching the reference's anchor ordering: for
    each pixel, every size with ratios[0] first, then the remaining
    ratios with sizes[0].
    """
    h, w = data.shape[2], data.shape[3]
    sizes = tuple(sizes)
    ratios = tuple(ratios)
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    cy = (np.arange(h) + offsets[0]) * step_y
    cx = (np.arange(w) + offsets[1]) * step_x
    # anchor (width, height) list per the reference's enumeration:
    # sizes-first with ratios[0], then remaining ratios with sizes[0]
    whs = []
    r0 = np.sqrt(ratios[0])
    for s in sizes:
        whs.append((s * r0, s / r0))
    for r in ratios[1:]:
        sr = np.sqrt(r)
        whs.append((sizes[0] * sr, sizes[0] / sr))
    whs = np.asarray(whs)  # (A, 2)
    gy, gx = np.meshgrid(cy, cx, indexing="ij")
    centers = np.stack([gx.ravel(), gy.ravel()], axis=1)  # (HW, 2) x,y
    half = whs / 2.0
    mins = centers[:, None, :] - half[None, :, :]
    maxs = centers[:, None, :] + half[None, :, :]
    anchors = np.concatenate([mins, maxs], axis=2).reshape(-1, 4)
    if clip:
        anchors = np.clip(anchors, 0.0, 1.0)
    return jnp.asarray(anchors[None], jnp.float32)
