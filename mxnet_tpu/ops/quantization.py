"""int8 quantization op family (ref: src/operator/quantization/* —
quantize_v2, dequantize, requantize, quantized_conv, quantized_fully_
connected, quantized_pooling, quantized_flatten).

TPU-native design: symmetric signed-int8 (zero_point 0) everywhere — the
MXU consumes s8×s8→s32 natively (``preferred_element_type=int32``), and
symmetric quantization keeps the conv/fc epilogue a pure rescale that XLA
fuses into the matmul. Quantized tensors travel as the reference's
``(q, min_range, max_range)`` triple; the float range maps linearly onto
the integer range of q's dtype (±127 for int8, ±int32_max for the conv/fc
accumulator), so ``scale(q) = int_max(dtype) / max(|min|, |max|)``.

The graph surgery that strings these ops together lives in
``contrib/quantization.py — quantize_model``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register
from .nn import convolution, pooling

_INT8_MAX = 127.0
_INT32_MAX = float(2 ** 31 - 1)


def _amax(min_range, max_range):
    """Symmetric float range from a (min, max) calibration pair."""
    return jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))


def _scale8(min_range, max_range):
    return _INT8_MAX / jnp.maximum(_amax(min_range, max_range), 1e-30)


@register("quantize_v2", aliases=("_contrib_quantize_v2",),
          num_outputs=3, differentiable=False)
def quantize_v2(data, min_calib_range=None, max_calib_range=None,
                out_type="int8"):
    """f32 → (int8, min, max) (ref: quantization/quantize_v2-inl.h).
    Calibrated ranges come in as attrs; otherwise the range is computed
    from the data (dynamic quantization)."""
    if out_type not in ("int8", "auto"):
        raise ValueError("TPU build quantizes to signed int8 only "
                         "(got out_type=%r)" % (out_type,))
    if min_calib_range is not None and max_calib_range is not None:
        amax = jnp.maximum(abs(float(min_calib_range)),
                           abs(float(max_calib_range)))
        amax = jnp.asarray(amax, jnp.float32)
    else:
        amax = jnp.max(jnp.abs(data)).astype(jnp.float32)
    scale = _INT8_MAX / jnp.maximum(amax, 1e-30)
    q = jnp.clip(jnp.round(data.astype(jnp.float32) * scale),
                 -_INT8_MAX, _INT8_MAX).astype(jnp.int8)
    return q, -amax, amax


@register("dequantize", aliases=("_contrib_dequantize",),
          differentiable=False)
def dequantize(data, min_range, max_range, out_type="float32"):
    """(int8|int32, min, max) → f32 (ref: quantization/dequantize-inl.h)."""
    del out_type
    int_max = _INT8_MAX if data.dtype == jnp.int8 else _INT32_MAX
    amax = _amax(min_range, max_range)
    return data.astype(jnp.float32) * (amax / int_max)


@register("requantize", aliases=("_contrib_requantize",),
          num_outputs=3, differentiable=False)
def requantize(data, min_range, max_range, min_calib_range=None,
               max_calib_range=None):
    """int32 accumulator → int8 (ref: quantization/requantize-inl.h).
    With calibrated ranges the rescale factor is a compile-time constant;
    without, the range is taken from the actual int32 values (dynamic)."""
    in_amax = _amax(min_range, max_range)
    in_scale = _INT32_MAX / jnp.maximum(in_amax, 1e-30)
    if min_calib_range is not None and max_calib_range is not None:
        out_amax = jnp.asarray(
            max(abs(float(min_calib_range)), abs(float(max_calib_range))),
            jnp.float32)
    else:
        out_amax = jnp.max(jnp.abs(data)).astype(jnp.float32) / in_scale
    out_scale = _INT8_MAX / jnp.maximum(out_amax, 1e-30)
    q = jnp.clip(jnp.round(data.astype(jnp.float32) * (out_scale / in_scale)),
                 -_INT8_MAX, _INT8_MAX).astype(jnp.int8)
    return q, -out_amax, out_amax


def _accum_triple(out_i32, scale_prod):
    """(int32 accum, its float range) — the int32 triple convention:
    float = q / (int32_max / amax) with amax = int32_max / scale_prod."""
    amax = _INT32_MAX / scale_prod
    return out_i32, -amax, amax


@register("quantized_conv", aliases=("_contrib_quantized_conv",),
          num_outputs=3, differentiable=False)
def quantized_conv(data, weight, bias, min_data, max_data, min_weight,
                   max_weight, min_bias=None, max_bias=None, kernel=(),
                   stride=(), dilate=(), pad=(), num_filter=0, num_group=1,
                   no_bias=False, layout=None):
    """s8×s8→s32 convolution (ref: quantization/quantized_conv.cc).
    Inference-only, like the reference (no gradient). The f32 bias is
    folded into the int32 accumulator at the combined input scale."""
    from .nn import _conv_dn
    del num_filter
    sd = _scale8(min_data, max_data)
    sw = _scale8(min_weight, max_weight)
    nd_ = len(kernel)
    stride = tuple(stride) if stride else (1,) * nd_
    dilate = tuple(dilate) if dilate else (1,) * nd_
    pad = tuple(pad) if pad else (0,) * nd_
    dn = _conv_dn(layout, nd_)
    # s8×s8 with an int32 accumulator — THE reason this op exists (a
    # plain int8 conv would wrap at ±128)
    out = jax.lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    if not no_bias and bias is not None:
        del min_bias, max_bias  # bias arrives f32; scale is exact
        c_ax = dn[2].index("C")
        shape = [1] * out.ndim
        shape[c_ax] = bias.shape[0]
        b_i32 = jnp.round(bias.astype(jnp.float32) * (sd * sw)) \
            .astype(jnp.int32)
        out = out + b_i32.reshape(shape)
    return _accum_triple(out, sd * sw)


@register("quantized_fully_connected",
          aliases=("_contrib_quantized_fully_connected",),
          num_outputs=3, differentiable=False)
def quantized_fully_connected(data, weight, bias, min_data, max_data,
                              min_weight, max_weight, min_bias=None,
                              max_bias=None, num_hidden=None, no_bias=False,
                              flatten=True):
    """s8×s8→s32 matmul (ref: quantization/quantized_fully_connected.cc)."""
    del num_hidden, min_bias, max_bias
    sd = _scale8(min_data, max_data)
    sw = _scale8(min_weight, max_weight)
    x = data.reshape((data.shape[0], -1)) if flatten and data.ndim > 2 \
        else data
    out = jax.lax.dot_general(
        x, weight, (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    if not no_bias and bias is not None:
        b_i32 = jnp.round(bias.astype(jnp.float32) * (sd * sw)) \
            .astype(jnp.int32)
        out = out + b_i32
    return _accum_triple(out, sd * sw)


@register("quantized_pooling", aliases=("_contrib_quantized_pooling",),
          num_outputs=3, differentiable=False)
def quantized_pooling(data, min_data, max_data, kernel=(), pool_type="max",
                      global_pool=False, stride=(), pad=(),
                      pooling_convention="valid", count_include_pad=True,
                      layout=None):
    """Pooling directly on int8 (ref: quantization/quantized_pooling.cc).
    Max pool is exact; avg pool accumulates in f32 and re-rounds to the
    same scale (range is preserved either way, so the triple passes
    through)."""
    if pool_type == "max":
        out = pooling(data, kernel=kernel, pool_type="max",
                      global_pool=global_pool, stride=stride, pad=pad,
                      pooling_convention=pooling_convention, layout=layout)
    else:
        avg = pooling(data.astype(jnp.float32), kernel=kernel,
                      pool_type=pool_type, global_pool=global_pool,
                      stride=stride, pad=pad,
                      pooling_convention=pooling_convention,
                      count_include_pad=count_include_pad, layout=layout)
        out = jnp.clip(jnp.round(avg), -_INT8_MAX, _INT8_MAX) \
            .astype(jnp.int8)
    return out, min_data, max_data


@register("quantized_flatten", aliases=("_contrib_quantized_flatten",),
          num_outputs=3, differentiable=False)
def quantized_flatten(data, min_data, max_data):
    """ref: quantization/quantized_flatten-inl.h."""
    return (data.reshape((data.shape[0], -1)), min_data, max_data)


# ---------------------------------------------------------------------------
# weight-only quantization (serving decode matmuls; no reference-op
# heritage — this is the serving-economics half of the int8 family)
# ---------------------------------------------------------------------------
def quantize_rowwise(w):
    """f32 (k, n) weight -> (int8 q, (n,) f32 amax): symmetric signed
    int8 per OUTPUT column, ``scale = 127 / amax`` — finer than the
    tensor-wide (min, max) triple because decode matmul error is
    dominated by the widest column. Zero columns get amax 0 and
    dequantize to exact zeros."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0)
    scale = _INT8_MAX / jnp.maximum(amax, 1e-30)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) * scale[None, :]),
                 -_INT8_MAX, _INT8_MAX).astype(jnp.int8)
    return q, amax


def woq_matmul(x, qw, amax):
    """Weight-only-quantized matmul: activations stay float, the int8
    weight dequantizes AT the matmul (XLA folds the per-column rescale
    into the epilogue, and on HBM-bound decode shapes the win is the
    4x smaller weight read — the same bytes argument as the quantized
    KV pages). Numerics == ``x @ dequantize(qw)`` exactly."""
    w = qw.astype(jnp.float32) * (amax * (1.0 / _INT8_MAX))[None, :]
    return x @ w


@register("quantized_act", aliases=("_contrib_quantized_act",),
          num_outputs=3, differentiable=False)
def quantized_act(data, min_data, max_data, act_type="relu"):
    """relu directly on int8 (ref: quantized_activation in the oneDNN
    path). Exact: relu commutes with a positive scale and fixes 0, so
    relu(dequantize(q)) == dequantize(max(q, 0)) and the range triple
    passes through unchanged."""
    if act_type != "relu":
        raise ValueError("only relu stays exact on the int8 grid "
                         "(got act_type=%r)" % (act_type,))
    return jnp.maximum(data, 0), min_data, max_data
