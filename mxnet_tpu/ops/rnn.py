"""Fused multi-layer RNN op (ref: src/operator/rnn.cc, cudnn_rnn-inl.h).

The reference runs the whole multi-layer LSTM/GRU/RNN over the sequence in
one cuDNN call with a packed flat weight vector. TPU-native equivalent: one
``lax.scan`` per layer inside a single traced program — XLA fuses the cell,
keeps weights resident, and the scan compiles to a tight loop feeding the
MXU with (B, gates*H) matmuls.

Packed layout (cuDNN-compatible ordering, gate order LSTM=[i,f,g,o],
GRU=[r,z,n]): for each layer, for each direction: W_i2h(G*H, in), then
W_h2h(G*H, H); after ALL weights, for each layer/direction: b_i2h(G*H),
b_h2h(G*H).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_param_size(mode, input_size, state_size, num_layers=1,
                   bidirectional=False):
    g = _GATES[mode]
    d = 2 if bidirectional else 1
    h = state_size
    total = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else h * d
        total += d * (g * h * in_sz + g * h * h)  # weights
        total += d * (2 * g * h)  # biases
    return total


def _unpack(params, mode, input_size, state_size, num_layers, bidirectional):
    g = _GATES[mode]
    d = 2 if bidirectional else 1
    h = state_size
    ws, bs = [], []
    off = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else h * d
        lw = []
        for _ in range(d):
            wi = params[off:off + g * h * in_sz].reshape(g * h, in_sz)
            off += g * h * in_sz
            wh = params[off:off + g * h * h].reshape(g * h, h)
            off += g * h * h
            lw.append((wi, wh))
        ws.append(lw)
    for layer in range(num_layers):
        lb = []
        for _ in range(d):
            bi = params[off:off + g * h]
            off += g * h
            bh = params[off:off + g * h]
            off += g * h
            lb.append((bi, bh))
        bs.append(lb)
    return ws, bs


def _cell_step(mode, h_size):
    if mode == "lstm":
        def step(carry, gates_x, wh, bh):
            h, c = carry
            gates = gates_x + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c2 = f * c + i * g
            h2 = o * jnp.tanh(c2)
            return (h2, c2), h2
    elif mode == "gru":
        def step(carry, gates_x, wh, bh):
            (h,) = carry
            gh = h @ wh.T + bh
            xr, xz, xn = jnp.split(gates_x, 3, axis=-1)
            hr, hz, hn = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            h2 = (1 - z) * n + z * h
            return (h2,), h2
    else:
        act = jnp.tanh if mode == "rnn_tanh" else (lambda x: jnp.maximum(x, 0))

        def step(carry, gates_x, wh, bh):
            (h,) = carry
            h2 = act(gates_x + h @ wh.T + bh)
            return (h2,), h2
    return step


def _scan_unroll(T):
    """Unroll factor for the recurrent scan. Short sequences unroll fully:
    each residual scan iteration is a while-loop step, and on the axon PJRT
    tunnel every loop iteration costs ~3.4ms of launch overhead (measured —
    a T=35 LSTM spent 112ms/step on loop overhead alone). Long sequences
    unroll partially so compile time stays bounded. MXT_RNN_UNROLL
    overrides (0 = no unrolling)."""
    from .. import config as _config

    override = _config.get("MXT_RNN_UNROLL")
    if override is not None:
        return max(1, int(override)) if int(override) > 0 else 1
    if T <= 128:
        return T
    return 16


def _run_layer(x, mode, wi, wh, bi, bh, h0, c0, reverse=False):
    """x: (T, B, in) → (T, B, H). Pre-computes the input projection for the
    whole sequence as ONE big matmul (MXU-friendly), scanning only the
    recurrent part."""
    gates_x = jnp.einsum("tbi,gi->tbg", x, wi) + bi  # (T, B, G*H)
    step = _cell_step(mode, wh.shape[1])
    carry = (h0, c0) if mode == "lstm" else (h0,)

    def body(carry, gx):
        return step(carry, gx, wh, bh)

    carry, outs = jax.lax.scan(body, carry, gates_x, reverse=reverse,
                               unroll=_scan_unroll(x.shape[0]))
    return carry, outs


def _wavefront_lstm(x, ws, bs, state, state_cell, num_layers):
    """Multi-layer LSTM as a diagonal WAVEFRONT (MXT_RNN_WAVEFRONT=1).

    The standard path runs layer scans sequentially: the serial chain is
    num_layers * T small (B, H)@(H, 4H) matmuls, each latency-bound at
    small batch (PERF.md round-4 LSTM ceiling analysis). At diagonal step
    d, layer l processes t = d - l, so every active layer's recurrent
    gemm is INDEPENDENT — they batch into one (A, B, 2H)@(A, 2H, 4H)
    einsum per diagonal. Chain length drops from L*T to T + L - 1 at the
    cost of zero-padding layer 0's unused input half (latency-bound
    segments, so the padded FLOPs are ~free).

    Unidirectional, no inter-layer dropout, T small enough to unroll —
    the caller gates on that. Numerically equivalent to the sequential
    path up to FP reduction order (the fused [h,x]@[Wh;Wi] contraction
    sums over one axis); pinned at rtol 1e-6 by the
    tests/test_gluon_rnn.py equivalence test."""
    T, B, _ = x.shape
    L = num_layers
    H = ws[0][0][1].shape[1]

    # layer 0's input projection hoists into one big gemm, as before
    wi0, wh0 = ws[0][0]
    bi0, bh0 = bs[0][0]
    gates_x0 = jnp.einsum("tbi,gi->tbg", x, wi0) + bi0 + bh0  # (T, B, 4H)

    # per-layer stacked weights: operand is [h_prev, x_in] (B, 2H) ->
    # weight [Wh ; Wi] (4H, 2H); layer 0's x half is zero (its x term is
    # the precomputed gates_x0)
    wcat, bias = [], []
    for l in range(L):
        wi, wh = ws[l][0]
        bi, bh = bs[l][0]
        if l == 0:
            wcat.append(jnp.concatenate(
                [wh0, jnp.zeros((wh0.shape[0], H), wh0.dtype)], axis=1))
            bias.append(jnp.zeros_like(bi0))  # biases live in gates_x0
        else:
            wcat.append(jnp.concatenate([wh, wi], axis=1))
            bias.append(bi + bh)
    wcat = jnp.stack(wcat)          # (L, 4H, 2H)
    bias = jnp.stack(bias)          # (L, 4H)

    h = [state[l] for l in range(L)]
    c = [state_cell[l] for l in range(L)]
    outs = []
    for d in range(T + L - 1):
        lo, hi = max(0, d - T + 1), min(L - 1, d)
        # layer l's input at this diagonal is layer l-1's output from
        # the PREVIOUS diagonal — which is exactly h[l-1] right now
        ops = jnp.stack([
            jnp.concatenate(
                [h[l], h[l - 1] if l > 0 else jnp.zeros_like(h[0])],
                axis=-1)
            for l in range(lo, hi + 1)])             # (A, B, 2H)
        gates = jnp.einsum("abe,afe->abf", ops, wcat[lo:hi + 1]) \
            + bias[lo:hi + 1][:, None, :]            # (A, B, 4H)
        if lo == 0:  # layer 0 active at t = d: add its hoisted x gates
            gates = gates.at[0].add(gates_x0[d])
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = (jax.nn.sigmoid(i), jax.nn.sigmoid(f),
                   jax.nn.sigmoid(o))
        g = jnp.tanh(g)
        cs = jnp.stack([c[l] for l in range(lo, hi + 1)])
        c2 = f * cs + i * g
        h2 = o * jnp.tanh(c2)
        for a, l in enumerate(range(lo, hi + 1)):
            h[l], c[l] = h2[a], c2[a]
        if hi == L - 1:  # final layer produced y_{L-1, d-(L-1)}
            outs.append(h[L - 1])
    out = jnp.stack(outs)                            # (T, B, H)
    return out, jnp.stack(h), jnp.stack(c)


@register("RNN", num_outputs=3)
def rnn_op(data, parameters, state, state_cell=None, mode="lstm",
           state_size=0, num_layers=1, bidirectional=False, p=0.0,
           state_outputs=False, projection_size=None, lstm_state_clip_min=None,
           lstm_state_clip_max=None, lstm_state_clip_nan=False,
           use_sequence_length=False, train_mode=False):
    """Fused RNN (ref: src/operator/rnn.cc — RNNParam). data is (T, B, I);
    state is (L*D, B, H). Returns (out, h_n[, c_n])."""
    del projection_size, lstm_state_clip_min, lstm_state_clip_max
    del lstm_state_clip_nan, use_sequence_length
    d = 2 if bidirectional else 1
    h = state_size
    input_size = data.shape[2]
    ws, bs = _unpack(parameters, mode, input_size, h, num_layers, bidirectional)

    from .. import config as _config

    if (mode == "lstm" and d == 1 and num_layers >= 2
            and data.shape[0] <= 128 and (p == 0 or not train_mode)
            and _config.get("MXT_RNN_WAVEFRONT")):
        return _wavefront_lstm(data, ws, bs, state, state_cell, num_layers)

    x = data
    h_finals, c_finals = [], []
    for layer in range(num_layers):
        outs_dir = []
        for di in range(d):
            idx = layer * d + di
            h0 = state[idx]
            c0 = state_cell[idx] if mode == "lstm" else None
            wi, wh = ws[layer][di]
            bi, bh = bs[layer][di]
            carry, outs = _run_layer(
                x, mode, wi, wh, bi, bh, h0, c0, reverse=(di == 1)
            )
            outs_dir.append(outs)
            h_finals.append(carry[0])
            if mode == "lstm":
                c_finals.append(carry[1])
        x = outs_dir[0] if d == 1 else jnp.concatenate(outs_dir, axis=-1)
        if p > 0 and train_mode and layer < num_layers - 1:
            from .. import random as _random

            keep = 1.0 - p
            mask = jax.random.bernoulli(_random.new_key(), keep, x.shape)
            x = jnp.where(mask, x / keep, 0.0).astype(x.dtype)

    h_n = jnp.stack(h_finals, axis=0)
    if mode == "lstm":
        return (x, h_n, jnp.stack(c_finals, axis=0))
    return (x, h_n)
