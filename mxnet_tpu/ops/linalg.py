"""Linear-algebra operators — the ``linalg_*`` family
(ref: src/operator/tensor/la_op.{cc,h} — gemm/potrf/trsm/… backed by
cuBLAS/cuSOLVER; here each lowers to the XLA linalg primitives, which
map Cholesky/triangular-solve onto the MXU-friendly blocked algorithms).

All ops operate on the last two axes and broadcast over leading batch
axes, like the reference. Differentiability comes from jax's built-in
rules (jnp.linalg / lax.linalg are fully differentiable).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

__all__ = []


def _t(x):
    return jnp.swapaxes(x, -1, -2)


@register("linalg_gemm", aliases=("_linalg_gemm",))
def linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0,
                beta=1.0, axis=-2):
    """C <- alpha * op(A) op(B) + beta * C (ref: la_op — linalg_gemm)."""
    del axis
    a = _t(A) if transpose_a else A
    b = _t(B) if transpose_b else B
    return alpha * jnp.matmul(a, b) + beta * C


@register("linalg_gemm2", aliases=("_linalg_gemm2",))
def linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0,
                 axis=-2):
    del axis
    a = _t(A) if transpose_a else A
    b = _t(B) if transpose_b else B
    return alpha * jnp.matmul(a, b)


@register("linalg_potrf", aliases=("_linalg_potrf",))
def linalg_potrf(A):
    """Cholesky factor L with A = L L^T (ref: la_op — linalg_potrf)."""
    return jnp.linalg.cholesky(A)


@register("linalg_potri", aliases=("_linalg_potri",))
def linalg_potri(A):
    """Inverse from a Cholesky factor: out = (L L^T)^-1 given L
    (ref: la_op — linalg_potri)."""
    eye = jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape)
    linv = jax.scipy.linalg.solve_triangular(A, eye, lower=True)
    return jnp.matmul(_t(linv), linv)


@register("linalg_trsm", aliases=("_linalg_trsm",))
def linalg_trsm(A, B, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    """Solve op(A) X = alpha B (or X op(A) = alpha B)
    (ref: la_op — linalg_trsm)."""
    b = alpha * B
    if rightside:
        # X op(A) = b  <=>  op(A)^T X^T = b^T
        sol = jax.scipy.linalg.solve_triangular(
            _t(A), _t(b), lower=not lower, trans=1 if transpose else 0)
        return _t(sol)
    return jax.scipy.linalg.solve_triangular(
        A, b, lower=lower, trans=1 if transpose else 0)


@register("linalg_trmm", aliases=("_linalg_trmm",))
def linalg_trmm(A, B, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    """Triangular matmul: out = alpha op(tri(A)) B (or B op(tri(A)))
    (ref: la_op — linalg_trmm)."""
    tri = jnp.tril(A) if lower else jnp.triu(A)
    op_a = _t(tri) if transpose else tri
    if rightside:
        return alpha * jnp.matmul(B, op_a)
    return alpha * jnp.matmul(op_a, B)


@register("linalg_syrk", aliases=("_linalg_syrk",))
def linalg_syrk(A, transpose=False, alpha=1.0):
    """Symmetric rank-k: alpha A A^T (or alpha A^T A)
    (ref: la_op — linalg_syrk)."""
    if transpose:
        return alpha * jnp.matmul(_t(A), A)
    return alpha * jnp.matmul(A, _t(A))


@register("linalg_makediag", aliases=("_linalg_makediag",))
def linalg_makediag(A, offset=0):
    """Vector(s) → diagonal matrix (ref: la_op — linalg_makediag)."""
    n = A.shape[-1] + abs(offset)
    base = jnp.zeros(A.shape[:-1] + (n, n), A.dtype)
    idx = jnp.arange(A.shape[-1])
    rows = idx if offset >= 0 else idx - offset
    cols = idx + offset if offset >= 0 else idx
    return base.at[..., rows, cols].set(A)


@register("linalg_extractdiag", aliases=("_linalg_extractdiag",))
def linalg_extractdiag(A, offset=0):
    return jnp.diagonal(A, offset=offset, axis1=-2, axis2=-1)


@register("linalg_maketrian", aliases=("_linalg_maketrian",))
def linalg_maketrian(A, offset=0, lower=True):
    """Packed vector → triangular matrix (ref: la_op — linalg_maketrian).
    Only offset=0 packing is supported (the common case)."""
    if offset != 0:
        raise NotImplementedError("linalg_maketrian supports offset=0")
    k = A.shape[-1]
    n = int((-1 + (1 + 8 * k) ** 0.5) / 2)
    rows, cols = jnp.tril_indices(n)
    if not lower:
        rows, cols = cols, rows
    out = jnp.zeros(A.shape[:-1] + (n, n), A.dtype)
    return out.at[..., rows, cols].set(A)


@register("linalg_extracttrian", aliases=("_linalg_extracttrian",))
def linalg_extracttrian(A, offset=0, lower=True):
    if offset != 0:
        raise NotImplementedError("linalg_extracttrian supports offset=0")
    n = A.shape[-1]
    rows, cols = jnp.tril_indices(n)
    if not lower:
        rows, cols = cols, rows
    return A[..., rows, cols]


@register("linalg_sumlogdiag", aliases=("_linalg_sumlogdiag",))
def linalg_sumlogdiag(A):
    """sum(log(diag(A))) per matrix (ref: la_op — linalg_sumlogdiag)."""
    return jnp.sum(jnp.log(jnp.diagonal(A, axis1=-2, axis2=-1)), axis=-1)


@register("linalg_det", aliases=("_linalg_det", "det"))
def linalg_det(A):
    return jnp.linalg.det(A)


@register("linalg_slogdet", aliases=("_linalg_slogdet", "slogdet"),
          num_outputs=2)
def linalg_slogdet(A):
    sign, logabs = jnp.linalg.slogdet(A)
    return sign, logabs


@register("linalg_inverse", aliases=("_linalg_inverse", "inverse"))
def linalg_inverse(A):
    return jnp.linalg.inv(A)
