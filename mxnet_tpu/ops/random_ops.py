"""Random sampling ops (ref: src/operator/random/sample_op.cc).

Backed by jax.random with keys drawn from the stateful facade in
mxnet_tpu.random — eager calls consume the global key; traced calls fold a
counter into the scope key (see random.key_scope).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register
from .. import random as _random
from ..base import get_dtype


def _dt(dtype):
    return get_dtype(dtype if dtype not in (None, "None") else "float32")


@register("_random_uniform", aliases=("uniform",), differentiable=False)
def random_uniform(low=0.0, high=1.0, shape=(), dtype=None, ctx=None):
    del ctx
    return jax.random.uniform(
        _random.new_key(), tuple(shape), _dt(dtype), minval=low, maxval=high
    )


@register("_random_normal", aliases=("normal",), differentiable=False)
def random_normal(loc=0.0, scale=1.0, shape=(), dtype=None, ctx=None):
    del ctx
    return loc + scale * jax.random.normal(_random.new_key(), tuple(shape), _dt(dtype))


@register("_random_gamma", differentiable=False)
def random_gamma(alpha=1.0, beta=1.0, shape=(), dtype=None, ctx=None):
    del ctx
    return beta * jax.random.gamma(_random.new_key(), alpha, tuple(shape), _dt(dtype))


@register("_random_exponential", differentiable=False)
def random_exponential(lam=1.0, shape=(), dtype=None, ctx=None):
    del ctx
    return jax.random.exponential(_random.new_key(), tuple(shape), _dt(dtype)) / lam


@register("_random_poisson", differentiable=False)
def random_poisson(lam=1.0, shape=(), dtype=None, ctx=None):
    del ctx
    return jax.random.poisson(_random.new_key(), lam, tuple(shape)).astype(_dt(dtype))


@register("_random_negative_binomial", differentiable=False)
def random_negative_binomial(k=1, p=0.5, shape=(), dtype=None, ctx=None):
    del ctx
    key1, key2 = jax.random.split(_random.new_key())
    g = jax.random.gamma(key1, k, tuple(shape)) * (1 - p) / p
    return jax.random.poisson(key2, g, tuple(shape)).astype(_dt(dtype))


@register("_random_randint", aliases=("randint",), differentiable=False)
def random_randint(low=0, high=1, shape=(), dtype="int32", ctx=None):
    del ctx
    return jax.random.randint(
        _random.new_key(), tuple(shape), int(low), int(high)
    ).astype(_dt(dtype))


@register("_sample_uniform", differentiable=False)
def sample_uniform(low, high, shape=(), dtype=None):
    u = jax.random.uniform(
        _random.new_key(), low.shape + tuple(shape), _dt(dtype)
    )
    low_ = low.reshape(low.shape + (1,) * len(shape)).astype(u.dtype)
    high_ = high.reshape(high.shape + (1,) * len(shape)).astype(u.dtype)
    return low_ + u * (high_ - low_)


@register("_sample_normal", differentiable=False)
def sample_normal(mu, sigma, shape=(), dtype=None):
    z = jax.random.normal(_random.new_key(), mu.shape + tuple(shape), _dt(dtype))
    return mu.reshape(mu.shape + (1,) * len(shape)).astype(z.dtype) + \
        sigma.reshape(sigma.shape + (1,) * len(shape)).astype(z.dtype) * z


@register("_sample_gamma", differentiable=False)
def sample_gamma(alpha, beta, shape=(), dtype=None):
    a = alpha.reshape(alpha.shape + (1,) * len(shape))
    g = jax.random.gamma(
        _random.new_key(), a, alpha.shape + tuple(shape), _dt(dtype)
    )
    return g * beta.reshape(beta.shape + (1,) * len(shape)).astype(g.dtype)


@register("_sample_multinomial",
          aliases=("sample_multinomial", "multinomial"),
          differentiable=False)
def sample_multinomial(data, shape=(), get_prob=False, dtype="int32"):
    """Sample category indices from probability rows
    (ref: src/operator/random/multisample_op.cc)."""
    n = 1
    for s in shape if isinstance(shape, tuple) else (shape,):
        n *= int(s)
    n = max(n, 1)
    logits = jnp.log(jnp.maximum(data, 1e-37))
    samp = jax.random.categorical(
        _random.new_key(), logits[..., None, :], axis=-1,
        shape=data.shape[:-1] + (n,)
    )
    out_shape = data.shape[:-1] + (tuple(shape) if isinstance(shape, tuple) else (shape,))
    if shape == () or shape == 1:
        out_shape = data.shape[:-1]
    samp = samp.reshape(out_shape).astype(_dt(dtype))
    if get_prob:
        lp = jnp.take_along_axis(
            jnp.log(jnp.maximum(data, 1e-37)),
            samp.astype(jnp.int32).reshape(data.shape[:-1] + (-1,)), axis=-1
        ).reshape(out_shape)
        return (samp, lp)
    return samp


@register("_shuffle", aliases=("shuffle",), differentiable=False)
def shuffle(data):
    return jax.random.permutation(_random.new_key(), data, axis=0)


@register("bernoulli", differentiable=False)
def bernoulli(prob=0.5, shape=(), dtype="float32"):
    return jax.random.bernoulli(
        _random.new_key(), prob, tuple(shape)
    ).astype(_dt(dtype))
