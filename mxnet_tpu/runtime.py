"""Runtime feature detection (ref: python/mxnet/runtime.py — Features /
feature_list over libinfo). The reference reports compile-time flags
(CUDA, CUDNN, MKLDNN, ...); here features reflect the TPU build: what
backend is live, which optional subsystems (native record engine, Pallas
flash attention) are usable on this machine.
"""
from __future__ import annotations

__all__ = ["Feature", "Features", "feature_list"]


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self.enabled = bool(enabled)

    def __repr__(self):
        return "%s %s" % ("✔" if self.enabled else "✖", self.name)


def _detect():
    feats = {}

    def add(name, enabled):
        feats[name] = Feature(name, enabled)

    try:
        import jax

        backend = jax.default_backend()
    except Exception:  # noqa: BLE001 — no backend at all
        backend = ""
    add("TPU", backend in ("tpu", "axon"))
    add("CPU", True)
    # reference compile-time flags that are inherently off in this build
    for flag in ("CUDA", "CUDNN", "NCCL", "TENSORRT", "MKLDNN", "OPENCV"):
        add(flag, False)
    add("BLAS_OPEN", True)  # XLA's own kernels play the BLAS role
    add("DIST_KVSTORE", True)  # jax.distributed + collectives path
    add("PROFILER", True)  # mx.profiler over jax.profiler
    add("SIGNAL_HANDLER", False)
    add("INT64_TENSOR_SIZE", True)
    # TPU-build-specific capabilities
    try:
        from . import native

        add("NATIVE_RECORDIO", native.available())
    except Exception:  # noqa: BLE001
        add("NATIVE_RECORDIO", False)
    try:
        from .ops import attention

        add("FLASH_ATTENTION", attention._use_pallas())
    except Exception:  # noqa: BLE001
        add("FLASH_ATTENTION", False)
    add("SEQUENCE_PARALLEL", True)
    add("INT8_QUANTIZATION", True)  # contrib.quantization, s8 MXU kernels
    try:
        from .ops import bn_pallas

        # enabled(): flag + pallas + TPU backend — the condition under
        # which the fused BN backward actually runs (same "usable here"
        # semantics as FLASH_ATTENTION above)
        add("BN_PALLAS", bn_pallas.enabled())
    except Exception:  # noqa: BLE001
        add("BN_PALLAS", False)
    try:
        from . import tuning

        # usable == decisions survive the process (a path is configured)
        add("KERNEL_AUTOTUNE", tuning.table().path is not None)
        add("COMPILE_CACHE", tuning.cache_dir() is not None)
    except Exception:  # noqa: BLE001
        add("KERNEL_AUTOTUNE", False)
        add("COMPILE_CACHE", False)
    return feats


class Features(dict):
    """Mapping of feature name -> Feature (ref: runtime.py — Features)."""

    def __init__(self):
        super().__init__(_detect())

    def is_enabled(self, name):
        name = name.upper()
        if name not in self:
            raise RuntimeError("feature %r does not exist" % (name,))
        return self[name].enabled

    def __repr__(self):
        return "[%s]" % ", ".join(repr(v) for v in self.values())


def feature_list():
    """List of Feature objects (ref: runtime.py — feature_list)."""
    return list(Features().values())
