"""Async dispatch engine — ThreadedEngine semantics over XLA
(ref: src/engine/threaded_engine.h + python/mxnet/engine.py).

The reference's ThreadedEngine lets the *host* run ahead of the device:
ops enqueue into a dependency queue, reads are the only sync points, and
``MXNET_ENGINE_BULK_SIZE`` bounds how much work is in flight. XLA's async
dispatch covers the device half of that, but until now every fused train
step still synchronized per step — the non-finite guard flag was read
back immediately, so each ~3.4 ms launch (PERF.md §1.2) paid a full
host↔device round-trip and the host could never pipeline.

This module is the missing host half:

- :class:`InflightWindow` is the per-call-site dependency queue: every
  dispatched fused program pushes a token; host-consumed scalars (the
  guard flag mask, a throttle read of the loss) ride tokens as deferred
  :class:`~mxnet_tpu.ndarray.pending.PendingValue` handles and are only
  materialized when the token *retires* — once the in-flight window is
  full, or at an explicit barrier. :class:`StepStream` is the training
  face of the same window (PR 4's name, kept as an alias); the serving
  decode stream (serving/engine.py) rides the SAME class with per-step
  *values* instead of guard flags: each decode step stages its sampled
  token ids, the window stacks a whole snapshot's worth into ONE device
  array, and a single deferred read delivers K steps of tokens to the
  scheduler — the decode hot loop never blocks on the device.
- the window depth K comes from ``MXT_MAX_INFLIGHT`` (default 2), and
  :func:`bulk`/:func:`set_bulk_size` are now the REAL knob instead of
  no-op shims: ``with engine.bulk(1):`` forces synchronous per-step
  reads, ``engine.bulk(8)`` lets 8 steps pipeline. The window also
  bounds backpressure: a retirement blocks until its step finished, so
  the un-synced dispatch queue (and the HBM working set behind the
  donated buffers) can never grow past ~2K steps.
- guard flags travel as a device-carried bitmask (one uint32 riding the
  fused program), so ONE host read retires up to K steps' worth of
  flags: host_syncs/step <= 1/K instead of 1.
- :func:`wait_all` drains every live stream — ``mx.nd.waitall()`` routes
  through it, making it the barrier tests and chaos_matrix.sh rely on,
  exactly like the reference's ``Engine::WaitForAll``.

Deferred-read callbacks retire on whichever thread triggers the read, so
everything here and the profiler counters it bumps are lock-guarded.
"""
from __future__ import annotations

import collections
import contextlib
import threading
import time
import weakref

__all__ = ["bulk", "set_bulk_size", "max_inflight", "InflightWindow",
           "StepStream", "wait_all", "inflight_depth", "window_states"]

# flag bits a single snapshot read may cover: the mask is a uint32 riding
# the fused program, and with snapshots every K pushes plus one token
# still in the window, up to 2K bits can be pending at a read -> K <= 15
_MASK_BITS = 15

_lock = threading.RLock()
_streams = weakref.WeakSet()  # every live StepStream, for wait_all()
_BULK_SIZE = None  # set_bulk_size override; None -> MXT_MAX_INFLIGHT


def _config():
    from . import config

    return config


def max_inflight():
    """Effective dispatch-window depth K: the ``set_bulk_size`` override
    when one is active, else ``MXT_MAX_INFLIGHT``; clamped to [1, 15]."""
    size = _BULK_SIZE
    if size is None:
        size = _config().get("MXT_MAX_INFLIGHT")
    return max(1, min(int(size), _MASK_BITS))


def set_bulk_size(size):
    """Set the in-flight step window depth; returns the previous
    effective depth (ref: engine.py — set_bulk_size). Unlike the earlier
    shim this is load-bearing: fused steps defer their host reads until
    ``size`` steps are in flight."""
    global _BULK_SIZE
    with _lock:
        prev = max_inflight()
        _BULK_SIZE = int(size)
    return prev


@contextlib.contextmanager
def bulk(size):
    """with-scope analog of the reference's engine bulking
    (ref: engine.py — bulk): ``with engine.bulk(1):`` is the synchronous
    A/B baseline, larger sizes deepen the dispatch pipeline."""
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)


def inflight_depth():
    """Total dispatched-but-unobserved steps across all live streams
    (also published as the ``dispatch_depth`` profiler gauge)."""
    with _lock:
        return sum(s.pending for s in _streams)


def _update_depth_gauge():
    from . import profiler

    profiler.set_gauge("dispatch_depth", inflight_depth())


def _telemetry():
    from . import telemetry

    return telemetry


def _diag():
    from . import diagnostics

    return diagnostics


def window_states():
    """[{name, dispatched, consumed, pending, staged, held_bytes}] for
    every live stream — what the hang watchdog's stall report and the
    post-mortem dump snapshot (pure host bookkeeping)."""
    with _lock:
        streams = list(_streams)
    return [{"name": s.name, "dispatched": s._dispatched,
             "consumed": s._consumed, "pending": s.pending,
             "staged": len(s._staged), "held_bytes": s._held_bytes}
            for s in streams]


def _nbytes(v):
    """Host-side byte count of a device value (shape metadata only —
    reading ``.nbytes`` never transfers)."""
    return int(getattr(getattr(v, "data", v), "nbytes", 0) or 0)


class _Token:
    """One retirement point in a stream: a deferred host read covering
    every step dispatched since the previous token."""

    __slots__ = ("pv", "has_flags", "upto", "nvalues", "nbytes")

    def __init__(self, pv, has_flags, upto, nvalues=0, nbytes=0):
        self.pv = pv
        self.has_flags = has_flags
        self.upto = upto
        self.nvalues = nvalues
        self.nbytes = nbytes


class InflightWindow:
    """The dependency queue for ONE dispatch site (a CachedTrainStep, a
    guarded _FusedUpdate, the serving decode stream): ``push()`` records
    a dispatched launch, every K-th push becomes a snapshot token
    carrying a deferred read, and tokens retire oldest-first as the
    window slides.

    Two retirement payloads, one deferred read each:

    - ``on_flags`` (training) receives one ``finite: bool`` per retired
      step, in dispatch order, decoded from a device-carried guard
      bitmask — deferred bookkeeping (update counts, loss-scale,
      skipped-step counter) lives in that callback.
    - ``on_values`` (serving decode, training health) receives
      ``(step_no, host_row)`` per retired step, in dispatch order. Each
      push stages its per-step device value (the decode step's sampled
      token ids, or the training step's packed health stat row); at
      snapshot time the window stacks the staged values into ONE device
      array, so a single deferred transfer still retires a whole
      window's worth of steps — host_syncs/step stays <= 1/K no matter
      how much per-step data rides the window.

    A single push may defer flags or a value, not both (the snapshot
    carries exactly one deferred device source). The training-health
    plane exploits that: in guard mode the stat row's LAST column packs
    this step's non-finite bit, so the guard flag and the stats retire
    from the SAME stacked read (health.py / gluon/train_step.py) and
    syncs/step stays bit-equal with health on or off.
    """

    def __init__(self, name="step", on_flags=None, on_values=None):
        self.name = name
        self._on_flags = on_flags
        self._on_values = on_values
        self._dispatched = 0
        self._consumed = 0
        self._last_snap = 0
        self._window = []  # snapshot tokens not yet retired
        self._staged = []  # per-step device values since the last snapshot
        self._latest = None  # (sync_value, flags) of the newest push
        self._retire_lock = threading.RLock()
        # host wall-clock of each dispatch, consumed oldest-first at
        # retirement: the dispatch->retire latency histogram costs zero
        # extra device reads (it is measured INSIDE the deferred read
        # the engine already performs)
        self._dispatch_ts = collections.deque()
        # bytes the window itself retains (staged per-step values +
        # snapshot token sources) — the 'inflight_window' HBM pool
        self._held_bytes = 0
        with _lock:
            _streams.add(self)
        # the watchdog observes window retires: pending work with a
        # frozen retire counter == a wedged device or a dead pipeline
        _diag().register_source("engine_retire", pending_fn=inflight_depth)

    @property
    def pending(self):
        """Steps dispatched but not yet observed on host."""
        return self._dispatched - self._consumed

    @staticmethod
    def _stack(values):
        """One device array from a snapshot's staged per-step values —
        a pure device op (async dispatch), never a host transfer."""
        import jax.numpy as jnp

        raw = [getattr(v, "data", v) for v in values]
        return jnp.stack(raw)

    def push(self, sync_value, flags=None, value=None):
        """Record one dispatched fused step; returns its step number.

        ``sync_value``: any device output of the step (used for the
        throttle read when there are no flags). ``flags``: the step's
        output guard bitmask (newest bit = this step), read deferred.
        ``value``: a per-step device array staged for ``on_values``
        delivery (every push in a stream must then carry one, and the
        shapes must match so a snapshot can stack them).
        """
        from .ndarray.pending import PendingValue

        if flags is not None and value is not None:
            from .base import MXNetError

            raise MXNetError("InflightWindow.push: a step may defer "
                             "flags or a value, not both")
        retire = []
        with _lock:
            self._dispatched += 1
            self._dispatch_ts.append(time.perf_counter())
            depth = self._dispatched - self._consumed
            step_no = self._dispatched
            self._latest = (sync_value, flags)
            if value is not None:
                self._staged.append(value)
                self._held_bytes += _nbytes(value)
            k = max_inflight()
            if self._dispatched - self._last_snap >= k:
                if self._staged:
                    src = self._stack(self._staged)
                    # staged bytes were counted per push; the token
                    # inherits them so retirement releases the total
                    tok = _Token(PendingValue(src), False,
                                 self._dispatched, len(self._staged),
                                 nbytes=sum(_nbytes(v)
                                            for v in self._staged))
                    self._staged = []
                else:
                    src = flags if flags is not None else sync_value
                    tok = _Token(PendingValue(src), flags is not None,
                                 self._dispatched, nbytes=_nbytes(src))
                    self._held_bytes += tok.nbytes
                self._last_snap = self._dispatched
                self._window.append(tok)
                if k == 1:
                    retire.append(self._window.pop())
                else:
                    while len(self._window) > 1:
                        retire.append(self._window.pop(0))
        _telemetry().record_dispatch(self.name, step_no, depth)
        self._publish_held()
        if retire:
            with self._retire_lock:
                for tok in retire:
                    self._retire(tok)
        _update_depth_gauge()
        return step_no

    def _publish_held(self):
        """Export the window's retained bytes as the 'inflight_window'
        HBM-ledger pool (host arithmetic on shape metadata)."""
        _diag().hbm_set("inflight_window", self.name,
                        max(0, self._held_bytes))

    def _retire(self, tok):
        """Materialize one token's deferred read and catch host-side
        bookkeeping up to it. Serialized per stream by _retire_lock."""
        n = tok.upto - self._consumed
        if n <= 0:
            return
        value = tok.pv.get()  # blocks until the covered steps finished
        with _lock:
            self._held_bytes -= tok.nbytes
        # retires are the engine's watchdog heartbeat: a frozen counter
        # with a non-empty window means the device stopped answering
        diag = _diag()
        diag.progress("engine_retire")
        self._publish_held()
        # dispatch->retire latency per covered step, clocked off the
        # read that just happened (telemetry adds NO host sync here)
        now = time.perf_counter()
        tel = _telemetry()
        for i in range(n):
            ts = self._dispatch_ts.popleft() if self._dispatch_ts else now
            tel.record_step_retired(self.name, tok.upto - n + 1 + i,
                                    now - ts)
        if tok.has_flags and self._on_flags is not None:
            mask = int(value)
            for k in range(n - 1, -1, -1):  # oldest step first
                self._on_flags((mask >> k) & 1 == 0)
        if tok.nvalues and self._on_values is not None:
            first = tok.upto - tok.nvalues + 1
            for i in range(tok.nvalues):  # oldest step first
                self._on_values(first + i, value[i])
        self._consumed = tok.upto

    def flush(self):
        """Drain: retire every queued token, then synthesize one for any
        steps dispatched since the last snapshot, so ``pending`` is 0 and
        all deferred bookkeeping has landed."""
        from .ndarray.pending import PendingValue

        with self._retire_lock:
            with _lock:
                tokens, self._window = self._window, []
                staged, self._staged = self._staged, []
                latest = self._latest
                upto = self._dispatched
                self._last_snap = upto
            for tok in tokens:
                self._retire(tok)
            if self._consumed < upto and latest is not None:
                sync_value, flags = latest
                if staged:
                    # staged bytes entered the ledger at push time; the
                    # synthesized token carries them out at retirement
                    self._retire(_Token(PendingValue(self._stack(staged)),
                                        False, upto, len(staged),
                                        nbytes=sum(_nbytes(v)
                                                   for v in staged)))
                else:
                    src = flags if flags is not None else sync_value
                    self._retire(_Token(PendingValue(src),
                                        flags is not None, upto))
        _update_depth_gauge()


class StepStream(InflightWindow):
    """The training face of :class:`InflightWindow` (PR 4's name):
    CachedTrainStep / the guarded _FusedUpdate push fused train steps
    and retire guard-flag bitmasks through ``on_flags``."""


def wait_all():
    """Drain every live stream's in-flight window (the host half of
    ``Engine::WaitForAll``; ``mx.nd.waitall()`` calls this first). The
    barrier is also the durability point for the kernel-tuning table:
    decisions the autotuner recorded since the last save hit disk here,
    so a process killed mid-epoch still leaves its tuning work behind
    for the next one (the same contract waitall gives the telemetry
    JSONL sink)."""
    with _lock:
        streams = list(_streams)
    for s in streams:
        s.flush()
    try:
        from . import tuning

        if tuning.table().dirty:
            tuning.save()
    except Exception:  # noqa: BLE001 — tuning persistence is best-effort
        pass
