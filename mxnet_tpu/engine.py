"""Engine controls (ref: python/mxnet/engine.py — bulk/set_bulk_size).

The reference's engine bulks consecutive async ops into one scheduling
unit to cut per-op dispatch cost. Here XLA compiles whole programs and
fuses internally, so bulking is structural, not a runtime switch —
these shims keep the API importable and record the requested size."""
from __future__ import annotations

import contextlib

__all__ = ["bulk", "set_bulk_size"]

_BULK_SIZE = 15  # reference default (MXNET_ENGINE_BULK_SIZE)


def set_bulk_size(size):
    """Returns the previous size (ref: engine.py — set_bulk_size).
    No-op on execution: under jit every traced program is already one
    'bulk'."""
    global _BULK_SIZE
    prev, _BULK_SIZE = _BULK_SIZE, int(size)
    return prev


@contextlib.contextmanager
def bulk(size):
    """with-scope analog of the reference's engine bulking
    (ref: engine.py — bulk)."""
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)
