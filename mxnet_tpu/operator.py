"""Custom operators — Python-defined ops usable from nd / Gluon / Symbol
(ref: python/mxnet/operator.py — CustomOp/CustomOpProp/register;
src/operator/custom/custom.cc ran the Python body on a dedicated thread
pool, async under the engine).

TPU-native mechanism: the Python forward/backward run on the HOST through
``jax.pure_callback``, so a Custom op composes with jit/grad — XLA treats
it as an opaque host call with declared output shapes (the shape contract
comes from ``CustomOpProp.infer_shape``, exactly like the reference).
Gradients route through a ``jax.custom_vjp`` whose backward is another
host callback into ``CustomOp.backward``.
"""
from __future__ import annotations

import functools

import numpy as np

from .base import MXNetError

__all__ = ["CustomOp", "CustomOpProp", "register", "get_prop"]

_PROPS = {}


class CustomOp:
    """Base class for the Python operator body (ref: operator.py —
    CustomOp). Subclass and implement forward/backward; use ``assign`` to
    honor the req mode."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    @staticmethod
    def assign(dst, req, src):
        """Write ``src`` into ``dst`` honoring req ('write'/'add'/'null');
        dst is a host numpy buffer here."""
        if req in ("write", "inplace"):
            dst[...] = src
        elif req == "add":
            dst[...] += src
        elif req == "null":
            pass
        else:
            raise MXNetError("unknown req %r" % (req,))


class CustomOpProp:
    """Shape/type contract + factory (ref: operator.py — CustomOpProp)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = bool(need_top_grad)

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        raise NotImplementedError

    def need_top_grad(self):
        return self.need_top_grad_


def register(reg_name):
    """Decorator registering a CustomOpProp under ``op_type`` for
    ``nd.Custom(..., op_type=reg_name)`` (ref: mx.operator.register)."""

    def deco(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register expects a CustomOpProp subclass")
        _PROPS[reg_name] = prop_cls
        return prop_cls

    return deco


def get_prop(op_type, kwargs=None):
    if op_type not in _PROPS:
        raise MXNetError(
            "custom op %r is not registered (use "
            "@mx.operator.register(%r))" % (op_type, op_type))
    # reference passes ctor kwargs as strings
    return _PROPS[op_type](**{k: str(v) for k, v in (kwargs or {}).items()})


# ---------------------------------------------------------------------------
# the jittable bridge, registered as the 'Custom' op in the registry
# ---------------------------------------------------------------------------
def _make_custom_fn(prop, op_type):
    """Build the jax-side function for one (prop, input-signature) call."""
    import jax
    import jax.numpy as jnp

    n_out = len(prop.list_outputs())

    def _infer(in_avals):
        in_shapes = [tuple(a.shape) for a in in_avals]
        shapes = prop.infer_shape([list(s) for s in in_shapes])
        _, out_shapes, _ = shapes
        types = prop.infer_type([a.dtype for a in in_avals])
        _, out_types, _ = types
        return [jax.ShapeDtypeStruct(tuple(s), np.dtype(t))
                for s, t in zip(out_shapes, out_types)]

    @functools.cache
    def _op_instance():
        return prop.create_operator(None, None, None)

    def _host_forward(is_train, *arrays):
        op = _op_instance()
        in_data = [np.asarray(a) for a in arrays]
        out_structs = _infer(arrays)
        out_data = [np.zeros(s.shape, s.dtype) for s in out_structs]
        op.forward(bool(is_train), ["write"] * len(out_data), in_data,
                   out_data, [])
        return tuple(out_data)

    def _host_backward(n_in, *arrays):
        op = _op_instance()
        out_grad = [np.asarray(a) for a in arrays[:n_out]]
        in_data = [np.asarray(a) for a in arrays[n_out:n_out + n_in]]
        out_data = [np.asarray(a) for a in arrays[n_out + n_in:]]
        in_grad = [np.zeros_like(a) for a in in_data]
        op.backward(["write"] * len(in_grad), out_grad, in_data, out_data,
                    in_grad, [])
        return tuple(in_grad)

    @jax.custom_vjp
    def custom_apply(*inputs):
        outs = tuple(jax.pure_callback(
            functools.partial(_host_forward, False), _infer(inputs),
            *inputs))
        return outs if n_out > 1 else outs[0]

    def custom_fwd(*inputs):
        outs = tuple(jax.pure_callback(
            functools.partial(_host_forward, True), _infer(inputs),
            *inputs))
        result = outs if n_out > 1 else outs[0]
        return result, (inputs, outs)

    def custom_bwd(res, cts):
        inputs, outs = res
        cts = cts if isinstance(cts, tuple) else (cts,)
        in_structs = [jax.ShapeDtypeStruct(i.shape, i.dtype)
                      for i in inputs]
        grads = jax.pure_callback(
            functools.partial(_host_backward, len(inputs)), in_structs,
            *(tuple(cts) + tuple(inputs) + tuple(outs)))
        return tuple(grads)

    custom_apply.defvjp(custom_fwd, custom_bwd)
    custom_apply.__name__ = "Custom_%s" % op_type
    return custom_apply


_FN_CACHE = {}


def custom(*inputs, op_type=None, **kwargs):
    """The registered ``Custom`` op body (ref: nd.Custom)."""
    if op_type is None:
        raise MXNetError("Custom requires op_type=")
    cache_key = (op_type, tuple(sorted(kwargs.items())))
    fn = _FN_CACHE.get(cache_key)
    if fn is None:
        prop = get_prop(op_type, kwargs)
        fn = _make_custom_fn(prop, op_type)
        _FN_CACHE[cache_key] = fn
    return fn(*inputs)


# register into the central op registry so nd.Custom / sym.Custom exist
from .ops.registry import register as _register_op  # noqa: E402


@_register_op("Custom", aliases=("_custom",))
def Custom(*inputs, op_type=None, **kwargs):  # noqa: N802 — reference name
    return custom(*inputs, op_type=op_type, **kwargs)
