"""Fleet-wide observability plane — membership-driven metric
aggregation and end-to-end distributed request tracing.

PRs 10-12 made the repo a genuine fleet: serving replicas, embedding
servers, and training workers all register in one coordinator
MembershipTable. Every observability surface so far (the PR 5 registry,
the PR 9 diagnostics, ``mxt_top``) is strictly process-local — an
operator has N Prometheus endpoints and no answer to "what happened to
request X" once it crossed the router, a hedge, a failover, and a
replica's decode engine. This module is the cross-process half:

1. **Membership-driven collector.** :class:`FleetCollector` discovers
   every live member from the coordinator's membership view — the
   registration ``meta`` already carries each serving replica's and
   embedding server's endpoint — and scrapes each one's metrics
   registry and trace spans over the SAME authenticated async-server
   transport the data plane uses (new ``tel_snapshot`` / ``tel_spans``
   ops; requests ride ``resilience.kv_retry`` with a bounded deadline,
   so a dead member is marked *stale* with its last-seen age — never a
   hang). Scraped registries merge into one :class:`FleetRegistry`
   using PR 5's mergeable histograms: identical-bucket histograms fold
   across members for fleet-level quantiles, every sample is re-exposed
   with a ``member`` label (stale members additionally carry
   ``stale="true"`` plus ``mxt_fleet_scrape_age_seconds{member}`` so a
   reaped member's gauges can never masquerade as live data).

2. **Distributed request tracing.** The fleet router mints a
   ``trace_id`` per request at ``submit`` and propagates it through
   dispatch, hedge duplicates, failover re-enqueues, and the replicas'
   ``srv_*`` frames; router and scheduler stamp
   queue/prefill/decode/commit spans against it host-side (spans close
   inside the existing deferred PendingValue retirement — zero new
   device syncs, lint-enforced by tools/check_host_syncs.py, which
   scans this module too). The collector reassembles the span trees
   from every member's ``tel_spans`` and :func:`chrome_trace` exports
   **Chrome trace-event JSON** loadable in Perfetto — a hedged request
   renders as two replica tracks with the loser's cancel visible;
   ``/debug/timeline?trace_id=`` (and whole-fleet ``/debug/timeline``)
   serve it from the telemetry endpoint.

Host/device split: the collector is PURE host bookkeeping — wire
payloads, wall clocks, dict merges. It performs zero device reads and
runs entirely off the serving hot path (its scrapes read registries
the hot paths already maintain), so serving-path host-sync counts are
bit-identical with the collector on or off — asserted in
tests/test_telemetry_fleet.py and the ``fleet_observability_ab`` bench
row.
"""
from __future__ import annotations

import json
import threading
import time

from .base import MXNetError
from . import telemetry
from .telemetry import histogram_quantile, sanitize_metric_name

__all__ = [
    "FleetRegistry", "FleetCollector", "chrome_trace", "trace_tree",
    "default_collector", "set_default_collector", "handle_timeline",
]

# the member/staleness labels the fleet view owns: a scraped family
# already carrying one would produce ambiguous series after the merge
_RESERVED_LABELS = ("member", "stale")


# ---------------------------------------------------------------------------
# the merged fleet registry
# ---------------------------------------------------------------------------
class FleetRegistry:
    """One merged view over many processes' registry snapshots.

    ``ingest`` folds a :func:`telemetry.registry_export` payload in
    under a member name; families are schema-checked across members
    (same name must mean same kind/labels/buckets everywhere — a
    mismatch is a typed error, never a silent second metric, exactly
    the process-local registry's contract lifted to the fleet) and the
    reserved ``member``/``stale`` labels collide typed.
    ``render_prometheus`` re-exposes every sample with the ``member``
    label (+ ``stale="true"`` for members whose last scrape failed);
    :meth:`merged_histogram` folds identical-bucket histograms across
    members — the cross-process aggregation PR 5's mergeable histogram
    children were built for."""

    def __init__(self):
        self._fams = {}  # name -> family record (see ingest)

    def ingest(self, member, export, stale=False):
        """Fold one member's registry snapshot in (replacing any
        earlier snapshot from the same member)."""
        member = str(member)
        for fam in (export or {}).get("families", ()):
            name = sanitize_metric_name(fam["name"])
            kind = str(fam["kind"])
            labelnames = tuple(fam.get("labelnames") or ())
            for reserved in _RESERVED_LABELS:
                if reserved in labelnames:
                    raise MXNetError(
                        "fleet registry label collision: member %r "
                        "exports metric %r with label %r, which the "
                        "fleet view reserves for scrape provenance"
                        % (member, name, reserved))
            buckets = tuple(fam.get("buckets") or ()) or None
            cur = self._fams.get(name)
            if cur is None:
                cur = self._fams[name] = {
                    "kind": kind, "help": str(fam.get("help", "")),
                    "labelnames": labelnames, "buckets": buckets,
                    "members": {}}
            else:
                if cur["kind"] != kind:
                    raise MXNetError(
                        "fleet registry schema mismatch: metric %r is a "
                        "%s on member %r but was a %s on an earlier "
                        "member" % (name, kind, member, cur["kind"]))
                if cur["labelnames"] != labelnames:
                    raise MXNetError(
                        "fleet registry schema mismatch: metric %r has "
                        "labels %s on member %r but %s elsewhere"
                        % (name, labelnames, member, cur["labelnames"]))
                if cur["buckets"] != buckets:
                    raise MXNetError(
                        "fleet registry schema mismatch: histogram %r "
                        "buckets differ on member %r — identical bounds "
                        "are the merge precondition" % (name, member))
            cur["members"][member] = {
                "stale": bool(stale),
                "children": {tuple(str(v) for v in values): payload
                             for values, payload in fam["children"]}}

    def drop_member(self, member):
        """Remove every series a member contributed (the drop half of
        drop-or-label stale hygiene)."""
        member = str(member)
        for fam in self._fams.values():
            fam["members"].pop(member, None)

    def members(self):
        out = set()
        for fam in self._fams.values():
            out.update(fam["members"])
        return sorted(out)

    def families(self):
        return sorted(self._fams)

    def get(self, name):
        return self._fams.get(sanitize_metric_name(name))

    # -- cross-member aggregation ------------------------------------------
    def merged_histogram(self, name, labels=None, include_stale=False,
                         missing_ok=False):
        """One bucket-wise merged snapshot of histogram ``name`` across
        every (live, unless ``include_stale``) member — and across its
        labelsets unless ``labels`` pins one. Returns ``{"buckets",
        "counts", "sum", "count"}``; merged quantiles over it equal the
        quantiles of the union of every member's observations (same
        bounds, summed counts — the PR 5 merge contract).
        ``missing_ok=True`` returns ``None`` for an absent family
        instead of raising — the autoscaler's "no traffic yet" read,
        where a missing latency histogram is a signal, not an error."""
        fam = self.get(name)
        if fam is None:
            if missing_ok:
                return None
            raise MXNetError("fleet registry has no metric %r" % name)
        if fam["kind"] != "histogram":
            raise MXNetError("fleet metric %r is a %s, not a histogram"
                             % (name, fam["kind"]))
        want = None
        if labels is not None:
            want = tuple(str(labels[k]) for k in fam["labelnames"])
        bounds = fam["buckets"] or ()
        counts = [0] * (len(bounds) + 1)
        total, csum = 0, 0.0
        for rec in fam["members"].values():
            if rec["stale"] and not include_stale:
                continue
            for values, snap in rec["children"].items():
                if want is not None and values != want:
                    continue
                for i, c in enumerate(snap["counts"]):
                    counts[i] += int(c)
                total += int(snap["count"])
                csum += float(snap["sum"])  # sync-ok: host wire scalar
        return {"buckets": tuple(bounds), "counts": counts,
                "sum": csum, "count": total}

    def quantile(self, name, q, labels=None, include_stale=False,
                 missing_ok=False):
        snap = self.merged_histogram(name, labels=labels,
                                     include_stale=include_stale,
                                     missing_ok=missing_ok)
        if snap is None:
            return None
        return histogram_quantile(q, list(snap["buckets"]),
                                  list(snap["counts"]))

    def merged_value(self, name, labels=None, include_stale=False):
        """Sum of a counter/gauge across members (a fleet total)."""
        fam = self.get(name)
        if fam is None:
            return None
        want = None
        if labels is not None:
            want = tuple(str(labels[k]) for k in fam["labelnames"])
        total, seen = 0.0, False
        for rec in fam["members"].values():
            if rec["stale"] and not include_stale:
                continue
            for values, v in rec["children"].items():
                if fam["kind"] == "histogram":
                    continue
                if want is not None and values != want:
                    continue
                total += float(v)  # sync-ok: host wire scalar
                seen = True
        return total if seen else None

    def member_values(self, name, labels=None, include_stale=False):
        """``{member: value}`` of a counter/gauge per (live) member —
        the per-host view the training-health skew watch compares
        (health.fleet_skew): unlike :meth:`merged_value` the members
        stay separate, because a straggler only shows up as a SPREAD
        across hosts, never in the fleet sum. Members whose export
        lacks the family (or only has histogram children) are simply
        absent from the dict."""
        fam = self.get(name)
        if fam is None or fam["kind"] == "histogram":
            return {}
        want = None
        if labels is not None:
            want = tuple(str(labels[k]) for k in fam["labelnames"])
        out = {}
        for member, rec in fam["members"].items():
            if rec["stale"] and not include_stale:
                continue
            total, seen = 0.0, False
            for values, v in rec["children"].items():
                if want is not None and values != want:
                    continue
                total += float(v)  # sync-ok: host wire scalar
                seen = True
            if seen:
                out[member] = total
        return out

    # -- exposition ---------------------------------------------------------
    def render_prometheus(self):
        """The fleet page: every member's samples re-labeled with
        ``member=`` (+ ``stale="true"`` where the last scrape failed).
        Per-member values are bit-identical to the member's own page —
        the merge adds provenance, it never rewrites data."""
        from .telemetry import _fmt, _label_str

        lines = []
        for name in sorted(self._fams):
            fam = self._fams[name]
            if fam["help"]:
                lines.append("# HELP %s %s"
                             % (name, fam["help"].replace("\n", " ")))
            lines.append("# TYPE %s %s" % (name, fam["kind"]))
            for member in sorted(fam["members"]):
                rec = fam["members"][member]
                extra_names = ("member", "stale") if rec["stale"] \
                    else ("member",)
                extra_values = (member, "true") if rec["stale"] \
                    else (member,)
                for values in sorted(rec["children"]):
                    payload = rec["children"][values]
                    base = _label_str(fam["labelnames"] + extra_names,
                                      values + extra_values)
                    if fam["kind"] == "histogram":
                        cum = 0
                        for bound, c in zip(fam["buckets"] or (),
                                            payload["counts"]):
                            cum += c
                            lines.append("%s_bucket%s %d" % (
                                name,
                                _label_str(
                                    fam["labelnames"] + extra_names
                                    + ("le",),
                                    values + extra_values
                                    + (_fmt(bound),)), cum))
                        lines.append("%s_bucket%s %d" % (
                            name,
                            _label_str(
                                fam["labelnames"] + extra_names
                                + ("le",),
                                values + extra_values + ("+Inf",)),
                            payload["count"]))
                        lines.append("%s_sum%s %s"
                                     % (name, base, _fmt(payload["sum"])))
                        lines.append("%s_count%s %d"
                                     % (name, base, payload["count"]))
                    else:
                        lines.append("%s%s %s"
                                     % (name, base, _fmt(payload)))
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# collector-owned metrics (live in the LOCAL process registry, so they
# show on the collector's own endpoint AND — via the local member — on
# the fleet page)
# ---------------------------------------------------------------------------
def _scrape_age_gauge():
    return telemetry.gauge(
        "mxt_fleet_scrape_age_seconds",
        "Seconds since each fleet member's last successful telemetry "
        "scrape (stale members keep aging — their samples carry "
        "stale=\"true\" on the fleet page).", ("member",))


def _scrapes_total():
    return telemetry.counter(
        "mxt_fleet_scrapes_total",
        "Fleet telemetry scrapes by member and outcome (an 'error' "
        "marks the member stale; its last snapshot stays labeled, "
        "never silently live).", ("member", "outcome"))


def _members_gauge():
    return telemetry.gauge(
        "mxt_fleet_members",
        "Fleet members known to the collector by scrape state.",
        ("state",))


# the collector's own meta-metric families — appended verbatim to the
# fleet page (they already carry the member label natively)
_COLLECTOR_META = ("mxt_fleet_scrape_age_seconds",
                   "mxt_fleet_scrapes_total", "mxt_fleet_members")


def _render_collector_meta():
    """The collector-owned families' exposition lines, filtered out of
    the process's full render (they are scalars, so family = the first
    token up to '{' or ' ')."""
    out = []
    for line in telemetry.render_prometheus().splitlines():
        if line.startswith("#"):
            parts = line.split()
            fam = parts[2] if len(parts) > 2 else ""
        else:
            fam = line.partition("{")[0].partition(" ")[0]
        if fam in _COLLECTOR_META:
            out.append(line)
    return "\n".join(out) + "\n" if out else ""


# ---------------------------------------------------------------------------
# the membership-driven collector
# ---------------------------------------------------------------------------
class _Target:
    """One scrape target: a fleet member's telemetry endpoint plus the
    newest snapshot/spans we hold for it."""

    __slots__ = ("name", "endpoint", "client", "snapshot", "spans",
                 "last_ok", "stale", "error", "local")

    def __init__(self, name, endpoint=None, local=False):
        self.name = str(name)
        self.endpoint = endpoint   # (host, port) or None for local
        self.local = bool(local)
        self.client = None
        self.snapshot = None
        self.spans = []
        self.last_ok = None
        self.stale = False
        self.error = None


def member_name(meta, worker_id=None):
    """Canonical member name from registration meta: serving replicas
    are ``replica-<i>``, embedding servers ``emb-<i>``, anything else
    ``member-<worker_id>``."""
    if isinstance(meta, dict):
        if meta.get("serving_replica"):
            return "replica-%d" % int(meta.get("index", 0))
        if meta.get("embedding_server"):
            return "emb-%d" % int(meta.get("index", 0))
    return "member-%s" % (worker_id,)


def _meta_endpoint(meta):
    """The scrapeable async-server endpoint a member announced in its
    registration meta, or None (in-process members carry none — the
    collector covers them through its local member)."""
    if not isinstance(meta, dict):
        return None
    ep = meta.get("endpoint")
    if ep:
        return (ep[0], int(ep[1]))
    if meta.get("host") and meta.get("port"):
        return (meta["host"], int(meta["port"]))
    return None


class FleetCollector:
    """Discover fleet members from the coordinator's membership table,
    scrape each one's registry + trace spans over the async transport,
    and serve the merged fleet view (see module docstring).

    ``server`` is an in-process coordinator
    :class:`~mxnet_tpu.async_server.AsyncParamServer` (the
    ``local_serving_fleet`` shape); ``coordinator`` is a ``(host,
    port)`` pair for a remote one — either supplies the membership
    view. ``include_local=True`` (default) also ingests THIS process's
    registry and spans as member ``local``, which is what covers
    in-process replicas (they share the collector's registry)."""

    def __init__(self, server=None, coordinator=None, include_local=True,
                 local_name="local", timeout=None,
                 now_fn=time.monotonic):
        from . import config

        self.server = server
        self.coordinator = coordinator
        self.include_local = bool(include_local)
        self.local_name = str(local_name)
        if timeout is None:
            timeout = config.get("MXT_FLEET_SCRAPE_TIMEOUT")
        self.timeout = float(timeout)  # sync-ok: host config scalar
        self._now = now_fn
        self._lock = threading.Lock()
        self._targets = {}   # name -> _Target
        self._coord_client = None
        self._thread = None
        self._stop = threading.Event()
        self.scrapes = 0
        if self.include_local:
            self._targets[self.local_name] = _Target(
                self.local_name, local=True)

    # -- membership discovery ----------------------------------------------
    def _membership_view(self):
        if self.server is not None:
            return self.server.membership.view()
        if self.coordinator is None:
            return None
        from .async_server import AsyncClient

        try:
            if self._coord_client is None:
                self._coord_client = AsyncClient(
                    self.coordinator[0], int(self.coordinator[1]),
                    timeout=self.timeout)
            return self._coord_client.request(
                "members", deadline=self.timeout)
        except (MXNetError, ConnectionError, OSError):
            if self._coord_client is not None:
                self._coord_client.close()
                self._coord_client = None
            return None

    def refresh(self):
        """Reconcile targets with the membership view: members that
        registered an endpoint become (or stay) remote scrape targets.
        A member that vanished from the view KEEPS its target — the
        next scrape fails typed and marks it stale with its last-seen
        age, which is exactly the operator-visible verdict a reaped
        member deserves (silent removal would let its gauges vanish
        without a trace)."""
        view = self._membership_view()
        if view is None:
            return self
        meta = view.get("meta", {})
        with self._lock:
            for wid, m in meta.items():
                ep = _meta_endpoint(m)
                if ep is None:
                    continue  # in-process member: the local target covers it
                name = member_name(m, wid)
                t = self._targets.get(name)
                if t is None:
                    self._targets[name] = _Target(name, endpoint=ep)
                elif t.endpoint != ep:
                    # the member re-registered elsewhere (restart):
                    # drop the dead connection, adopt the new endpoint
                    if t.client is not None:
                        t.client.close()
                        t.client = None
                    t.endpoint = ep
        return self

    def add_member(self, name, host, port):
        """Explicit remote target (tests, static fleets without a
        coordinator)."""
        with self._lock:
            self._targets[str(name)] = _Target(name,
                                               endpoint=(host, int(port)))
        return self

    def targets(self):
        with self._lock:
            return dict(self._targets)

    # -- scraping ------------------------------------------------------------
    def _scrape_one(self, t, now):
        """Scrape one target; never raises, never hangs past the
        bounded transport deadline — a failure marks the target stale
        and keeps its last snapshot for the stale-labeled page."""
        from .async_server import AsyncClient

        if t.local:
            t.snapshot = telemetry.registry_export()
            t.spans = telemetry.trace_spans()
            t.last_ok, t.stale, t.error = now, False, None
            _scrapes_total().labels(t.name, "ok").inc()
            return True
        try:
            if t.client is None:
                t.client = AsyncClient(t.endpoint[0], t.endpoint[1],
                                       timeout=self.timeout)
            # rides AsyncClient.request's kv_retry machinery under an
            # explicit deadline: a dead/hung member costs a bounded
            # timeout, then surfaces as a typed stale verdict
            t.snapshot = t.client.request("tel_snapshot",
                                          deadline=self.timeout)
            t.spans = list(t.client.request("tel_spans",
                                            deadline=self.timeout))
            t.last_ok, t.stale, t.error = now, False, None
            _scrapes_total().labels(t.name, "ok").inc()
            return True
        except (MXNetError, ConnectionError, OSError) as e:
            t.stale = True
            t.error = str(e)
            if t.client is not None:
                t.client.close()
                t.client = None
            _scrapes_total().labels(t.name, "error").inc()
            return False

    def scrape(self):
        """One scrape pass over every target. Publishes
        ``mxt_fleet_scrape_age_seconds{member}`` and the member-state
        gauge; returns self (chain ``.fleet_registry()``)."""
        now = self._now()
        self.scrapes += 1
        live = stale = 0
        for t in self.targets().values():
            self._scrape_one(t, now)
            if t.stale:
                stale += 1
            else:
                live += 1
            age = 0.0 if t.last_ok is None else max(0.0, now - t.last_ok)
            _scrape_age_gauge().labels(t.name).set(round(age, 6))
        g = _members_gauge()
        g.labels("live").set(live)
        g.labels("stale").set(stale)
        return self

    def fleet_registry(self):
        """The merged :class:`FleetRegistry` over the newest scrapes
        (stale members included, labeled). Families a member exports
        with the reserved ``member``/``stale`` labels — a member that
        itself runs a collector, or this process's own scrape
        meta-metrics — are skipped here rather than raised: the strict
        typed collision stays in :meth:`FleetRegistry.ingest` for
        direct callers, but a legitimate scrape must never die on
        nested provenance."""
        reg = FleetRegistry()
        for t in self.targets().values():
            if t.snapshot is None:
                continue
            fams = [f for f in t.snapshot.get("families", ())
                    if not any(r in (f.get("labelnames") or ())
                               for r in _RESERVED_LABELS)]
            reg.ingest(t.name, {"families": fams}, stale=t.stale)
        return reg

    def render_prometheus(self):
        """The fleet exposition page from the newest scrapes: every
        member's samples with ``member=`` provenance, plus the
        collector's own scrape meta-metrics (age/outcome/member-state)
        rendered verbatim."""
        page = self.fleet_registry().render_prometheus()
        meta = _render_collector_meta()
        return page + meta if meta else page

    # -- trace reassembly ----------------------------------------------------
    def spans(self, trace_id=None):
        """Every span the fleet knows for ``trace_id`` (or all traces):
        this process's span log plus each scraped member's, de-duplicated
        by span id (the local member and a remote registration of the
        same process must not double-count)."""
        seen = set()
        out = []
        rows = list(telemetry.trace_spans(trace_id))
        for t in self.targets().values():
            for r in t.spans:
                if trace_id is not None \
                        and r.get("trace_id") != trace_id:
                    continue
                rows.append(r)
        for r in rows:
            sid = r.get("span_id")
            if sid is not None and sid in seen:
                continue
            if sid is not None:
                seen.add(sid)
            out.append(r)
        out.sort(key=lambda r: (r.get("t0") or 0.0, r.get("t1") or 0.0))
        return out

    def chrome_trace(self, trace_id=None):
        return chrome_trace(self.spans(trace_id))

    def trace_tree(self, trace_id):
        return trace_tree(self.spans(trace_id), trace_id)

    # -- background loop ------------------------------------------------------
    def start(self, interval=None):
        """Refresh+scrape on a daemon thread every ``interval`` seconds
        (default ``MXT_FLEET_SCRAPE_INTERVAL``)."""
        from . import config

        if interval is None:
            interval = config.get("MXT_FLEET_SCRAPE_INTERVAL")
        interval = float(interval)  # sync-ok: host config scalar
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def _loop():
            while not self._stop.wait(interval):
                try:
                    self.refresh()
                    self.scrape()
                except Exception:  # noqa: BLE001 — the collector must
                    pass           # never take the fleet down

        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name="mxt-fleet-collector")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def close(self):
        self.stop()
        for t in self.targets().values():
            if t.client is not None:
                try:
                    t.client.close()
                except OSError:
                    pass
                t.client = None
        if self._coord_client is not None:
            self._coord_client.close()
            self._coord_client = None
        if default_collector() is self:
            set_default_collector(None)


# ---------------------------------------------------------------------------
# Chrome trace-event export
# ---------------------------------------------------------------------------
def chrome_trace(spans):
    """Chrome trace-event JSON (the Perfetto-loadable dict) from span
    rows. Tracks ("router", "replica-0", ...) become processes, each
    trace_id a named thread within them — so a hedged request shows as
    the same trace on two replica tracks, and zero-duration rows
    (commit, hedge, cancel, failover re-enqueue) render as instant
    events."""
    events = []
    pids = {}       # track -> pid
    tids = {}       # (pid, trace_id) -> tid
    for s in sorted(spans, key=lambda r: (r.get("t0") or 0.0)):
        track = s.get("track") or "process"
        pid = pids.get(track)
        if pid is None:
            pid = pids[track] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name",
                           "pid": pid, "tid": 0, "ts": 0,
                           "args": {"name": track}})
        key = (pid, s.get("trace_id"))
        tid = tids.get(key)
        if tid is None:
            tid = tids[key] = sum(1 for k in tids if k[0] == pid) + 1
            events.append({"ph": "M", "name": "thread_name",
                           "pid": pid, "tid": tid, "ts": 0,
                           "args": {"name": "trace %s"
                                    % (s.get("trace_id"),)}})
        t0 = float(s.get("t0") or 0.0)  # sync-ok: host wire scalar
        t1 = float(s.get("t1") or t0)   # sync-ok: host wire scalar
        args = dict(s.get("attrs") or {})
        args["trace_id"] = s.get("trace_id")
        ev = {"name": s.get("name"), "cat": "mxt", "pid": pid,
              "tid": tid, "ts": round(t0 * 1e6, 3), "args": args}
        if t1 > t0:
            ev["ph"] = "X"
            ev["dur"] = round((t1 - t0) * 1e6, 3)
        else:
            ev["ph"] = "i"
            ev["s"] = "t"
        events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def trace_tree(spans, trace_id):
    """One request's span tree, reconstructed from trace_id alone:
    ``{"trace_id", "names", "tracks": {track: [span, ...]}, "t0",
    "t1"}`` with per-track spans time-ordered — what the acceptance
    asserts walk."""
    mine = [s for s in spans if s.get("trace_id") == trace_id]
    mine.sort(key=lambda r: (r.get("t0") or 0.0, r.get("t1") or 0.0))
    tracks = {}
    for s in mine:
        tracks.setdefault(s.get("track") or "process", []).append(s)
    return {
        "trace_id": trace_id,
        "names": [s.get("name") for s in mine],
        "tracks": tracks,
        "t0": min((s.get("t0") or 0.0 for s in mine), default=None),
        "t1": max((s.get("t1") or 0.0 for s in mine), default=None),
    }


# ---------------------------------------------------------------------------
# the process-default collector + the /debug/timeline route
# ---------------------------------------------------------------------------
_default_lock = threading.Lock()
_default = None


def default_collector():
    """The process's registered fleet collector (what ``/fleet`` and
    ``/debug/timeline`` serve from), or None."""
    return _default


def set_default_collector(collector):
    """Install (or with None, clear) the process-default collector."""
    global _default
    with _default_lock:
        _default = collector
    return collector


def handle_timeline(params):
    """``/debug/timeline[?trace_id=...]`` → Chrome trace-event JSON.
    With a default collector: the whole fleet's spans; without one:
    this process's span log (a single replica is still traceable)."""
    tid = params.get("trace_id")
    c = default_collector()
    spans = c.spans(tid) if c is not None else telemetry.trace_spans(tid)
    doc = chrome_trace(spans)
    return (200, "application/json",
            json.dumps(doc, default=str).encode("utf-8"))
