"""Sparse NDArrays — ``row_sparse`` and ``csr`` storage (SURVEY §2.2
sparse-ops row / §2.4 PullRowSparse / build-plan P9; ref:
python/mxnet/ndarray/sparse.py + src/operator/tensor/cast_storage*).

TPU-native design stance (P9): XLA requires static shapes, so *inside* a
jitted step the embedding gradient is a dense scatter-add (what the take
VJP lowers to — MXU/HBM-optimal on TPU). The sparse storage classes here
serve the places where sparsity actually pays on this hardware:

- **communication** — KVStore push/pull of only touched rows
  (``row_sparse_pull``, sparse push merge by index union), the reference's
  main use of row_sparse (dist embedding training);
- **optimizer updates** — lazy/sparse SGD/Adam/AdaGrad/FTRL update only
  the rows present in the gradient (ref: ``_sparse_sgd_update`` etc.,
  src/operator/optimizer_op.cc), preserving the reference's lazy-update
  semantics (untouched rows' momentum does NOT decay);
- **storage / IO** — CSR datasets (LibSVM-style) and ``cast_storage``.

Component arrays live on device as jax buffers; index manipulation
(union, dedupe) runs eagerly where data-dependent shapes are fine.
"""
from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp
import numpy as np

from .base import MXNetError, get_dtype
from .ndarray.ndarray import NDArray

__all__ = [
    "BaseSparseNDArray", "RowSparseNDArray", "CSRNDArray",
    "row_sparse_array", "csr_matrix", "cast_storage", "sparse_retain",
    "retain_rows", "dot", "add", "zeros", "empty", "array",
]

_IDX_DT = jnp.int64  # ref: row_sparse indices are int64


def _dense_fallback_warning(op):
    warnings.warn(
        "%s: storage fallback — operating on the dense representation "
        "(ref behavior: 'op falls back to dense')" % op, stacklevel=3)


class BaseSparseNDArray(NDArray):
    """Common behavior for sparse storage types. Subclasses NDArray so
    sparse arrays flow through APIs that type-check NDArray, but the
    dense buffer is materialized only on explicit fallback."""

    __slots__ = ()

    def _init_handle(self):
        # NDArray slots, bypassing its dense-buffer __init__
        self._base = None
        self._key = None
        self._grad = None
        self._ag_node = None
        self._data = None

    # subclasses must implement _dense()
    @property
    def data(self):
        raise NotImplementedError

    def asnumpy(self):
        """Dense numpy copy (ref: sparse .asnumpy returns dense)."""
        return np.asarray(self._dense())

    def wait_to_read(self):
        from .ndarray.ndarray import _device_sync
        for c in self._components():
            jax.block_until_ready(c)
            _device_sync(c)
        return self

    wait_to_write = wait_to_read

    def copy(self):
        return self.tostype(self.stype)

    def __len__(self):
        return self.shape[0]

    # dense-fallback arithmetic (explicit, warned — ref storage fallback)
    def _fallback_binary(self, other, fn, opname):
        _dense_fallback_warning(opname)
        o = other._dense() if isinstance(other, BaseSparseNDArray) else \
            (other.data if isinstance(other, NDArray) else other)
        return NDArray(fn(self._dense(), o))

    def __sub__(self, other):
        return self._fallback_binary(other, lambda a, b: a - b, "subtract")

    def __truediv__(self, other):
        return self._fallback_binary(other, lambda a, b: a / b, "divide")


class RowSparseNDArray(BaseSparseNDArray):
    """Row-sparse: values for a subset of rows + sorted unique row indices
    (ref: kRowSparseStorage — aux ``indices``; NDArray.h RowSparseAux)."""

    __slots__ = ("_values", "_indices", "_shape")

    stype = "row_sparse"

    def __init__(self, values, indices, shape):
        self._init_handle()
        self._values = values if isinstance(values, jax.Array) else \
            jnp.asarray(values)
        self._indices = (indices if isinstance(indices, jax.Array)
                         else jnp.asarray(indices)).astype(_IDX_DT)
        self._shape = tuple(int(s) for s in shape)
        if self._values.ndim != len(self._shape):
            raise MXNetError(
                "row_sparse values ndim %d must equal dense ndim %d"
                % (self._values.ndim, len(self._shape)))
        if self._values.shape[0] != self._indices.shape[0]:
            raise MXNetError("values rows %d != indices %d"
                             % (self._values.shape[0],
                                self._indices.shape[0]))

    def _components(self):
        return (self._values, self._indices)

    # -- properties (reference API: .data = values, .indices = row ids) --
    @property
    def data(self):
        return NDArray(self._values)

    @property
    def indices(self):
        return NDArray(self._indices)

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return np.dtype(self._values.dtype)

    @property
    def context(self):
        from .context import current_context
        return current_context()

    @property
    def num_rows(self):
        return int(self._indices.shape[0])

    def __repr__(self):
        return "<RowSparseNDArray %s, %d/%d rows>" % (
            "x".join(map(str, self._shape)), self.num_rows, self._shape[0])

    def _dense(self):
        out = jnp.zeros(self._shape, self._values.dtype)
        if self.num_rows:
            out = out.at[self._indices].set(self._values)
        return out

    def todense(self):
        return NDArray(self._dense())

    def tostype(self, stype):
        if stype == "row_sparse":
            return RowSparseNDArray(self._values, self._indices,
                                    self._shape)
        if stype == "default":
            return self.todense()
        if stype == "csr":
            raise MXNetError("cast_storage row_sparse -> csr is not "
                             "supported (matches reference)")
        raise MXNetError("unknown stype %r" % (stype,))

    def astype(self, dtype):
        return RowSparseNDArray(self._values.astype(get_dtype(dtype)),
                                self._indices, self._shape)

    def copyto(self, other):
        if isinstance(other, RowSparseNDArray):
            other._values = self._values
            other._indices = self._indices
            other._shape = self._shape
            return other
        if isinstance(other, NDArray):
            other._set_data(self._dense())
            return other
        raise MXNetError("copyto: unsupported target %r" % (other,))

    def retain(self, indices):
        return sparse_retain(self, indices)

    def __add__(self, other):
        if isinstance(other, RowSparseNDArray):
            return add(self, other)
        return self._fallback_binary(other, lambda a, b: a + b, "add")

    __radd__ = __add__

    def __mul__(self, other):
        from .base import numeric_types
        if isinstance(other, numeric_types):
            return RowSparseNDArray(self._values * other, self._indices,
                                    self._shape)
        return self._fallback_binary(other, lambda a, b: a * b, "multiply")

    __rmul__ = __mul__


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row, 2-D (ref: kCSRStorage — aux ``indptr`` +
    ``indices``)."""

    __slots__ = ("_values", "_indices", "_indptr", "_shape")

    stype = "csr"

    def __init__(self, values, indices, indptr, shape):
        self._init_handle()
        self._values = jnp.asarray(values)
        self._indices = jnp.asarray(indices).astype(_IDX_DT)
        self._indptr = jnp.asarray(indptr).astype(_IDX_DT)
        self._shape = tuple(int(s) for s in shape)
        if len(self._shape) != 2:
            raise MXNetError("csr arrays are 2-D, got shape %s"
                             % (self._shape,))
        if self._indptr.shape[0] != self._shape[0] + 1:
            raise MXNetError("indptr length %d != rows+1 (%d)"
                             % (self._indptr.shape[0], self._shape[0] + 1))

    def _components(self):
        return (self._values, self._indices, self._indptr)

    @property
    def data(self):
        return NDArray(self._values)

    @property
    def indices(self):
        return NDArray(self._indices)

    @property
    def indptr(self):
        return NDArray(self._indptr)

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return np.dtype(self._values.dtype)

    @property
    def context(self):
        from .context import current_context
        return current_context()

    def __repr__(self):
        return "<CSRNDArray %s, %d stored>" % (
            "x".join(map(str, self._shape)), int(self._values.shape[0]))

    def _row_ids(self):
        """Per-nnz row id from indptr (host-side; eager path)."""
        indptr = np.asarray(self._indptr)
        counts = np.diff(indptr)
        return jnp.asarray(np.repeat(np.arange(self._shape[0]), counts),
                           dtype=_IDX_DT)

    def _dense(self):
        out = jnp.zeros(self._shape, self._values.dtype)
        if int(self._values.shape[0]):
            out = out.at[self._row_ids(), self._indices].set(self._values)
        return out

    def todense(self):
        return NDArray(self._dense())

    def tostype(self, stype):
        if stype == "csr":
            return CSRNDArray(self._values, self._indices, self._indptr,
                              self._shape)
        if stype == "default":
            return self.todense()
        raise MXNetError("cast_storage csr -> %s is not supported" % stype)

    def astype(self, dtype):
        return CSRNDArray(self._values.astype(get_dtype(dtype)),
                          self._indices, self._indptr, self._shape)

    def __getitem__(self, key):
        """Row slicing (ref: CSRNDArray supports slice on dim 0)."""
        if isinstance(key, int):
            key = slice(key, key + 1)
        if not isinstance(key, slice) or key.step not in (None, 1):
            raise MXNetError("csr supports contiguous row slices only")
        start, stop, _ = key.indices(self._shape[0])
        indptr = np.asarray(self._indptr)
        lo, hi = int(indptr[start]), int(indptr[stop])
        return CSRNDArray(self._values[lo:hi], self._indices[lo:hi],
                          self._indptr[start:stop + 1] - lo,
                          (stop - start, self._shape[1]))


# ---------------------------------------------------------------------------
# constructors
# ---------------------------------------------------------------------------
def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """Build a RowSparseNDArray from ``(data, indices)`` or from a dense
    source (nonzero rows kept), ref: sparse.py — row_sparse_array."""
    del ctx
    if isinstance(arg1, RowSparseNDArray):
        return arg1.tostype("row_sparse")
    if isinstance(arg1, tuple) and len(arg1) == 2 and not isinstance(
            arg1[0], (int, np.integer)):
        data, indices = arg1
        data = data.data if isinstance(data, NDArray) else jnp.asarray(data)
        indices = indices.data if isinstance(indices, NDArray) \
            else jnp.asarray(indices)
        if dtype is not None:
            data = data.astype(get_dtype(dtype))
        if shape is None:
            raise MXNetError("row_sparse_array((data, indices)) requires "
                             "shape=")
        order = np.argsort(np.asarray(indices), kind="stable")
        if not np.all(order == np.arange(len(order))):
            data = data[jnp.asarray(order)]
            indices = indices[jnp.asarray(order)]
        return RowSparseNDArray(data, indices, shape)
    # dense source
    dense = arg1.data if isinstance(arg1, NDArray) else jnp.asarray(
        np.asarray(arg1))
    if dtype is not None:
        dense = dense.astype(get_dtype(dtype))
    if shape is not None and tuple(shape) != tuple(dense.shape):
        raise MXNetError("shape mismatch: %s vs %s"
                         % (shape, dense.shape))
    nz = np.nonzero(np.asarray(
        jnp.any(dense.reshape(dense.shape[0], -1) != 0, axis=1)))[0]
    idx = jnp.asarray(nz, dtype=_IDX_DT)
    return RowSparseNDArray(dense[idx], idx, dense.shape)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """Build a CSRNDArray from ``(data, indices, indptr)`` or a dense
    source (ref: sparse.py — csr_matrix)."""
    del ctx
    if isinstance(arg1, CSRNDArray):
        return arg1.tostype("csr")
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = (
            a.data if isinstance(a, NDArray) else jnp.asarray(a)
            for a in arg1)
        if dtype is not None:
            data = data.astype(get_dtype(dtype))
        if shape is None:
            raise MXNetError("csr_matrix((data, indices, indptr)) requires "
                             "shape=")
        return CSRNDArray(data, indices, indptr, shape)
    dense = np.asarray(arg1.asnumpy() if isinstance(arg1, NDArray)
                       else arg1)
    if dtype is not None:
        dense = dense.astype(get_dtype(dtype))
    if dense.ndim != 2:
        raise MXNetError("csr_matrix needs a 2-D source")
    rows, cols = np.nonzero(dense)
    counts = np.bincount(rows, minlength=dense.shape[0])
    indptr = np.concatenate([[0], np.cumsum(counts)])
    return CSRNDArray(jnp.asarray(dense[rows, cols]),
                      jnp.asarray(cols, dtype=_IDX_DT),
                      jnp.asarray(indptr, dtype=_IDX_DT), dense.shape)


def array(source, stype="default", dtype=None, ctx=None):
    if stype == "default":
        return NDArray(source if not isinstance(source, NDArray)
                       else source.data, dtype=dtype, ctx=ctx)
    if stype == "row_sparse":
        return row_sparse_array(source, dtype=dtype, ctx=ctx)
    if stype == "csr":
        return csr_matrix(source, dtype=dtype, ctx=ctx)
    raise MXNetError("unknown stype %r" % (stype,))


def zeros(stype, shape, ctx=None, dtype=None):
    """ref: sparse.zeros — an all-zero sparse array stores nothing."""
    del ctx
    dt = get_dtype(dtype) if dtype else jnp.float32
    if stype == "row_sparse":
        vshape = (0,) + tuple(shape[1:])
        return RowSparseNDArray(jnp.zeros(vshape, dt),
                                jnp.zeros((0,), _IDX_DT), shape)
    if stype == "csr":
        return CSRNDArray(jnp.zeros((0,), dt), jnp.zeros((0,), _IDX_DT),
                          jnp.zeros((shape[0] + 1,), _IDX_DT), shape)
    if stype == "default":
        return NDArray(jnp.zeros(shape, dt))
    raise MXNetError("unknown stype %r" % (stype,))


empty = zeros


# ---------------------------------------------------------------------------
# storage ops (ref: src/operator/tensor/cast_storage*, sparse_retain*)
# ---------------------------------------------------------------------------
def cast_storage(arr, stype="default"):
    """ref: cast_storage op — dense<->row_sparse<->csr conversions."""
    if isinstance(arr, BaseSparseNDArray):
        return arr.tostype(stype)
    if stype == "default":
        return arr.copy()
    if stype == "row_sparse":
        return row_sparse_array(arr)
    if stype == "csr":
        return csr_matrix(arr)
    raise MXNetError("unknown stype %r" % (stype,))


def sparse_retain(rsp, indices):
    """Keep only the requested rows (ref: sparse_retain op). Rows absent
    from ``rsp`` come back as missing (not zero-filled)."""
    if not isinstance(rsp, RowSparseNDArray):
        raise MXNetError("sparse_retain expects a RowSparseNDArray")
    req = np.unique(np.asarray(
        indices.data if isinstance(indices, NDArray) else indices
    ).astype(np.int64))
    have = np.asarray(rsp._indices)
    mask = np.isin(have, req)
    keep = jnp.asarray(np.nonzero(mask)[0])
    return RowSparseNDArray(rsp._values[keep],
                            rsp._indices[keep], rsp.shape)


def retain_rows(src, row_ids, out=None):
    """Gather rows of a dense NDArray into a RowSparseNDArray — the server
    side of ``KVStore::PullRowSparse`` (only touched rows travel)."""
    ids = np.unique(np.asarray(
        row_ids.data if isinstance(row_ids, NDArray) else row_ids
    ).astype(np.int64))
    idx = jnp.asarray(ids, dtype=_IDX_DT)
    if isinstance(src, RowSparseNDArray):
        result = sparse_retain(src, idx)
    else:
        vals = src.data[idx]
        result = RowSparseNDArray(vals, idx, src.shape)
    if out is not None:
        return result.copyto(out)
    return result


def add(lhs, rhs):
    """row_sparse + row_sparse -> row_sparse over the index union
    (ref: elemwise_add with FInferStorageType rsp,rsp->rsp)."""
    if not (isinstance(lhs, RowSparseNDArray)
            and isinstance(rhs, RowSparseNDArray)):
        raise MXNetError("sparse.add expects two RowSparseNDArrays")
    if lhs.shape != rhs.shape:
        raise MXNetError("shape mismatch %s vs %s" % (lhs.shape, rhs.shape))
    li, ri = np.asarray(lhs._indices), np.asarray(rhs._indices)
    union = np.union1d(li, ri)
    uj = jnp.asarray(union, dtype=_IDX_DT)
    vshape = (len(union),) + lhs.shape[1:]
    vals = jnp.zeros(vshape, jnp.promote_types(lhs.dtype, rhs.dtype))
    lpos = jnp.asarray(np.searchsorted(union, li))
    rpos = jnp.asarray(np.searchsorted(union, ri))
    vals = vals.at[lpos].add(lhs._values.astype(vals.dtype))
    vals = vals.at[rpos].add(rhs._values.astype(vals.dtype))
    return RowSparseNDArray(vals, uj, lhs.shape)


def dot(lhs, rhs, transpose_a=False):
    """Sparse matmul: csr @ dense (and csr^T @ dense — the Embedding-grad
    shape, ref: dot(csr.T, dense) kernel in src/operator/tensor/dot-inl.h).
    segment_sum over nnz keeps this MXU/VPU-friendly."""
    if not isinstance(lhs, CSRNDArray):
        raise MXNetError("sparse.dot expects a CSRNDArray lhs")
    dense = rhs.data if isinstance(rhs, NDArray) else jnp.asarray(rhs)
    rows = lhs._row_ids()
    cols = lhs._indices
    vals = lhs._values
    if not transpose_a:
        # out[r] = sum_nnz(v * dense[c]) grouped by row
        contrib = vals[:, None] * dense[cols]
        out = jax.ops.segment_sum(contrib, rows.astype(jnp.int32),
                                  num_segments=lhs.shape[0])
        return NDArray(out.astype(dense.dtype))
    contrib = vals[:, None] * dense[rows]
    out = jax.ops.segment_sum(contrib, cols.astype(jnp.int32),
                              num_segments=lhs.shape[1])
    return NDArray(out.astype(dense.dtype))


# ---------------------------------------------------------------------------
# sparse optimizer updates (ref: src/operator/optimizer_op.cc — the
# _sparse_* variants; lazy_update semantics: rows NOT in the gradient are
# untouched, including their momentum/history)
# ---------------------------------------------------------------------------
def _rows_of(grad):
    return grad._indices, grad._values


def sparse_sgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0,
                      clip_gradient=-1.0):
    idx, gvals = _rows_of(grad)
    w = weight.data
    g = gvals.astype(jnp.float32) * rescale_grad
    if clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    rows = w[idx].astype(jnp.float32)
    new = rows - lr * (g + wd * rows)
    weight._set_data(w.at[idx].set(new.astype(w.dtype)))
    return weight


def sparse_sgd_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                          rescale_grad=1.0, clip_gradient=-1.0):
    idx, gvals = _rows_of(grad)
    w = weight.data
    m = mom.data
    g = gvals.astype(jnp.float32) * rescale_grad
    if clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    rows = w[idx].astype(jnp.float32)
    m_rows = m[idx].astype(jnp.float32)
    m_new = momentum * m_rows - lr * (g + wd * rows)
    mom._set_data(m.at[idx].set(m_new.astype(m.dtype)))
    weight._set_data(w.at[idx].set((rows + m_new).astype(w.dtype)))
    return weight


def sparse_adam_update(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                       epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, t=None):
    """``t=None`` means the caller already folded bias correction into
    ``lr`` (the Optimizer.update convention); pass a step number to apply
    the classic correction here instead."""
    idx, gvals = _rows_of(grad)
    w = weight.data
    g = gvals.astype(jnp.float32) * rescale_grad
    if clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    rows = w[idx].astype(jnp.float32)
    g = g + wd * rows
    m_rows = mean.data[idx].astype(jnp.float32)
    v_rows = var.data[idx].astype(jnp.float32)
    m_new = beta1 * m_rows + (1 - beta1) * g
    v_new = beta2 * v_rows + (1 - beta2) * g * g
    lr_t = lr if t is None else \
        lr * np.sqrt(1 - beta2 ** t) / (1 - beta1 ** t)
    new = rows - lr_t * m_new / (jnp.sqrt(v_new) + epsilon)
    mean._set_data(mean.data.at[idx].set(m_new.astype(mean.dtype)))
    var._set_data(var.data.at[idx].set(v_new.astype(var.dtype)))
    weight._set_data(w.at[idx].set(new.astype(w.dtype)))
    return weight


def sparse_adagrad_update(weight, grad, history, lr, epsilon=1e-7, wd=0.0,
                          rescale_grad=1.0, clip_gradient=-1.0):
    idx, gvals = _rows_of(grad)
    w = weight.data
    g = gvals.astype(jnp.float32) * rescale_grad
    if clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    rows = w[idx].astype(jnp.float32)
    g = g + wd * rows
    h_rows = history.data[idx].astype(jnp.float32) + g * g
    new = rows - lr * g / (jnp.sqrt(h_rows) + epsilon)
    history._set_data(history.data.at[idx].set(
        h_rows.astype(history.dtype)))
    weight._set_data(w.at[idx].set(new.astype(w.dtype)))
    return weight


def sparse_ftrl_update(weight, grad, z, n, lr, lamda1=0.01, beta=1.0,
                       wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    idx, gvals = _rows_of(grad)
    w = weight.data
    g = gvals.astype(jnp.float32) * rescale_grad
    if clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    rows = w[idx].astype(jnp.float32)
    z_rows = z.data[idx].astype(jnp.float32)
    n_rows = n.data[idx].astype(jnp.float32)
    n_new = n_rows + g * g
    sigma = (jnp.sqrt(n_new) - jnp.sqrt(n_rows)) / lr
    z_new = z_rows + g - sigma * rows
    new = jnp.where(
        jnp.abs(z_new) <= lamda1,
        jnp.zeros_like(rows),
        -(z_new - jnp.sign(z_new) * lamda1)
        / ((beta + jnp.sqrt(n_new)) / lr + wd))
    z._set_data(z.data.at[idx].set(z_new.astype(z.dtype)))
    n._set_data(n.data.at[idx].set(n_new.astype(n.dtype)))
    weight._set_data(w.at[idx].set(new.astype(w.dtype)))
    return weight
