"""Training callbacks (ref: python/mxnet/callback.py)."""
from __future__ import annotations

import logging
import time

__all__ = ["Speedometer", "ProgressBar", "do_checkpoint",
           "log_train_metric", "module_checkpoint",
           "LogValidationMetricsCallback"]


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Checkpoint callback for the Module API
    (ref: callback.py — module_checkpoint)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)

    return _callback


def do_checkpoint(prefix, period=1):
    """Save params every `period` epochs (ref: callback.py — do_checkpoint)."""
    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            from .model import save_checkpoint

            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)

    return _callback


def log_train_metric(period, auto_reset=False):
    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()

    return _callback


class Speedometer:
    """Log training speed every `frequent` batches — the number BASELINE
    tracks (ref: callback.py — Speedometer).

    With ``jsonl`` set, every measurement also appends a structured row
    (the BASELINE.md harness requirement):
    ``{config, chips, batch_size, dtype,
       images_or_tokens_per_sec_per_chip, epoch, batch}`` — plus the
    async-health fields ``host_syncs_per_step``, ``launches_per_step``
    (per-window deltas of the telemetry-registry counters, reset-aware)
    and the live ``dispatch_depth`` gauge, so harness rows self-report
    whether the fused/async path actually engaged.
    """

    def __init__(self, batch_size, frequent=50, auto_reset=True,
                 jsonl=None, config=None, dtype=None, chips=1):
        self.batch_size = batch_size
        self.frequent = frequent
        self.init = False
        self.tic = 0
        self.last_count = 0
        self.auto_reset = auto_reset
        self.last_speed = None
        self.jsonl = jsonl
        self.config = config
        self.dtype = dtype
        self.chips = max(1, int(chips))
        self._counter_snap = None  # (host_syncs, launches) at window start

    def _counter_deltas(self):
        """(host_syncs, launches) accumulated since the last window,
        tolerant of a profiler reset mid-window (a reset makes the
        counters smaller than the snapshot — re-baseline at 0 instead of
        reporting a negative rate)."""
        from . import profiler

        cur = (profiler.host_sync_count(), profiler.launch_count())
        prev = self._counter_snap
        self._counter_snap = cur
        if prev is None:
            return None
        return tuple(c - p if c >= p else c for c, p in zip(cur, prev))

    def _emit_jsonl(self, speed, param, deltas):
        import json

        from . import profiler

        row = {
            "config": self.config or "unnamed",
            "chips": self.chips,
            "batch_size": self.batch_size,
            "dtype": self.dtype or "float32",
            "images_or_tokens_per_sec_per_chip": round(speed / self.chips, 2),
            "epoch": getattr(param, "epoch", 0),
            "batch": getattr(param, "nbatch", 0),
            "dispatch_depth": profiler.gauge_value("dispatch_depth"),
        }
        if deltas is not None:
            syncs, launches = deltas
            row["host_syncs_per_step"] = round(syncs / self.frequent, 3)
            row["launches_per_step"] = round(launches / self.frequent, 2)
        with open(self.jsonl, "a") as f:
            f.write(json.dumps(row) + "\n")

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0:
                speed = self.frequent * self.batch_size / \
                    (time.time() - self.tic)
                self.last_speed = speed
                if self.jsonl:
                    self._emit_jsonl(speed, param, self._counter_deltas())
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset()
                    msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec"
                    msg += "\t%s=%f" * len(name_value)
                    logging.info(msg, param.epoch, count, speed,
                                 *sum(name_value, ()))
                else:
                    logging.info(
                        "Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                        param.epoch, count, speed)
                self.tic = time.time()
        else:
            self.init = True
            self.tic = time.time()
            self._counter_deltas()  # baseline the async-health counters


class ProgressBar:
    """ASCII progress bar (ref: callback.py — ProgressBar)."""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = math_ceil(100.0 * count / float(self.total))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        logging.info("[%s] %s%s\r", prog_bar, percents, "%")


def math_ceil(x):
    import math

    return math.ceil(x)


class LogValidationMetricsCallback:
    """Log validation metrics at epoch end
    (ref: callback.py — LogValidationMetricsCallback)."""

    def __call__(self, param):
        if not param.eval_metric:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Epoch[%d] Validation-%s=%f",
                         param.epoch, name, value)
