// Native RecordIO engine (ref: 3rdparty/dmlc-core/src/recordio.cc and
// src/io/iter_image_recordio_2.cc — the reference reads + parses record
// shards in C++ worker threads; this is the TPU build's equivalent,
// exposed to Python over a C ABI consumed via ctypes, see
// mxnet_tpu/native.py).
//
// Byte format (must stay bit-identical with mxnet_tpu/recordio.py):
//   [kMagic u32 LE][cflag:3|len:29 u32 LE][payload][pad to 4B]
//
// Three services:
//   1. mxt_rio_scan    — build an offset/length index of a shard by
//                        magic-walk (no .idx sidecar needed), ~memory-bw.
//   2. mxt_rio_read    — random-access read of one record into caller buf.
//   3. mxt_rio_prefetch_* — N worker threads read+copy records in a
//                        caller-given order into a bounded ring of slots;
//                        the Python iterator pops blocking. This overlaps
//                        file IO with host preprocessing and device steps.
//
// Build: g++ -O2 -shared -fPIC -pthread recordio.cc -o libmxt_recordio.so

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include <atomic>
#include <condition_variable>
#include <mutex>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xCED7230Au;
constexpr uint32_t kLenMask = (1u << 29) - 1;

struct Reader {
  FILE* f = nullptr;
  int64_t size = 0;
};

struct Slot {
  std::vector<uint8_t> data;
  int64_t index = -1;  // position in the requested order
  bool full = false;
};

struct Prefetcher {
  Reader* reader = nullptr;  // not owned
  std::vector<int64_t> offsets;
  std::vector<int64_t> lengths;
  std::vector<Slot> ring;
  std::vector<std::thread> workers;
  std::mutex mu;
  std::condition_variable cv_full, cv_free;
  std::atomic<int64_t> next_fetch{0};  // next order position to claim
  int64_t next_pop = 0;                // next order position to hand out
  std::atomic<bool> stop{false};
  std::atomic<bool> error{false};  // worker IO failure — pop returns -2
  std::string path;  // workers use their own FILE* per thread
};

}  // namespace

extern "C" {

// ---------------------------------------------------------------- reader --
void* mxt_rio_open(const char* path) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  std::fseek(f, 0, SEEK_END);
  auto* r = new Reader();
  r->f = f;
  r->size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  return r;
}

void mxt_rio_close(void* h) {
  auto* r = static_cast<Reader*>(h);
  if (!r) return;
  if (r->f) std::fclose(r->f);
  delete r;
}

int64_t mxt_rio_file_size(void* h) {
  return static_cast<Reader*>(h)->size;
}

// Walk the shard by magic framing; fill offsets/lengths (payload only, no
// header) up to cap entries. Returns the record count found (may exceed
// cap — call again with a larger buffer), or -1 on framing corruption.
int64_t mxt_rio_scan(void* h, int64_t* offsets, int64_t* lengths,
                     int64_t cap) {
  auto* r = static_cast<Reader*>(h);
  std::fseek(r->f, 0, SEEK_SET);
  int64_t pos = 0, n = 0;
  uint32_t header[2];
  while (pos + 8 <= r->size) {
    if (std::fread(header, 4, 2, r->f) != 2) break;
    if (header[0] != kMagic) return -1;
    const int64_t len = header[1] & kLenMask;
    const int64_t padded = (len + 3) & ~int64_t(3);
    if (pos + 8 + len > r->size) return -1;  // truncated record
    if (n < cap) {
      offsets[n] = pos + 8;
      lengths[n] = len;
    }
    ++n;
    pos += 8 + padded;
    std::fseek(r->f, pos, SEEK_SET);
  }
  return n;
}

// Read `length` payload bytes at `offset` into out. Returns bytes read.
int64_t mxt_rio_read(void* h, int64_t offset, int64_t length, uint8_t* out) {
  auto* r = static_cast<Reader*>(h);
  std::fseek(r->f, offset, SEEK_SET);
  return static_cast<int64_t>(std::fread(out, 1, length, r->f));
}

// Sequential read of the next record (framing-aware). Returns payload
// length, 0 at EOF, -1 on corruption or if out_cap is too small (the
// needed size is written to *needed either way).
int64_t mxt_rio_read_next(void* h, uint8_t* out, int64_t out_cap,
                          int64_t* needed) {
  auto* r = static_cast<Reader*>(h);
  uint32_t header[2];
  if (std::fread(header, 4, 2, r->f) != 2) return 0;
  if (header[0] != kMagic) return -1;
  const int64_t len = header[1] & kLenMask;
  if (needed) *needed = len;
  if (len > out_cap) {
    std::fseek(r->f, -8, SEEK_CUR);  // rewind so caller can retry
    return -1;
  }
  if (std::fread(out, 1, len, r->f) != static_cast<size_t>(len)) return -1;
  const int64_t pad = (4 - (len % 4)) % 4;
  if (pad) std::fseek(r->f, pad, SEEK_CUR);
  return len;
}

// ------------------------------------------------------------ prefetcher --
// order[i] indexes into (offsets, lengths); workers fill ring slots in
// claim order, pop hands records out strictly in `order` sequence.
void* mxt_rio_prefetch_start(const char* path, const int64_t* offsets,
                             const int64_t* lengths, const int64_t* order,
                             int64_t n, int32_t num_threads,
                             int32_t capacity) {
  if (num_threads < 1) num_threads = 1;
  if (capacity < num_threads) capacity = num_threads * 2;
  auto* p = new Prefetcher();
  p->path = path;
  p->offsets.resize(n);
  p->lengths.resize(n);
  for (int64_t i = 0; i < n; ++i) {
    p->offsets[i] = offsets[order[i]];
    p->lengths[i] = lengths[order[i]];
  }
  p->ring.resize(capacity);
  for (int t = 0; t < num_threads; ++t) {
    p->workers.emplace_back([p]() {
      // any IO failure flags the whole prefetcher: a silently-exiting
      // worker would leave its claimed slot forever unfilled and the
      // consumer blocked in pop()
      FILE* f = std::fopen(p->path.c_str(), "rb");
      if (!f) {
        {
          // store+notify under the mutex: a consumer between its predicate
          // check and its block would otherwise miss the only wakeup
          std::lock_guard<std::mutex> lk(p->mu);
          p->error.store(true);
        }
        p->cv_full.notify_all();
        return;
      }
      const int64_t n_rec = static_cast<int64_t>(p->offsets.size());
      const int64_t cap = static_cast<int64_t>(p->ring.size());
      while (!p->stop.load(std::memory_order_relaxed)) {
        const int64_t i = p->next_fetch.fetch_add(1);
        if (i >= n_rec) break;
        std::vector<uint8_t> buf(p->lengths[i]);
        std::fseek(f, p->offsets[i], SEEK_SET);
        if (std::fread(buf.data(), 1, buf.size(), f) != buf.size()) {
          {
            std::lock_guard<std::mutex> lk(p->mu);
            p->error.store(true);
          }
          p->cv_full.notify_all();
          break;
        }
        Slot& s = p->ring[i % cap];
        {
          std::unique_lock<std::mutex> lk(p->mu);
          // wait until this slot's previous occupant was consumed
          p->cv_free.wait(lk, [p, &s, i, cap]() {
            return p->stop.load() || (!s.full && p->next_pop > i - cap);
          });
          if (p->stop.load()) break;
          s.data = std::move(buf);
          s.index = i;
          s.full = true;
        }
        p->cv_full.notify_all();
      }
      std::fclose(f);
    });
  }
  return p;
}

// Blocking pop of the next record in order. Returns its length, 0 when the
// sequence is exhausted, -1 if out_cap is too small (*needed set; record
// stays queued), -2 if a worker hit an IO error.
int64_t mxt_rio_prefetch_pop(void* h, uint8_t* out, int64_t out_cap,
                             int64_t* needed) {
  auto* p = static_cast<Prefetcher*>(h);
  const int64_t n_rec = static_cast<int64_t>(p->offsets.size());
  if (p->next_pop >= n_rec) return 0;
  const int64_t cap = static_cast<int64_t>(p->ring.size());
  Slot& s = p->ring[p->next_pop % cap];
  std::unique_lock<std::mutex> lk(p->mu);
  p->cv_full.wait(lk, [p, &s]() {
    return p->stop.load() || p->error.load()
        || (s.full && s.index == p->next_pop);
  });
  if (p->error.load() && !(s.full && s.index == p->next_pop)) return -2;
  if (p->stop.load()) return 0;
  const int64_t len = static_cast<int64_t>(s.data.size());
  if (needed) *needed = len;
  if (len > out_cap) return -1;
  std::memcpy(out, s.data.data(), len);
  s.full = false;
  s.data.clear();
  s.data.shrink_to_fit();
  ++p->next_pop;
  lk.unlock();
  p->cv_free.notify_all();
  return len;
}

void mxt_rio_prefetch_stop(void* h) {
  auto* p = static_cast<Prefetcher*>(h);
  if (!p) return;
  p->stop.store(true);
  p->cv_full.notify_all();
  p->cv_free.notify_all();
  for (auto& t : p->workers) {
    if (t.joinable()) t.join();
  }
  delete p;
}

}  // extern "C"
