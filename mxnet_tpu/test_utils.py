"""Testing SDK (ref: python/mxnet/test_utils.py + tests/python/unittest/common.py).

Rebuilt early per the survey's test strategy: assert_almost_equal with
per-dtype tolerance ladder, numeric gradient checking, cpu↔accelerator
consistency checks (replacing the reference's cpu↔gpu check_consistency),
seeded-repro decorator (@with_seed logging MXNET_TEST_SEED), and random
array helpers.
"""
from __future__ import annotations

import functools
import logging
import os
import random as pyrandom

import numpy as np

from . import random as mx_random
from .context import cpu, current_context
from .ndarray.ndarray import NDArray, array

__all__ = [
    "default_context",
    "assert_almost_equal",
    "almost_equal",
    "same",
    "rand_ndarray",
    "rand_shape_nd",
    "with_seed",
    "check_numeric_gradient",
    "check_consistency",
    "default_rtols",
]

_DEFAULT_RTOL = {
    np.dtype(np.float16): 1e-2,
    np.dtype(np.float32): 1e-4,
    np.dtype(np.float64): 1e-6,
}
_DEFAULT_ATOL = {
    np.dtype(np.float16): 1e-2,
    np.dtype(np.float32): 1e-5,
    np.dtype(np.float64): 1e-7,
}
try:
    import ml_dtypes as _ml

    _DEFAULT_RTOL[np.dtype(_ml.bfloat16)] = 2e-2
    _DEFAULT_ATOL[np.dtype(_ml.bfloat16)] = 2e-2
except Exception:  # pragma: no cover
    pass


def default_rtols(dtype):
    d = np.dtype(dtype)
    return _DEFAULT_RTOL.get(d, 1e-5), _DEFAULT_ATOL.get(d, 1e-6)


def default_context():
    """Context tests run in; override with MXT_TEST_CTX=cpu|tpu
    (ref: test_utils.default_context + MXNET_TEST_DEFAULT_GPU)."""
    name = os.environ.get("MXT_TEST_CTX")
    if name:
        from .context import Context

        return Context(name, 0)
    return current_context()


def _to_np(a):
    if isinstance(a, NDArray):
        return a.asnumpy()
    return np.asarray(a)


def same(a, b):
    return np.array_equal(_to_np(a), _to_np(b))


def almost_equal(a, b, rtol=None, atol=None):
    a, b = _to_np(a), _to_np(b)
    rt, at = default_rtols(a.dtype if a.dtype.kind == "f" else np.float32)
    return np.allclose(a.astype(np.float64), b.astype(np.float64),
                       rtol=rtol if rtol is not None else rt,
                       atol=atol if atol is not None else at)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    a_np, b_np = _to_np(a), _to_np(b)
    dt = a_np.dtype if a_np.dtype.kind == "f" else np.dtype(np.float32)
    rt, at = default_rtols(dt)
    rtol = rtol if rtol is not None else rt
    atol = atol if atol is not None else at
    np.testing.assert_allclose(
        a_np.astype(np.float64), b_np.astype(np.float64),
        rtol=rtol, atol=atol, equal_nan=equal_nan,
        err_msg="%s and %s differ" % names,
    )


def rand_shape_nd(ndim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=ndim))


def rand_ndarray(shape, stype="default", density=None, dtype=None, ctx=None):
    dtype = dtype or np.float32
    arr = np.random.uniform(-1.0, 1.0, size=shape).astype(dtype)
    if stype == "default":
        return array(arr, ctx=ctx)
    if stype == "row_sparse":
        from .sparse import row_sparse_array

        density = 0.5 if density is None else density
        keep = np.random.uniform(size=shape[0]) < density
        arr[~keep] = 0
        return row_sparse_array(array(arr), ctx=ctx)
    raise ValueError("unsupported stype %r" % (stype,))


def with_seed(seed=None):
    """Seed np/python/mx RNGs per test, logging the seed so failures are
    reproducible via MXNET_TEST_SEED (ref: tests/python/unittest/common.py)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            env = os.environ.get("MXNET_TEST_SEED")
            this_seed = (
                seed if seed is not None
                else int(env) if env
                else np.random.randint(0, 2 ** 31)
            )
            np_state = np.random.get_state()
            py_state = pyrandom.getstate()
            np.random.seed(this_seed)
            pyrandom.seed(this_seed)
            mx_random.seed(this_seed)
            try:
                return fn(*args, **kwargs)
            except Exception:
                logging.error(
                    "test %s failed with seed %d: set MXNET_TEST_SEED=%d "
                    "to reproduce", fn.__name__, this_seed, this_seed,
                )
                raise
            finally:
                np.random.set_state(np_state)
                pyrandom.setstate(py_state)

        return wrapper

    return deco


def check_numeric_gradient(fn, inputs, eps=1e-3, rtol=1e-2, atol=1e-3,
                           grad_nodes=None):
    """Compare autograd gradients against central differences
    (ref: test_utils.check_numeric_gradient). ``fn`` maps NDArrays to a
    scalar-or-tensor NDArray; ``inputs`` is a list of NDArrays (float64
    recommended for tight tolerances).
    """
    from . import autograd as ag

    inputs = [x if isinstance(x, NDArray) else array(x) for x in inputs]
    if grad_nodes is None:
        grad_nodes = list(range(len(inputs)))
    for x in inputs:
        x.attach_grad()
    with ag.record():
        out = fn(*inputs)
        out.backward(NDArray(np.ones(out.shape, out.dtype)))
    analytic = [inputs[i].grad.asnumpy() for i in grad_nodes]

    numeric = []
    base_inputs = [x.asnumpy().astype(np.float64) for x in inputs]
    for gi in grad_nodes:
        g = np.zeros_like(base_inputs[gi])
        src = base_inputs[gi]
        for j in range(src.size):
            orig = src.flat[j]
            src.flat[j] = orig + eps
            f_plus = fn(*[array(b.astype(inputs[k].dtype))
                          for k, b in enumerate(base_inputs)]).asnumpy().sum()
            src.flat[j] = orig - eps
            f_minus = fn(*[array(b.astype(inputs[k].dtype))
                           for k, b in enumerate(base_inputs)]).asnumpy().sum()
            src.flat[j] = orig
            g.flat[j] = (f_plus - f_minus) / (2 * eps)
        numeric.append(g)

    for i, (a, n) in enumerate(zip(analytic, numeric)):
        np.testing.assert_allclose(
            a.astype(np.float64), n, rtol=rtol, atol=atol,
            err_msg="gradient mismatch for input %d" % grad_nodes[i],
        )


def check_consistency(fn, inputs, ctx_list=None, rtol=None, atol=None):
    """Run ``fn`` under each context and compare outputs — the reference's
    cpu↔gpu consistency check re-aimed at cpu↔tpu
    (ref: test_utils.check_consistency)."""
    if ctx_list is None:
        ctx_list = [cpu(0), default_context()]
    outs = []
    for ctx in ctx_list:
        moved = [x.as_in_context(ctx) for x in inputs]
        out = fn(*moved)
        outs.append(out.asnumpy())
    for o in outs[1:]:
        assert_almost_equal(outs[0], o, rtol=rtol, atol=atol)
    return outs
