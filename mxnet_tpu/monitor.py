"""Monitor — per-tensor training statistics (ref: python/mxnet/monitor.py
— Monitor installs an output callback on executors; here the tap runs as a
jitted all-intermediates graph pass, see symbol/executor.py
_build_monitor_fn).

Typical use (identical to the reference)::

    mon = mx.monitor.Monitor(100, norm_stat)          # every 100 batches
    mod.install_monitor(mon)                          # or mon.install(exe)
    for batch in data:
        mon.tic()
        mod.forward_backward(batch)
        mon.toc_print()

Host-sync posture: the default statistic (mean absolute value) is
computed ON DEVICE per tap — ``stat_helper`` performs no read — and
``toc()`` materializes every queued device scalar with ONE stacked
transfer per collection batch, routed through
``profiler.record_host_sync``. The reference read each tensor back
eagerly (one blocking round-trip per tapped tensor per batch — hundreds
of syncs per collected step on a deep net); here a collection costs one.
A custom ``stat_func`` may still return host values (numpy) and behaves
exactly as before.
"""
from __future__ import annotations

import logging
import re

import numpy as np

from .ndarray.ndarray import NDArray

__all__ = ["Monitor"]


def _default_stat(arr):
    """mean(|x|) as a DEVICE scalar — no host transfer here; toc()
    batches the reads."""
    import jax.numpy as jnp

    return NDArray(jnp.abs(arr.data).mean())


class Monitor:
    """Collects a statistic of every op output each ``interval`` batches.

    Parameters mirror the reference: ``interval`` (batches between
    collections), ``stat_func`` (NDArray -> scalar/ndarray; default
    mean(|x|), computed on device), ``pattern`` (regex on tap names),
    ``sort`` (sort taps by name in toc output).
    """

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            stat_func = _default_stat
        self.interval = int(interval)
        self.stat_func = stat_func
        self.re_prog = re.compile(pattern)
        self.sort = sort
        self.queue = []
        self.step = 0
        self.activated = False
        self.exes = []

    def install(self, exe, monitor_all=False):
        """Attach to an Executor (ref: Monitor.install →
        executor.set_monitor_callback)."""
        exe.set_monitor_callback(self.stat_helper, monitor_all)
        self.exes.append(exe)

    def stat_helper(self, name, arr):
        if not self.activated or not self.re_prog.match(name):
            return
        self.queue.append((self.step, name, self.stat_func(arr)))

    def tic(self):
        """Start collecting if this step is on the interval."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True

    def _materialize(self, queued):
        """Resolve queued stats to host values with at most ONE device
        read for every deferred scalar the default stat produced (plus
        one per non-scalar custom stat)."""
        from . import profiler

        dev_idx = [i for i, (_, _, s) in enumerate(queued)
                   if isinstance(s, NDArray) and
                   getattr(s.data, "ndim", None) == 0]
        out = list(queued)
        if dev_idx:
            import jax.numpy as jnp

            stacked = jnp.stack([queued[i][2].data for i in dev_idx])
            profiler.record_host_sync()
            host = np.asarray(stacked)  # sync-ok: ONE batched read per tap batch
            for j, i in enumerate(dev_idx):
                step, name, _ = queued[i]
                out[i] = (step, name, host[j])
        for i, (step, name, s) in enumerate(out):
            if isinstance(s, NDArray):  # non-scalar custom stat
                # asnumpy records its own host_sync tick
                out[i] = (step, name, s.asnumpy())  # sync-ok: custom non-scalar stat
        return out

    def toc(self):
        """End collection; returns [(step, tap_name, stat), ...]."""
        if not self.activated:
            self.step += 1
            return []
        self.activated = False
        res = self._materialize(self.queue)
        self.queue = []
        if self.sort:
            res.sort(key=lambda x: x[1])
        self.step += 1
        return res

    def toc_print(self):
        res = self.toc()
        for step, name, stat in res:
            logging.info("Batch: %7d %30s %s", step, name, stat)
        return res
