"""Monitor — per-tensor training statistics (ref: python/mxnet/monitor.py
— Monitor installs an output callback on executors; here the tap runs as a
jitted all-intermediates graph pass, see symbol/executor.py
_build_monitor_fn).

Typical use (identical to the reference)::

    mon = mx.monitor.Monitor(100, norm_stat)          # every 100 batches
    mod.install_monitor(mon)                          # or mon.install(exe)
    for batch in data:
        mon.tic()
        mod.forward_backward(batch)
        mon.toc_print()
"""
from __future__ import annotations

import logging
import re

import numpy as np

__all__ = ["Monitor"]


class Monitor:
    """Collects a statistic of every op output each ``interval`` batches.

    Parameters mirror the reference: ``interval`` (batches between
    collections), ``stat_func`` (NDArray -> scalar/ndarray; default
    mean(|x|)), ``pattern`` (regex on tap names), ``sort`` (sort taps by
    name in toc output).
    """

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def stat_func(arr):
                return np.abs(arr.asnumpy()).mean()
        self.interval = int(interval)
        self.stat_func = stat_func
        self.re_prog = re.compile(pattern)
        self.sort = sort
        self.queue = []
        self.step = 0
        self.activated = False
        self.exes = []

    def install(self, exe, monitor_all=False):
        """Attach to an Executor (ref: Monitor.install →
        executor.set_monitor_callback)."""
        exe.set_monitor_callback(self.stat_helper, monitor_all)
        self.exes.append(exe)

    def stat_helper(self, name, arr):
        if not self.activated or not self.re_prog.match(name):
            return
        self.queue.append((self.step, name, self.stat_func(arr)))

    def tic(self):
        """Start collecting if this step is on the interval."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True

    def toc(self):
        """End collection; returns [(step, tap_name, stat), ...]."""
        if not self.activated:
            self.step += 1
            return []
        self.activated = False
        res = list(self.queue)
        self.queue = []
        if self.sort:
            res.sort(key=lambda x: x[1])
        self.step += 1
        return res

    def toc_print(self):
        res = self.toc()
        for step, name, stat in res:
            logging.info("Batch: %7d %30s %s", step, name, stat)
        return res
