"""mxnet_tpu — a TPU-native framework with the capabilities of MXNet 1.x.

Built on JAX/XLA/Pallas: XLA async dispatch plays the ThreadedEngine, XLA
buffer assignment plays PlanMemory, jit plays CachedOp/GraphExecutor, and
sharding collectives over ICI play KVStore/NCCL. Blueprint: SURVEY.md.
"""
from __future__ import annotations

__version__ = "0.1.0"

import jax as _jax

# MXNet supports float64/int64 tensors as first-class dtypes; JAX gates them
# behind x64. Enable it — all framework defaults remain explicit float32.
_jax.config.update("jax_enable_x64", True)

from . import base
from .base import MXNetError
from . import context
from .context import Context, cpu, gpu, tpu, cpu_pinned, current_context, num_gpus
from . import operator  # registers the 'Custom' op before nd codegen
from . import ndarray
from . import ndarray as nd
from .ndarray.ndarray import NDArray
from . import autograd
from . import random
from . import test_utils
from . import initializer
from . import initializer as init
from . import lr_scheduler
from . import optimizer
from . import optimizer as opt
from . import metric
from . import kvstore
from . import kvstore as kv
from . import kvstore_server
from . import callback
from . import recordio
from . import io
from . import symbol
from . import symbol as sym
from .symbol import Symbol
from . import attribute
from .attribute import AttrScope
from . import name
from . import engine
from . import gluon
from . import module
from . import module as mod
from . import rnn
from .module import Module, BucketingModule, SequentialModule
from . import model
from .model import save_checkpoint, load_checkpoint
from . import parallel
from . import profiler
from . import monitor
from . import image
from . import config
from . import telemetry
telemetry._maybe_autostart()  # MXT_TELEMETRY_PORT exposition endpoint
from . import diagnostics
diagnostics._maybe_autostart()  # flight recorder tap (+ watchdog when
#                                 MXT_WATCHDOG_TIMEOUT is set)
# compile observability (jax.monitoring listeners) + persistent compile
# cache (MXT_COMPILE_CACHE_DIR) + the kernel tuning table
from . import tuning
from . import resilience
from . import membership
from . import embedding
from . import data_plane
from . import visualization
from . import visualization as viz
from . import amp
from . import contrib
from . import runtime
from . import util

__all__ = [
    "nd", "ndarray", "autograd", "random", "context", "Context", "cpu",
    "gpu", "tpu", "NDArray", "MXNetError", "test_utils", "initializer",
    "init", "gluon", "optimizer", "opt", "metric", "kvstore", "kv",
    "lr_scheduler", "callback", "recordio", "io", "parallel", "symbol",
    "sym", "Symbol", "module", "mod", "Module", "BucketingModule", "model",
    "save_checkpoint", "load_checkpoint", "profiler", "monitor",
    "operator", "image", "config", "amp", "contrib", "resilience",
    "membership", "telemetry", "tuning", "diagnostics", "data_plane",
    "SequentialModule", "visualization", "viz", "runtime", "util", "rnn",
    "attribute", "AttrScope", "name", "engine",
]
