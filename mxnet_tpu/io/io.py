"""Data iterators (ref: python/mxnet/io/io.py + src/io/*).

The reference's C++ iterator chain (read → decode → augment → batch →
prefetch, src/io/iter_image_recordio_2.cc) is rebuilt as python threads over
the RecordIO reader with a double-buffered prefetcher — host CPU work that
overlaps with device compute (XLA dispatch is async, so the train loop's
next-batch decode runs while the TPU executes the step). The DataIter/
DataBatch/DataDesc API is preserved for Module binding.
"""
from __future__ import annotations

import queue
import threading
from collections import namedtuple

import numpy as np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from ..ndarray import ndarray as _nd

__all__ = ["LibSVMIter", "DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "CSVIter", "MNISTIter", "ImageRecordIter",
           "ImageRecordUInt8Iter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    """Name/shape/dtype/layout of one input (ref: io.py — DataDesc)."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return "DataDesc[%s,%s,%s,%s]" % (self.name, self.shape, self.dtype,
                                          self.layout)

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    """One batch: data list + label list + padding info
    (ref: io.py — DataBatch)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            data = [data]
        if label is not None and not isinstance(label, (list, tuple)):
            label = [label]
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        data_shapes = [d.shape for d in self.data] if self.data else None
        label_shapes = [l.shape for l in self.label] if self.label else None
        return "{}: data shapes: {} label shapes: {}".format(
            self.__class__.__name__, data_shapes, label_shapes)


class DataIter:
    """Base iterator (ref: io.py — DataIter)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    """Normalize data/label inputs to a list of (name, np.ndarray)
    (ref: io.py — _init_data)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {"_%d_%s" % (i, default_name): d
                    for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError(
            "Input must be NDArray, numpy.ndarray, a list of them or dict "
            "with them as values")
    out = {}
    for k, v in data.items():
        if isinstance(v, NDArray):
            out[k] = v.asnumpy()
        else:
            out[k] = np.asarray(v)
    return list(sorted(out.items()))


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (ref: io.py — NDArrayIter).
    Supports shuffle, discard/pad/roll_over last-batch handling."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False,
                               default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self.idx = np.arange(self.data[0][1].shape[0])
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.num_data = self.idx.shape[0]
        if last_batch_handle == "discard":
            self.num_data = (self.num_data // batch_size) * batch_size
        assert self.num_data >= batch_size, \
            "batch_size needs to be smaller than data size"
        self.cursor = -batch_size
        self._roll_cache = None  # leftover sample idx carried across epochs
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        base = np.arange(self.data[0][1].shape[0])
        if self.shuffle:
            np.random.shuffle(base)
        if self.last_batch_handle == "roll_over" and \
                self._roll_cache is not None:
            # leftover partial batch from last epoch leads this epoch
            # (ref: io.py — NDArrayIter roll_over caches remainder data)
            base = np.concatenate([self._roll_cache, base])
            self._roll_cache = None
        self.idx = base
        self.num_data = self.idx.shape[0]
        if self.last_batch_handle == "discard":
            self.num_data = (self.num_data // self.batch_size) \
                * self.batch_size
        self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        if self.cursor >= self.num_data:
            return False
        if self.last_batch_handle == "roll_over" and \
                self.cursor + self.batch_size > self.num_data:
            # withhold the partial batch: it rolls into the next epoch
            self._roll_cache = self.idx[self.cursor:self.num_data].copy()
            return False
        return True

    def _take(self, arrays):
        start = self.cursor
        end = min(start + self.batch_size, self.num_data)
        out = []
        for _, arr in arrays:
            chunk = arr[self.idx[start:end]]
            if end - start < self.batch_size:  # pad from the beginning
                pad = self.batch_size - (end - start)
                chunk = np.concatenate([chunk, arr[self.idx[:pad]]], axis=0)
            out.append(_nd.array(chunk, dtype=chunk.dtype))
        return out

    def getdata(self):
        return self._take(self.data)

    def getlabel(self):
        return self._take(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0

    def getindex(self):
        end = min(self.cursor + self.batch_size, self.num_data)
        return self.idx[self.cursor:end]


class ResizeIter(DataIter):
    """Resize an iterator to `size` batches per epoch
    (ref: io.py — ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Thread-prefetching wrapper — the dmlc::ThreadedIter double-buffer
    analog (ref: io.py — PrefetchingIter, src/io/iter_prefetcher.h)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        super().__init__()
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0].shape[0]
        self._queues = [queue.Queue(maxsize=2) for _ in iters]
        self._threads = []
        self._stop = threading.Event()
        self._start_threads()
        self.current_batch = [None] * len(iters)

    def _start_threads(self):
        # workers capture THIS generation's stop event + queue: after
        # reset() rebinds self._stop/_queues, a late worker still sees only
        # its own (stopped) generation and exits instead of racing the new
        # epoch's threads on the shared underlying iterator
        def worker(it, q, stop):
            def put(item):
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        return True
                    except queue.Full:
                        continue
                return False

            while not stop.is_set():
                try:
                    batch = it.next()
                except StopIteration:
                    put(None)
                    return
                if not put(batch):
                    return

        self._threads = [
            threading.Thread(target=worker,
                             args=(self.iters[i], self._queues[i],
                                   self._stop),
                             daemon=True)
            for i in range(len(self.iters))]
        for t in self._threads:
            t.start()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([
            [DataDesc(r[x.name], x.shape, x.dtype)
             if isinstance(r, dict) else x
             for x in i.provide_data]
            for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([
            [DataDesc(r[x.name], x.shape, x.dtype)
             if isinstance(r, dict) else x
             for x in i.provide_label]
            for r, i in zip(self.rename_label, self.iters)], [])

    def reset(self):
        self._stop.set()
        for t in self._threads:
            while t.is_alive():
                # drain so a worker blocked mid-put can observe the stop
                for q in self._queues:
                    while not q.empty():
                        q.get_nowait()
                t.join(timeout=0.2)
        for it in self.iters:
            it.reset()
        self._stop = threading.Event()
        self._queues = [queue.Queue(maxsize=2) for _ in self.iters]
        self._start_threads()

    def iter_next(self):
        batches = [q.get() for q in self._queues]
        if any(b is None for b in batches):
            return False
        self.current_batch = batches
        return True

    def next(self):
        if self.iter_next():
            if len(self.current_batch) == 1:
                return self.current_batch[0]
            return DataBatch(
                data=sum([b.data for b in self.current_batch], []),
                label=sum([b.label for b in self.current_batch], []),
                pad=self.current_batch[0].pad)
        raise StopIteration

    def getdata(self):
        return sum([b.data for b in self.current_batch], [])

    def getlabel(self):
        return sum([b.label for b in self.current_batch], [])

    def getpad(self):
        return self.current_batch[0].pad


class CSVIter(NDArrayIter):
    """CSV-backed iterator (ref: src/io/iter_csv.cc — CSVIter). Loads to
    memory (host RAM is ample relative to the reference's streaming C++
    design; revisit if a config needs out-of-core CSV)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
        super().__init__(
            data, label, batch_size=batch_size,
            last_batch_handle="pad" if round_batch else "discard", **kwargs)


class LibSVMIter(DataIter):
    """LibSVM-format iterator yielding CSR data batches
    (ref: src/io/iter_libsvm.cc — LibSVMIter). Each line is
    ``label idx:value idx:value ...``; ``data_shape`` gives the feature
    dimension. Batches carry CSRNDArray data (the sparse subsystem's
    storage class) and dense labels — the Wide&Deep/sparse training
    input path."""

    def __init__(self, data_libsvm, data_shape, batch_size=1,
                 label_libsvm=None, round_batch=True, num_parts=1,
                 part_index=0):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape) if not isinstance(
            data_shape, int) else (data_shape,)
        ncol = int(np.prod(self.data_shape))
        labels, data, indices, indptr = [], [], [], [0]
        with open(data_libsvm) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                for tok in parts[1:]:
                    i, v = tok.split(":")
                    idx = int(i)
                    if idx >= ncol or idx < 0:
                        raise MXNetError(
                            "feature index %d out of range [0, %d) in %s"
                            % (idx, ncol, data_libsvm))
                    indices.append(idx)
                    data.append(float(v))
                indptr.append(len(indices))
        if label_libsvm is not None:
            labels = []
            with open(label_libsvm) as f:
                for line in f:
                    if line.strip():
                        labels.append(float(line.split()[0]))
        if len(labels) != len(indptr) - 1:
            raise MXNetError(
                "label file has %d rows but data file has %d"
                % (len(labels), len(indptr) - 1))
        self._data = np.asarray(data, np.float32)
        self._indices = np.asarray(indices, np.int64)
        self._indptr = np.asarray(indptr, np.int64)
        self._labels = np.asarray(labels, np.float32)
        # distributed sharding (dmlc InputSplit semantics)
        if num_parts > 1:
            keep = np.arange(part_index, len(self._labels), num_parts)
            counts = self._indptr[keep + 1] - self._indptr[keep]
            sel = np.concatenate([
                np.arange(self._indptr[r], self._indptr[r + 1])
                for r in keep]) if len(keep) else np.empty(0, np.int64)
            self._data = self._data[sel.astype(np.int64)]
            self._indices = self._indices[sel.astype(np.int64)]
            self._indptr = np.concatenate([[0], np.cumsum(counts)])
            self._labels = self._labels[keep]
        self._n = len(self._labels)
        self.round_batch = round_batch
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label", (self.batch_size,))]

    def reset(self):
        self._cursor = 0

    def next(self):
        from ..sparse import csr_matrix

        if self._cursor >= self._n:
            raise StopIteration
        end = min(self._cursor + self.batch_size, self._n)
        rows = np.arange(self._cursor, end)
        pad = 0
        if end - self._cursor < self.batch_size:
            if not self.round_batch:
                # reference semantics (and CSVIter above): a short final
                # batch is discarded — provide_data's shape is a contract
                raise StopIteration
            pad = self.batch_size - (end - self._cursor)
            rows = np.concatenate([rows, np.arange(pad) % self._n])
        self._cursor = end
        # slice CSR rows
        counts = self._indptr[rows + 1] - self._indptr[rows]
        new_indptr = np.concatenate([[0], np.cumsum(counts)])
        sel = np.concatenate([
            np.arange(self._indptr[r], self._indptr[r + 1]) for r in rows
        ]) if len(rows) else np.empty(0, np.int64)
        sel = sel.astype(np.int64)
        batch = csr_matrix(
            (self._data[sel], self._indices[sel], new_indptr),
            shape=(len(rows), int(np.prod(self.data_shape))))
        label = NDArray(self._labels[rows])
        return DataBatch(data=[batch], label=[label], pad=pad)


class MNISTIter(NDArrayIter):
    """MNIST idx-format reader (ref: src/io/iter_mnist.cc)."""

    def __init__(self, image, label, batch_size=128, shuffle=True, flat=False,
                 silent=True, seed=None, **kwargs):
        import gzip
        import struct as _struct

        def read_idx(path):
            opener = gzip.open if path.endswith(".gz") else open
            with opener(path, "rb") as f:
                magic = _struct.unpack(">I", f.read(4))[0]
                ndim = magic & 0xFF
                shape = _struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
                return np.frombuffer(f.read(), dtype=np.uint8).reshape(shape)

        images = read_idx(image).astype(np.float32) / 255.0
        labels = read_idx(label).astype(np.float32)
        if flat:
            images = images.reshape(images.shape[0], -1)
        else:
            images = images.reshape(images.shape[0], 1,
                                    images.shape[1], images.shape[2])
        if seed is not None:
            np.random.seed(seed)
        super().__init__(images, labels, batch_size=batch_size,
                         shuffle=shuffle, **kwargs)


class ImageRecordIter(DataIter):
    """RecordIO image iterator with threaded decode + augmentation
    (ref: src/io/iter_image_recordio_2.cc — ImageRecordIOParser2).

    Supported augmentations (the hot subset of image_aug_default.cc):
    resize, rand_crop, rand_mirror, crop to data_shape, mean/std
    normalization. Decode threads pull record offsets from a shared cursor;
    a bounded queue feeds batches.
    """

    def __init__(self, path_imgrec, data_shape, batch_size, path_imgidx=None,
                 shuffle=False, rand_crop=False, rand_mirror=False,
                 mean_r=0.0, mean_g=0.0, mean_b=0.0, std_r=1.0, std_g=1.0,
                 std_b=1.0, resize=-1, label_width=1,
                 preprocess_threads=4, round_batch=True, seed=0,
                 part_index=0, num_parts=1, layout="NCHW", **kwargs):
        super().__init__(batch_size)
        from ..recordio import MXIndexedRecordIO, MXRecordIO, unpack_img

        self._unpack_img = unpack_img
        # TPU extension beyond the reference: layout="NHWC" emits
        # channels-last batches directly — the worker's slot write
        # becomes a contiguous memcpy (no CHW strided transpose) and an
        # NHWC model (nn.layout_scope) consumes it without a device-side
        # transpose. data_shape stays (C, H, W) in BOTH layouts, like
        # the reference API.
        if layout not in ("NCHW", "NHWC"):
            raise MXNetError("ImageRecordIter layout must be NCHW or "
                             "NHWC, got %r" % (layout,))
        self.layout = layout
        # dtype="uint8" → reference ImageRecordUInt8Iter semantics: raw
        # pixel batches (4× fewer host→device bytes; the model casts and
        # normalizes on device where it fuses into the first conv)
        self.dtype = np.dtype(kwargs.pop("dtype", "float32"))
        if self.dtype not in (np.dtype("float32"), np.dtype("uint8")):
            raise MXNetError("ImageRecordIter dtype must be float32 or "
                             "uint8, got %s" % self.dtype)
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        self.rand_crop = rand_crop
        self.rand_mirror = rand_mirror
        self.resize = resize
        self.mean = np.array([mean_r, mean_g, mean_b], dtype=np.float32)
        self.std = np.array([std_r, std_g, std_b], dtype=np.float32)
        # identity normalization is the common case — skip the two
        # full-crop elementwise passes entirely then
        self._normalize = bool(np.any(self.mean != 0.0)
                               or np.any(self.std != 1.0))
        self._inv_std = (1.0 / self.std).astype(np.float32)
        if self.dtype == np.uint8 and self._normalize:
            raise MXNetError(
                "dtype='uint8' emits raw pixels; normalize on device "
                "instead of passing mean_*/std_* (ref: "
                "ImageRecordUInt8Iter has no mean/std params)")
        self.round_batch = round_batch
        self.preprocess_threads = max(1, preprocess_threads)
        self._rng = np.random.RandomState(seed)

        # index all record offsets once. Fast path: the native engine
        # (src/recordio.cc) magic-scans the shard in C++ and its (payload
        # offset, length) index lets decode workers read records natively,
        # GIL-free — the reference's C++ parser role. Fallback: .idx
        # sidecar or a pure-Python scan.
        self._native = None
        self._payload = None  # (offsets, lengths) parallel to _offsets
        try:
            from .. import native as _native_mod

            if _native_mod.available():
                nat = _native_mod.NativeRecordReader(path_imgrec)
                offs, lens = nat.scan()
                nat.close()
                self._native = _native_mod
                self._offsets = list(
                    offs - _native_mod._HEADER_BYTES)  # record starts
                self._payload = (offs, lens)
        except Exception:  # noqa: BLE001 — fall back to Python paths
            self._native = None
            self._payload = None
        if self._native is None:
            if path_imgidx:
                rec = MXIndexedRecordIO(path_imgidx, path_imgrec, "r")
                self._offsets = [rec.idx[k] for k in rec.keys]
                rec.close()
            else:
                rec = MXRecordIO(path_imgrec, "r")
                self._offsets = []
                while True:
                    pos = rec.tell()
                    if rec.read() is None:
                        break
                    self._offsets.append(pos)
                rec.close()
        elif path_imgidx:
            # honor the sidecar's key order/subset when it exists; a stale
            # .idx drops us back to the Python reader
            rec = MXIndexedRecordIO(path_imgidx, path_imgrec, "r")
            wanted = [rec.idx[k] for k in rec.keys]
            rec.close()
            self._offsets = wanted
            self._payload = self._native.select_payload_by_starts(
                self._payload[0], self._payload[1], wanted)
            if self._payload is None:
                self._native = None
        # distributed sharding (part_index/num_parts — dmlc InputSplit)
        self._offsets = self._offsets[part_index::num_parts]
        if self._payload is not None:
            self._payload = (self._payload[0][part_index::num_parts],
                             self._payload[1][part_index::num_parts])
        self.path_imgrec = path_imgrec
        self.reset()

    @property
    def provide_data(self):
        c, h, w = self.data_shape
        shape = (self.batch_size, c, h, w) if self.layout == "NCHW" \
            else (self.batch_size, h, w, c)
        return [DataDesc("data", shape, dtype=self.dtype,
                         layout=self.layout)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 \
            else (self.batch_size, self.label_width)
        return [DataDesc("softmax_label", shape)]

    def reset(self):
        self._order = np.arange(len(self._offsets))
        if self.shuffle:
            self._rng.shuffle(self._order)
        self._cursor = 0
        # epoch-scoped native read-ahead: C++ workers pull records ahead
        # of the decode threads in shuffled order, overlapping file IO
        # with augmentation and the device step (the reference's
        # ThreadedIter/prefetcher role, src/io/iter_prefetcher.h)
        if getattr(self, "_prefetcher", None) is not None:
            self._prefetcher.stop()
        self._prefetcher = None
        if self._native is not None and len(self._order):
            try:
                self._prefetcher = self._native.NativePrefetcher(
                    self.path_imgrec, self._payload[0], self._payload[1],
                    self._order,
                    num_threads=max(2, self.preprocess_threads // 2),
                    capacity=4 * self.batch_size)
            except Exception:  # noqa: BLE001 — per-batch reads still work
                self._prefetcher = None

    def _decode_one(self, raw, rng):
        # stays uint8 through resize/crop/mirror (4-6x less data touched
        # than converting the full frame to f32 first); returns the HWC
        # crop as-is — _store does layout + f32 cast + normalize in one
        # numpy pass straight into the preallocated batch buffer
        header, img = self._unpack_img(raw)
        if self.resize > 0:
            img = _resize_short(img, self.resize)
        c, h, w = self.data_shape
        img = _crop(img, h, w,
                    rand=self.rand_crop, rng=rng)
        if self.rand_mirror and rng.rand() < 0.5:
            img = img[:, ::-1, :]
        label = header.label
        if isinstance(label, np.ndarray) and self.label_width == 1:
            label = float(label[0])
        return img, label  # HWC; _store handles layout/cast/normalize

    def _store(self, slot, img):
        """Write an HWC image into the batch slot (dtype follows
        self.dtype): the assignment does layout-copy AND any uint8→f32
        cast in one numpy pass (for NHWC it is a plain contiguous
        memcpy); the (rare) non-identity normalization then runs in
        place on the slot — f32 mode only, the uint8 ctor rejects it."""
        if self.layout == "NCHW":
            slot[...] = np.transpose(img, (2, 0, 1))
            if self._normalize:
                slot -= self.mean.reshape(-1, 1, 1)
                slot *= self._inv_std.reshape(-1, 1, 1)
        else:
            slot[...] = img
            if self._normalize:
                slot -= self.mean
                slot *= self._inv_std

    def next(self):
        from ..recordio import MXRecordIO

        n = len(self._offsets)
        if self._cursor >= n:
            raise StopIteration
        end = self._cursor + self.batch_size
        idxs = list(self._order[self._cursor:min(end, n)])
        pad = 0
        if end > n:
            if self.round_batch:
                # wrap to the start; pad reports the duplicated count
                pad = end - n
                idxs += list(self._order[:pad])
            # round_batch=False: emit the shorter final batch as-is
        self._cursor = end

        n_main = len(idxs) - pad  # in-order part; `pad` wraps to the start
        # raw record bytes: pop the epoch prefetcher for the in-order part
        # (already read ahead by the C++ ring); wrapped duplicates (pad)
        # and the non-native path read directly
        raws = [None] * len(idxs)
        if self._prefetcher is not None:
            for j in range(n_main):
                raws[j] = self._prefetcher.pop()

        # preallocated batch buffer (layout/dtype per provide_data):
        # workers _store their HWC crops straight into it (parallel
        # copies, no np.stack pass afterwards)
        data = np.empty((len(idxs),) + self.provide_data[0].shape[1:],
                        self.dtype)
        labels = [None] * len(idxs)
        # per-thread RNG (np.random.RandomState is not thread-safe), seeded
        # from the iterator's stream so a fixed seed stays deterministic
        rng_seeds = self._rng.randint(0, 2 ** 31 - 1,
                                      size=self.preprocess_threads)

        errors = []

        def worker(tid):
            # one file handle per thread (neither the Python reader nor the
            # native FILE* is safe to share across seeking threads)
            nat = reader = None
            if self._native is not None:
                offs, lens = self._payload

                def fetch(i):
                    nonlocal nat
                    if nat is None:
                        nat = self._native.NativeRecordReader(
                            self.path_imgrec)
                    return nat.read_at(int(offs[i]), int(lens[i]))
            else:
                def fetch(i):
                    nonlocal reader
                    if reader is None:
                        reader = MXRecordIO(self.path_imgrec, "r")
                    reader.handle.seek(self._offsets[i])
                    return reader.read()
            rng = np.random.RandomState(rng_seeds[tid])
            try:
                for j in range(tid, len(idxs), self.preprocess_threads):
                    raw = raws[j] if raws[j] is not None \
                        else fetch(idxs[j])
                    img, labels[j] = self._decode_one(raw, rng)
                    self._store(data[j], img)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                errors.append(e)
            finally:
                if nat is not None:
                    nat.close()
                if reader is not None:
                    reader.close()

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(self.preprocess_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            # surface the decode error on the caller's thread — a dead
            # worker otherwise shows up as an opaque None in np.stack
            raise errors[0]

        label = np.asarray(labels, dtype=np.float32)
        return DataBatch(data=[_nd.array(data)], label=[_nd.array(label)],
                         pad=pad)


def _resize_short(img, size):
    """Resize so the short edge is `size` (PIL bilinear)."""
    from PIL import Image

    h, w = img.shape[:2]
    if h < w:
        new_h, new_w = size, int(w * size / h)
    else:
        new_h, new_w = int(h * size / w), size
    pil = Image.fromarray(img if img.dtype == np.uint8
                          else img.astype(np.uint8))
    return np.asarray(pil.resize((new_w, new_h), Image.BILINEAR))


def _crop(img, th, tw, rand=False, rng=None):
    h, w = img.shape[:2]
    if h < th or w < tw:  # upscale if too small
        from PIL import Image

        scale = max(th / h, tw / w)
        pil = Image.fromarray(img if img.dtype == np.uint8
                              else img.astype(np.uint8))
        img = np.asarray(
            pil.resize((int(np.ceil(w * scale)), int(np.ceil(h * scale))),
                       Image.BILINEAR))
        h, w = img.shape[:2]
    if rand:
        y = rng.randint(0, h - th + 1)
        x = rng.randint(0, w - tw + 1)
    else:
        y = (h - th) // 2
        x = (w - tw) // 2
    return img[y:y + th, x:x + tw, :]


class ImageRecordUInt8Iter(ImageRecordIter):
    """Raw-pixel record iterator (ref: src/io/iter_image_recordio_2.cc —
    ImageRecordUInt8Iter registration): uint8 batches, no mean/std —
    4× fewer host→device bytes; cast+normalize on device, where XLA
    fuses them into the first conv."""

    def __init__(self, *args, **kwargs):
        if np.dtype(kwargs.setdefault("dtype", "uint8")) != np.uint8:
            raise MXNetError(
                "ImageRecordUInt8Iter emits uint8 by definition; use "
                "ImageRecordIter for dtype=%r" % (kwargs["dtype"],))
        super().__init__(*args, **kwargs)


class ImageDetRecordIter(ImageRecordIter):
    """Detection RecordIO iterator (ref: src/io/iter_image_det_recordio.cc
    — ImageDetRecordIter). Records carry im2rec --pack-label detection
    labels: a flat [header_width, object_width, extra..., then
    object_width floats per box (id, xmin, ymin, xmax, ymax)] vector in
    normalized coordinates.

    Emits label (batch, label_pad_width) padded with ``label_pad_value``
    (the reference's contract — MultiBoxTarget consumers reshape to
    (B, N, object_width) after stripping the header). Box-invariant
    augmentations only on this path: resize (normalized coords) and
    mirror WITH x-coordinate flip; the richer det augmenter zoo lives in
    mx.image.ImageDetIter/CreateDetAugmenter.
    """

    def __init__(self, path_imgrec, data_shape, batch_size,
                 label_pad_width=0, label_pad_value=-1.0, **kwargs):
        kwargs.setdefault("label_width", -1)
        if kwargs.pop("rand_crop", False):
            raise MXNetError(
                "ImageDetRecordIter does not support rand_crop (a crop "
                "would shift normalized box coords); use "
                "mx.image.ImageDetIter's detection-aware croppers")
        super().__init__(path_imgrec, data_shape, batch_size, **kwargs)
        self.label_pad_width = int(label_pad_width)
        self.label_pad_value = float(label_pad_value)
        if not self.label_pad_width:
            from ..recordio import MXRecordIO, unpack

            # derive from the largest record label: header-only parse
            # (unpack skips the image payload — no decode)
            widest = 0
            rec = MXRecordIO(path_imgrec, "r")
            try:
                while True:
                    raw = rec.read()
                    if raw is None:
                        break
                    header, _ = unpack(raw)
                    lab = np.atleast_1d(np.asarray(header.label))
                    widest = max(widest, lab.size)
            finally:
                rec.close()
            self.label_pad_width = widest

    @property
    def provide_label(self):
        return [DataDesc("label",
                         (self.batch_size, self.label_pad_width))]

    def _decode_one(self, raw, rng):
        from PIL import Image

        header, img = self._unpack_img(raw)
        c, h, w = self.data_shape
        # warp-resize straight to (w, h): the ONLY reshaping that keeps
        # normalized box coords valid (any crop would shift them)
        img = np.asarray(
            Image.fromarray(img if img.dtype == np.uint8
                            else img.astype(np.uint8)).resize(
                (w, h), Image.BILINEAR))
        lab = np.array(np.atleast_1d(np.asarray(header.label)),
                       dtype=np.float32)
        if self.rand_mirror and rng.rand() < 0.5:
            img = img[:, ::-1, :]
            # flip normalized x coords: object rows follow the
            # [hdr_w, obj_w, ...extra] header
            hdr_w = int(lab[0]) if lab.size >= 2 else 2
            obj_w = int(lab[1]) if lab.size >= 2 else 5
            body = lab[hdr_w:]
            n_obj = body.size // obj_w if obj_w else 0
            for i in range(n_obj):
                base = hdr_w + i * obj_w
                xmin, xmax = lab[base + 1], lab[base + 3]
                lab[base + 1], lab[base + 3] = 1.0 - xmax, 1.0 - xmin
        # HWC out; _store handles layout/cast/normalize
        if lab.size < self.label_pad_width:
            lab = np.concatenate([
                lab, np.full(self.label_pad_width - lab.size,
                             self.label_pad_value, np.float32)])
        elif lab.size > self.label_pad_width:
            raise MXNetError(
                "record label width %d exceeds label_pad_width %d"
                % (lab.size, self.label_pad_width))
        return img, lab
