"""Data iterators (ref: python/mxnet/io/__init__.py)."""
from .io import (
    DataDesc, DataBatch, DataIter, NDArrayIter, ResizeIter, PrefetchingIter,
    CSVIter, MNISTIter, ImageRecordIter, ImageRecordUInt8Iter,
    ImageDetRecordIter,
    LibSVMIter,
)

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "CSVIter", "MNISTIter", "ImageRecordIter",
           "ImageRecordUInt8Iter", "ImageDetRecordIter",
           "LibSVMIter"]
