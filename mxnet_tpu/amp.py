"""AMP — automatic mixed precision with autocast lists + dynamic loss
scaling (ref: python/mxnet/contrib/amp/{amp.py,lists/symbol.py}).

``init()`` patches the op registry the way the reference monkey-patches
the generated nd/sym namespaces: MXU-bound ops (TARGET_DTYPE_OPS) cast
their float inputs to the target dtype (bfloat16 on TPU — no loss scaling
*needed* for range, unlike fp16, but the dynamic scaler is still provided
for fp16 parity and for tiny-gradient regimes); numerically sensitive ops
(FP32_OPS) compute in float32.

``scale_loss``/``unscale`` + ``LossScaler`` implement the reference's
dynamic scaling: scale doubles every ``scale_window`` clean steps, halves
on overflow, and the overflow step is skipped by ``Trainer``.
"""
from __future__ import annotations

import contextlib

import numpy as np

from .base import MXNetError, get_dtype
from .ops import registry as _registry

__all__ = ["init", "init_trainer", "scale_loss", "unscale", "LossScaler",
           "TARGET_DTYPE_OPS", "FP32_OPS"]

# MXU-bound: run in the low-precision target (ref: lists/symbol.py
# TARGET_DTYPE_OPS — conv/FC/dot family)
TARGET_DTYPE_OPS = [
    "Convolution", "Deconvolution", "FullyConnected", "dot", "batch_dot",
    "flash_attention", "RNN",
]

# numerically sensitive: force float32 compute (ref: FP32_FUNCS)
FP32_OPS = [
    "softmax", "log_softmax", "softmin", "SoftmaxOutput",
    "softmax_cross_entropy", "BatchNorm", "LayerNorm", "InstanceNorm",
    "GroupNorm", "L2Normalization", "LRN", "norm", "mean", "sum", "prod",
    "exp", "expm1", "log", "log1p", "log2", "log10", "logsumexp",
    "erfinv", "gamma", "gammaln",
]

_state = {"initialized": False, "target": None, "originals": {}}


def _wrap_target(op, target):
    orig = op.fn

    def cast_fn(*args, **kwargs):
        import jax.numpy as jnp
        cast = tuple(
            a.astype(target) if hasattr(a, "dtype")
            and jnp.issubdtype(a.dtype, jnp.floating)
            and a.dtype != target else a
            for a in args)
        return orig(*cast, **kwargs)

    cast_fn.__name__ = getattr(orig, "__name__", op.name)
    return cast_fn


def _wrap_fp32(op):
    orig = op.fn

    def f32_fn(*args, **kwargs):
        import jax.numpy as jnp
        in_dt = next((a.dtype for a in args if hasattr(a, "dtype")
                      and jnp.issubdtype(a.dtype, jnp.floating)), None)
        cast = tuple(
            a.astype(jnp.float32) if hasattr(a, "dtype")
            and jnp.issubdtype(a.dtype, jnp.floating)
            and a.dtype != jnp.float32 else a
            for a in args)
        out = orig(*cast, **kwargs)
        if in_dt is not None and in_dt != jnp.float32:
            if isinstance(out, tuple):
                out = tuple(o.astype(in_dt) for o in out)
            else:
                out = out.astype(in_dt)
        return out

    f32_fn.__name__ = getattr(orig, "__name__", op.name)
    return f32_fn


def init(target_dtype="bfloat16"):
    """Patch the registry for autocasting (ref: amp.init — which patches
    the generated op modules). Idempotent; ``target_dtype`` is 'bfloat16'
    (TPU-native) or 'float16'."""
    if _state["initialized"]:
        if np.dtype(get_dtype(target_dtype)) != np.dtype(_state["target"]):
            raise MXNetError("amp already initialized with %s"
                             % _state["target"])
        return
    target = get_dtype(target_dtype)
    for name in TARGET_DTYPE_OPS:
        op = _registry.get_op(name)
        _state["originals"][name] = op.fn
        op.fn = _wrap_target(op, target)
    for name in FP32_OPS:
        op = _registry.get_op(name)
        _state["originals"][name] = op.fn
        op.fn = _wrap_fp32(op)
    _state["initialized"] = True
    _state["target"] = np.dtype(target)


def _deinit_for_tests():
    """Undo init() — test helper, not reference API."""
    for name, fn in _state["originals"].items():
        _registry.get_op(name).fn = fn
    _state.update(initialized=False, target=None, originals={})


class LossScaler:
    """Dynamic loss scale (ref: amp/loss_scaler.py — LossScaler)."""

    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000):
        self.loss_scale = float(init_scale)
        self._factor = scale_factor
        self._window = scale_window
        self._unskipped = 0

    def has_overflow(self, params):
        """True if any gradient is non-finite. ONE fused device check +
        one host read for the whole gradient set (ref: all_finite.cc —
        MultiAllFinite; a per-parameter loop would pay a launch and a
        full tunnel round-trip per parameter)."""
        from .ndarray.ndarray import NDArray

        arrs = []
        for p in params:
            g = p.grad()
            if hasattr(g, "_values"):  # row_sparse
                arrs.append(NDArray(g._values.data
                                    if isinstance(g._values, NDArray)
                                    else g._values))
            else:
                arrs.append(g if isinstance(g, NDArray) else NDArray(g))
        if not arrs:
            return False
        from . import nd

        flag = nd.multi_all_finite(*arrs, num_arrays=len(arrs))
        return float(flag.asnumpy()[0]) == 0.0

    def update_scale(self, overflow):
        if overflow:
            self.loss_scale = max(1.0, self.loss_scale / self._factor)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._window:
                self.loss_scale *= self._factor
                self._unskipped = 0

    # -- persistence (resilience.CheckpointManager rides this so a
    # resumed run re-enters with the backed-off scale, not the init one)
    def state_dict(self):
        return {"loss_scale": float(self.loss_scale),
                "unskipped": int(self._unskipped)}

    def load_state_dict(self, state):
        self.loss_scale = float(state["loss_scale"])
        self._unskipped = int(state.get("unskipped", 0))


def init_trainer(trainer):
    """Attach dynamic loss scaling to a Trainer (ref: amp.init_trainer):
    after this, ``trainer.step`` unscales gradients and SKIPS the update
    when they overflowed, then updates the scale.

    A ``CachedTrainStep`` built from this trainer with
    ``MXT_SKIP_NONFINITE=1`` drives the same scaler from its in-program
    overflow flag (one host read per step, no extra launches) — see
    resilience.py."""
    if getattr(trainer, "_amp_scaler", None) is not None:
        return
    scaler = LossScaler()
    trainer._amp_scaler = scaler
    orig_step = trainer.step

    def step(batch_size, ignore_stale_grad=False):
        params = [p for p in trainer._params if p.grad_req != "null"]
        overflow = scaler.has_overflow(params)
        if not overflow:
            scale = scaler.loss_scale
            if scale != 1.0:
                for p in params:
                    g = p.data()._grad
                    if g is not None:
                        p.data()._grad = g / scale
            orig_step(batch_size, ignore_stale_grad=ignore_stale_grad)
        scaler.update_scale(overflow)

    trainer.step = step


@contextlib.contextmanager
def scale_loss(loss, trainer):
    """``with amp.scale_loss(loss, trainer) as l: l.backward()`` — the
    reference API; multiplies the loss by the current scale (trainer.step
    then unscales the gradients)."""
    if getattr(trainer, "_amp_scaler", None) is None:
        init_trainer(trainer)
    scale = trainer._amp_scaler.loss_scale
    if isinstance(loss, (list, tuple)):
        yield [l * scale for l in loss]
    else:
        yield loss * scale
