"""Checkpoint helpers (ref: python/mxnet/model.py — save_checkpoint /
load_checkpoint; format: prefix-symbol.json + prefix-%04d.params with
``arg:``/``aux:`` key prefixes, identical to the reference on-disk layout).

These cover the symbolic graph + parameters ONLY — no optimizer state,
cursor, loss-scale, or PRNG, and the write is not crash-atomic. For
full-training-state checkpoints with CRC-verified atomic publication and
auto-resume (the Gluon path), use ``resilience.CheckpointManager``; the
mapping is documented in MIGRATION.md.
"""
from __future__ import annotations

from collections import namedtuple

from .base import MXNetError
from .ndarray import ndarray as _nd

__all__ = ["save_checkpoint", "load_checkpoint", "BatchEndParam"]

# callback payload for batch_end/score_end callbacks
# (ref: python/mxnet/model.py — BatchEndParam namedtuple)
BatchEndParam = namedtuple("BatchEndParam",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def pack_param_dict(arg_params, aux_params):
    """arg:/aux:-prefixed flat dict — THE on-disk param layout
    (shared by checkpoints and Module.save_params)."""
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    return save_dict


def unpack_param_dict(save_dict, strict=False):
    """Inverse of pack_param_dict. strict raises on unprefixed keys;
    otherwise they are skipped (checkpoint-reader leniency)."""
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        elif strict:
            raise MXNetError(
                "invalid param dict: key %r has no arg:/aux: prefix"
                % (k,))
    return arg_params, aux_params


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    del remove_amp_cast
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    param_name = "%s-%04d.params" % (prefix, epoch)
    _nd.save(param_name, pack_param_dict(arg_params, aux_params))


def load_checkpoint(prefix, epoch):
    from . import symbol as sym_mod

    symbol = sym_mod.load("%s-symbol.json" % prefix)
    save_dict = _nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params, aux_params = unpack_param_dict(save_dict)
    return symbol, arg_params, aux_params


class FeedForward:
    """Legacy pre-Module trainer (ref: python/mxnet/model.py —
    FeedForward; deprecated upstream in favor of Module, kept for API
    parity). Thin adapter over Module: fit/predict/score/save/load with
    the classic constructor surface."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        from . import initializer as init_mod

        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.optimizer = optimizer
        self.initializer = initializer or init_mod.Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self.kwargs = dict(kwargs)
        self._module = None

    def _label_names(self):
        candidates = [n for n in self.symbol.list_arguments()
                      if n.endswith("label")]
        return tuple(candidates) or ("softmax_label",)

    def _make_module(self, data=None):
        """ref: model.py — input names come from the iterator's
        provide_data/provide_label when available, not a hard-coded
        'data'."""
        from .module.module import Module

        if data is not None and getattr(data, "provide_data", None):
            data_names = tuple(d.name for d in data.provide_data)
        else:
            data_names = ("data",)
        if data is not None and getattr(data, "provide_label", None):
            label_names = tuple(d.name for d in data.provide_label)
        else:
            label_names = self._label_names()
        return Module(self.symbol, data_names=data_names,
                      label_names=label_names, context=self.ctx)

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", logger=None, work_load_list=None,
            monitor=None, eval_end_callback=None,
            eval_batch_end_callback=None):
        del logger, work_load_list
        assert self.num_epoch is not None, "num_epoch must be set"
        data = self._as_iter(X, y)
        self._module = self._make_module(data)
        self._module.fit(
            data, eval_data=eval_data, eval_metric=eval_metric,
            epoch_end_callback=epoch_end_callback,
            batch_end_callback=batch_end_callback, kvstore=kvstore,
            optimizer=self.optimizer, optimizer_params=self.kwargs,
            initializer=self.initializer, arg_params=self.arg_params,
            aux_params=self.aux_params, begin_epoch=self.begin_epoch,
            num_epoch=self.num_epoch, monitor=monitor,
            eval_end_callback=eval_end_callback,
            eval_batch_end_callback=eval_batch_end_callback)
        self.arg_params, self.aux_params = self._module.get_params()
        return self

    def _as_iter(self, X, y=None):
        from .io.io import DataIter, NDArrayIter

        if isinstance(X, DataIter):
            return X
        label_name = self._label_names()[0]
        # ref: model.py — _init_iter clamps batch_size to the data size
        bsz = min(self.numpy_batch_size, len(X))
        return NDArrayIter(X, y, batch_size=bsz, label_name=label_name)

    def _bind_for_inference(self, data):
        """Lazy module construction for predict/score (ref: model.py —
        _init_predictor)."""
        if self._module is not None:
            return
        if self.arg_params is None:
            raise MXNetError(
                "FeedForward has no parameters — call fit() first or "
                "construct with arg_params/load()")
        self._module = self._make_module(data)
        self._module.bind(data_shapes=data.provide_data,
                          label_shapes=data.provide_label,
                          for_training=False)
        self._module.set_params(self.arg_params, self.aux_params or {})

    def predict(self, X, num_batch=None, return_data=False,
                reset=True):
        del return_data
        import numpy as _np

        data = self._as_iter(X)
        self._bind_for_inference(data)
        if reset:
            data.reset()
        outs = None
        for i, batch in enumerate(data):
            if num_batch is not None and i >= num_batch:
                break
            self._module.forward(batch, is_train=False)
            batch_outs = [o.asnumpy() for o in self._module.get_outputs()]
            pad = batch.pad or 0
            if pad:  # last batch wraps around — trim the duplicates
                batch_outs = [o[:o.shape[0] - pad] for o in batch_outs]
            if outs is None:
                outs = [[] for _ in batch_outs]
            for acc, o in zip(outs, batch_outs):
                acc.append(o)
        if outs is None:
            raise MXNetError(
                "predict() saw no batches (exhausted iterator or "
                "num_batch=0)")
        merged = [_np.concatenate(acc, axis=0) for acc in outs]
        # ref: model.py — a single-output net returns the array itself
        return merged[0] if len(merged) == 1 else merged

    def score(self, X, eval_metric="acc", num_batch=None,
              batch_end_callback=None, reset=True):
        del batch_end_callback
        from . import metric as metric_mod

        data = self._as_iter(X)
        if reset:
            data.reset()
        metric = eval_metric if isinstance(
            eval_metric, metric_mod.EvalMetric) \
            else metric_mod.create(eval_metric)
        self._bind_for_inference(data)
        metric.reset()
        for i, batch in enumerate(data):
            if num_batch is not None and i >= num_batch:
                break
            self._module.forward(batch, is_train=False)
            self._module.update_metric(metric, batch.label)
        return metric.get()[1]

    def save(self, prefix, epoch=None):
        """ref: model.py — FeedForward.save (checkpoint format shared
        with Module)."""
        if epoch is None:
            epoch = self.num_epoch or 0
        save_checkpoint(prefix, epoch, self.symbol,
                        self.arg_params or {}, self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch,
                           **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None,
               epoch_size=None, optimizer="sgd", initializer=None,
               eval_data=None, eval_metric="acc",
               epoch_end_callback=None, batch_end_callback=None,
               kvstore="local", logger=None, work_load_list=None,
               eval_end_callback=None, eval_batch_end_callback=None,
               **kwargs):
        """ref: model.py — FeedForward.create (construct + fit)."""
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            epoch_size=epoch_size, optimizer=optimizer,
                            initializer=initializer, **kwargs)
        return model.fit(
            X, y, eval_data=eval_data, eval_metric=eval_metric,
            epoch_end_callback=epoch_end_callback,
            batch_end_callback=batch_end_callback, kvstore=kvstore,
            logger=logger, work_load_list=work_load_list,
            eval_end_callback=eval_end_callback,
            eval_batch_end_callback=eval_batch_end_callback)
