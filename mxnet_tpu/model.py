"""Checkpoint helpers (ref: python/mxnet/model.py — save_checkpoint /
load_checkpoint; format: prefix-symbol.json + prefix-%04d.params with
``arg:``/``aux:`` key prefixes, identical to the reference on-disk layout).
"""
from __future__ import annotations

from collections import namedtuple

from .ndarray import ndarray as _nd

__all__ = ["save_checkpoint", "load_checkpoint", "BatchEndParam"]

# callback payload for batch_end/score_end callbacks
# (ref: python/mxnet/model.py — BatchEndParam namedtuple)
BatchEndParam = namedtuple("BatchEndParam",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    del remove_amp_cast
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    _nd.save(param_name, save_dict)


def load_checkpoint(prefix, epoch):
    from . import symbol as sym_mod

    symbol = sym_mod.load("%s-symbol.json" % prefix)
    save_dict = _nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return symbol, arg_params, aux_params
